"""Out-of-core pair spill store: segment-committed, manifest-bound, resumable.

The billion-row write path cannot hold the candidate-pair set in host RAM,
and — at hours of ingest wall — cannot afford to lose a build to a
preemption either (re-ingesting the corpus is the real cost of a crash;
the progressive-ER principle the EM checkpoints already apply to training,
arXiv:1905.06167's framing of blocking as THE scalability bottleneck).
This module is the storage layer under the sharded emission driver
(blocking_device.emit_pairs_sharded) and the out-of-core index build:

  * pairs append to two flat binary files (``idx_l.bin`` / ``idx_r.bin``,
    the ``_PairSink`` memmap format promoted from overflow fallback to
    first-class artifact), in fixed (rule, shard, sequence) segment order;
  * every segment commits through ``pair_manifest.json`` — written with the
    SAME atomic machinery as the EM checkpoints (temp file + fsync +
    os.replace + directory fsync, resilience/checkpoint.py), recording the
    segment's pair count, byte offset, rule/shard identity, a sha256 over
    its bytes and the device-side transfer digest where the emission kernel
    computed one;
  * a killed build resumes from the last committed segment: ``attach``
    truncates any torn (uncommitted) tail off the bins and the driver skips
    committed segments, so the byte stream a resumed build produces is
    IDENTICAL to an uninterrupted run's;
  * the manifest binds to a state hash (settings + input fingerprint) and
    the emission-plan shape, so a stale store from a different job is
    refused, never silently extended.

The finished store memmaps as one ordinary :class:`~.blocking.PairIndex`
(downstream scoring is unchanged), and the streamed EM can consume the
manifest directly — segment by segment, gammas computed per chunk on
device, nothing per-pair ever resident on the host
(linker._run_em_streamed_spill).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from dataclasses import dataclass

import numpy as np

from .resilience.checkpoint import atomic_write_json, fsync_dir

logger = logging.getLogger("splink_tpu")

SPILL_VERSION = 1
MANIFEST_NAME = "pair_manifest.json"
_BIN_NAMES = ("idx_l.bin", "idx_r.bin")

# FNV/murmur-style mixing constants shared by the device digest kernel
# (blocking_device.make_chunk_digest_fn) and the host mirror below — the
# two MUST agree lane for lane or every transfer check fails.
DIGEST_MUL = 2654435761  # Knuth multiplicative hash constant (2^32 / phi)
DIGEST_ADD = 2246822519  # xxhash PRIME32_2


class SpillError(RuntimeError):
    """Unusable spill store (wrong job, wrong version, unreadable)."""


class SpillCorruptionError(SpillError):
    """A committed segment's bytes no longer match its manifest record."""


def chunk_digest_host(i: np.ndarray, j: np.ndarray) -> int:
    """Order-independent uint32 digest over a pair chunk — the host mirror
    of the jitted ``spill_chunk_digest`` kernel (sum of per-lane mixes,
    wraparound). Computed over the bytes actually written to disk, it
    closes the loop on the device-side value: a mismatch means the pairs
    were corrupted between device memory and the host buffer (a tunnelled
    D2H link failure mode) — BEFORE they poison a multi-hour build."""
    if len(i) == 0:
        return 0
    with np.errstate(over="ignore"):
        mixed = (i.astype(np.uint32) * np.uint32(DIGEST_MUL)) ^ (
            j.astype(np.uint32) + np.uint32(DIGEST_ADD)
        )
        mixed = mixed ^ (mixed >> np.uint32(15))
        return int(np.sum(mixed, dtype=np.uint32))


def _segment_sha(i: np.ndarray, j: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(i).tobytes())
    h.update(np.ascontiguousarray(j).tobytes())
    return h.hexdigest()


@dataclass
class SpillSegment:
    """One committed emission segment (a contiguous pair range)."""

    rule: int
    shard: int
    seq: int
    offset: int  # element offset into the bins
    pairs: int
    sha256: str
    digest: int | None = None  # device-side transfer digest (uint32)

    def to_json(self) -> dict:
        d = {
            "rule": self.rule,
            "shard": self.shard,
            "seq": self.seq,
            "offset": self.offset,
            "pairs": self.pairs,
            "sha256": self.sha256,
        }
        if self.digest is not None:
            d["digest"] = self.digest
        return d

    @classmethod
    def from_json(cls, d: dict) -> "SpillSegment":
        return cls(
            rule=int(d["rule"]),
            shard=int(d["shard"]),
            seq=int(d["seq"]),
            offset=int(d["offset"]),
            pairs=int(d["pairs"]),
            sha256=d["sha256"],
            digest=d.get("digest"),
        )


class PairSpillStore:
    """A durable, resumable pair spill directory (module docstring).

    Unlike the transient ``_PairSink`` spill (deleted when its PairIndex is
    garbage-collected), a store is OWNED BY THE CALLER: it survives the
    process, is the unit of crash recovery, and is deleted only explicitly.
    Use as a context manager — an exception mid-emission truncates the
    uncommitted tail (segments on disk but not in the manifest) instead of
    leaving torn bytes for the next attach to re-discover.
    """

    def __init__(self, directory: str, idx_dtype, meta: dict,
                 segments: list[SpillSegment], completed: bool):
        self.directory = directory
        self.idx_dtype = np.dtype(idx_dtype)
        self.meta = meta
        self.segments = segments
        self.completed = completed
        self._done = {(s.rule, s.shard, s.seq): s for s in segments}
        self._files: list | None = None
        self._maps: list[np.ndarray] = []

    # ------------------------------------------------------------------
    # Construction / resume
    # ------------------------------------------------------------------

    @classmethod
    def attach(cls, directory: str | os.PathLike, idx_dtype,
               meta: dict | None = None) -> "PairSpillStore":
        """Open-or-create the store at ``directory``.

        With an existing manifest the store RESUMES: the manifest must bind
        to the same ``meta`` (state hash + plan shape — a store written for
        a different job/plan raises :class:`SpillError` rather than being
        silently extended), and any bytes past the last committed segment
        (a torn tail from a kill mid-segment) are truncated away so the
        next emitted segment lands exactly where an uninterrupted run would
        have put it.
        """
        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
        manifest_path = os.path.join(directory, MANIFEST_NAME)
        idx_dtype = np.dtype(idx_dtype)
        if os.path.exists(manifest_path):
            try:
                with open(manifest_path, encoding="utf-8") as fh:
                    m = json.load(fh)
            except (OSError, json.JSONDecodeError) as e:
                raise SpillError(
                    f"unreadable spill manifest at {manifest_path}: {e}"
                ) from e
            if m.get("version") != SPILL_VERSION:
                raise SpillError(
                    f"spill store at {directory} has format version "
                    f"{m.get('version')!r}; this build reads {SPILL_VERSION}"
                )
            if np.dtype(m.get("dtype", "int32")) != idx_dtype:
                raise SpillError(
                    f"spill store at {directory} holds {m.get('dtype')!r} "
                    f"indices; this job needs {idx_dtype.name}"
                )
            if meta is not None:
                # compare only the caller's binding keys: finalize() may
                # have merged extra bookkeeping (e.g. exhausted) into the
                # stored meta, which must not break an idempotent re-attach
                stored = m.get("meta") or {}
                want = _jsonable_meta(meta)
                if any(stored.get(k) != v for k, v in want.items()):
                    raise SpillError(
                        f"spill store at {directory} was written for a "
                        "different job or emission plan (meta mismatch); "
                        "point build_spill_dir at a fresh directory or "
                        "delete it"
                    )
            segments = [SpillSegment.from_json(d) for d in m.get("segments", [])]
            store = cls(
                directory, idx_dtype, m.get("meta") or {}, segments,
                bool(m.get("completed")),
            )
            store._truncate_to_watermark()
            if segments:
                logger.info(
                    "spill store resumed at %s: %d committed segments, "
                    "%d pairs", directory, len(segments), store.total_pairs,
                )
            return store
        store = cls(directory, idx_dtype, _jsonable_meta(meta or {}), [], False)
        # fresh bins (a manifest-less directory holds nothing committed)
        for name in _BIN_NAMES:
            with open(os.path.join(directory, name), "wb"):
                pass
        store._write_manifest()
        return store

    def _truncate_to_watermark(self) -> None:
        want = self.total_pairs * self.idx_dtype.itemsize
        for name in _BIN_NAMES:
            path = os.path.join(self.directory, name)
            try:
                have = os.path.getsize(path)
            except OSError as e:
                raise SpillCorruptionError(
                    f"spill store at {self.directory} is missing {name}: {e}"
                ) from e
            if have < want:
                raise SpillCorruptionError(
                    f"spill bin {path} holds {have} bytes but the manifest "
                    f"commits {want}; the store is corrupt — delete it and "
                    "rebuild"
                )
            if have > want:
                logger.info(
                    "spill store %s: truncating %d torn bytes off %s "
                    "(uncommitted tail of an interrupted segment)",
                    self.directory, have - want, name,
                )
                with open(path, "r+b") as fh:
                    fh.truncate(want)
                    fh.flush()
                    os.fsync(fh.fileno())

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    @property
    def total_pairs(self) -> int:
        if not self.segments:
            return 0
        last = self.segments[-1]
        return last.offset + last.pairs

    def segment_done(self, rule: int, shard: int, seq: int) -> bool:
        return (rule, shard, seq) in self._done

    def segment_pairs(self, rule: int, shard: int, seq: int) -> int:
        return self._done[(rule, shard, seq)].pairs

    # ------------------------------------------------------------------
    # Writing
    # ------------------------------------------------------------------

    def _open_files(self):
        if self._files is None:
            self._files = [
                open(os.path.join(self.directory, name), "ab")
                for name in _BIN_NAMES
            ]
        return self._files

    def write_segment(self, rule: int, shard: int, seq: int,
                      i: np.ndarray, j: np.ndarray,
                      digest: int | None = None,
                      fault_hook=None) -> SpillSegment:
        """Append one segment and commit it to the manifest.

        Bytes land (flush + fsync) BEFORE the manifest rewrite — the
        manifest is the only commit point, so a crash anywhere in between
        leaves a torn tail the next attach truncates, never a committed
        segment without its bytes. ``fault_hook`` (a zero-arg callable)
        fires between the byte append and the manifest commit: it is the
        deterministic injection point the kill-and-resume tests aim at the
        widest vulnerable window.
        """
        if self.completed:
            raise SpillError(
                f"spill store at {self.directory} is finalized; refusing to "
                "append"
            )
        if self.segment_done(rule, shard, seq):
            raise SpillError(
                f"segment (rule={rule}, shard={shard}, seq={seq}) is "
                "already committed"
            )
        i = np.ascontiguousarray(i, dtype=self.idx_dtype)
        j = np.ascontiguousarray(j, dtype=self.idx_dtype)
        if len(i) != len(j):
            raise ValueError("idx_l / idx_r length mismatch")
        if digest is not None:
            host = chunk_digest_host(i, j)
            if host != int(digest) & 0xFFFFFFFF:
                raise SpillCorruptionError(
                    f"device transfer digest mismatch on segment (rule="
                    f"{rule}, shard={shard}, seq={seq}): device "
                    f"{int(digest) & 0xFFFFFFFF:#010x} vs host {host:#010x}"
                    " — the D2H download corrupted the chunk"
                )
        fl, fr = self._open_files()
        i.tofile(fl)
        j.tofile(fr)
        for fh in (fl, fr):
            fh.flush()
            os.fsync(fh.fileno())
        seg = SpillSegment(
            rule=rule, shard=shard, seq=seq, offset=self.total_pairs,
            pairs=len(i), sha256=_segment_sha(i, j),
            digest=None if digest is None else int(digest) & 0xFFFFFFFF,
        )
        if fault_hook is not None:
            fault_hook()
        self.segments.append(seg)
        self._done[(seg.rule, seg.shard, seg.seq)] = seg
        self._write_manifest()
        return seg

    def abort_uncommitted(self) -> None:
        """Drop any appended-but-uncommitted bytes (exception mid-segment):
        close the append handles FIRST (Windows cannot truncate an open
        file through a second handle), then truncate to the committed
        watermark."""
        self._close_files()
        self._truncate_to_watermark()

    def finalize(self, **extra) -> None:
        """Mark the store complete (one more atomic manifest write). A
        consumer requiring a FINISHED pair set (the streamed EM, the index
        build) checks ``completed`` and refuses a half-emitted store."""
        self.completed = True
        self.meta = dict(self.meta)
        self.meta.update(_jsonable_meta(extra))
        self._write_manifest()
        self._close_files()

    def _write_manifest(self) -> None:
        atomic_write_json(
            os.path.join(self.directory, MANIFEST_NAME),
            {
                "version": SPILL_VERSION,
                "dtype": self.idx_dtype.name,
                "completed": self.completed,
                "meta": self.meta,
                "total_pairs": self.total_pairs,
                "segments": [s.to_json() for s in self.segments],
            },
        )
        fsync_dir(self.directory)

    def _close_files(self) -> None:
        if self._files is not None:
            for fh in self._files:
                try:
                    fh.close()
                except OSError:
                    pass
            self._files = None

    def __enter__(self) -> "PairSpillStore":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.abort_uncommitted()
        else:
            self._close_files()

    # ------------------------------------------------------------------
    # Reading
    # ------------------------------------------------------------------

    def _map(self, name: str) -> np.ndarray:
        n = self.total_pairs
        if n == 0:
            return np.zeros(0, self.idx_dtype)
        arr = np.memmap(
            os.path.join(self.directory, name),
            dtype=self.idx_dtype, mode="r", shape=(n,),
        )
        self._maps.append(arr)
        return arr

    def open_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(idx_l, idx_r) read-only memmaps over the committed range.

        Memoised per committed length: the spill-fed EM calls this once
        per PASS (run_em_streamed re-invokes its batch factory every
        iteration), and re-mapping two multi-GB bins per iteration would
        accumulate hundreds of live mappings over a long training run."""
        cached = self._maps
        if len(cached) >= 2 and len(cached[-2]) == self.total_pairs:
            return cached[-2], cached[-1]
        return self._map(_BIN_NAMES[0]), self._map(_BIN_NAMES[1])

    def as_pair_index(self):
        """The committed pair set as an ordinary PairIndex (memmap-backed,
        NO deletion finalizer — the store is durable and caller-owned,
        unlike the transient ``_PairSink`` spill)."""
        from .blocking import PairIndex

        il, ir = self.open_arrays()
        out = PairIndex(il, ir)
        out.spill_store = self
        return out

    def iter_segments(self):
        """Yield ``(SpillSegment, idx_l, idx_r)`` per committed segment —
        the manifest-order stream the spill-fed EM and the verifier walk."""
        il, ir = self.open_arrays()
        for seg in self.segments:
            sl = slice(seg.offset, seg.offset + seg.pairs)
            yield seg, il[sl], ir[sl]

    def verify(self) -> None:
        """Recompute every committed segment's sha256 against the manifest;
        raises :class:`SpillCorruptionError` on the first mismatch. One
        sequential read of the bins — run it before trusting a store that
        crossed storage systems. Deliberately does NOT release the maps:
        open_arrays memoises them, so a PairIndex handed out earlier reads
        the same objects, and closing a map under a live numpy view does
        not fail — it makes the next access segfault."""
        for seg, i, j in self.iter_segments():
            got = _segment_sha(i, j)
            if got != seg.sha256:
                raise SpillCorruptionError(
                    f"segment (rule={seg.rule}, shard={seg.shard}, "
                    f"seq={seg.seq}) of {self.directory} fails its "
                    "manifest sha256 — the bins were corrupted on disk"
                )

    def release_maps(self) -> None:
        """Close every memmap handed out by this store (Windows-safe
        ordering: maps must be released BEFORE any unlink of the bins).

        EXPLICIT end-of-life only: mmap.close() succeeds even while numpy
        views are alive, and any later access through such a view is a
        hard crash — callers invoke this exactly when they are done with
        every array the store handed out (PairIndex.release, close)."""
        maps, self._maps = self._maps, []
        for arr in maps:
            mm = getattr(arr, "_mmap", None)
            if mm is not None:
                try:
                    mm.close()
                except (BufferError, OSError):
                    pass  # some mmap implementations do refuse live views

    def close(self) -> None:
        self._close_files()
        self.release_maps()


def _jsonable_meta(meta: dict) -> dict:
    """Round-trip ``meta`` through JSON so attach-time equality compares
    what the manifest actually stores (tuples become lists, numpy ints
    become ints)."""
    return json.loads(json.dumps(meta, sort_keys=True, default=_np_scalar))


def _np_scalar(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    raise TypeError(f"unserialisable meta value {v!r}")


def iter_spill_gamma_batches(store: PairSpillStore, program, batch_size: int,
                             pair_range: slice | None = None):
    """One pass of gamma micro-batches over a committed spill store — what
    ``run_em_streamed``'s ``batch_iter_factory`` calls every EM iteration.

    The pair index arrays stay memmapped; each ``batch_size`` slice is read
    once, its gamma block computed on device
    (:meth:`~.gammas.GammaProgram.iter_gamma_chunks`) and yielded — the
    gamma matrix NEVER materialises on the host, which is the point: at
    billions of pairs even the int8 G is tens of GB. ``pair_range``
    restricts the pass to a global slice (multi-controller runs pass
    ``distributed.global_pair_slice`` so each host streams only its own
    share of the manifest). Batch boundaries are identical to the
    materialised streamed path's, so the EM trajectory is bit-identical to
    a run that could afford the resident G.
    """
    if not store.completed:
        raise SpillError(
            f"spill store at {store.directory} is not finalized; refusing "
            "to train on a half-emitted pair set"
        )
    il, ir = store.open_arrays()
    lo, hi = 0, store.total_pairs
    if pair_range is not None:
        lo, hi = pair_range.start, pair_range.stop
    if hi <= lo:
        return
    yield from program.iter_gamma_chunks(
        il[lo:hi], ir[lo:hi], batch_size=batch_size
    )
