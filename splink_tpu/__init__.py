"""splink_tpu: a TPU-native probabilistic record-linkage framework.

A from-scratch JAX/XLA implementation of the Fellegi-Sunter EM record-linkage
model with the capability surface of early Splink (the reference Spark SQL
implementation): declarative settings, blocking, comparison-vector
computation, EM estimation, term-frequency adjustment, model persistence and
explainability — redesigned for TPU execution (fused jitted EM, vmapped
string kernels, pair-axis sharding over a device mesh).

Public API mirrors the reference (/root/reference/splink/__init__.py):
``Splink`` and ``load_from_json``, plus the lower-level building blocks.
"""

__version__ = "0.1.0"

from . import ops, parallel
from .em import run_em, score_pairs, score_pairs_with_intermediates
from .models.fellegi_sunter import FSParams
from .params import Params, load_params_from_dict, load_params_from_json
from .settings import complete_settings_dict
from .validate import validate_settings

__all__ = [
    "__version__",
    "ops",
    "parallel",
    "run_em",
    "score_pairs",
    "score_pairs_with_intermediates",
    "FSParams",
    "Params",
    "load_params_from_dict",
    "load_params_from_json",
    "complete_settings_dict",
    "validate_settings",
    # provided lazily from splink_tpu.linker (kept lazy to keep import light):
    "Splink",
    "load_from_json",
    "register_comparison",
]


def __getattr__(name):
    # Lazy linker import: keeps module import light and cycle-free.
    if name in ("Splink", "load_from_json", "register_comparison"):
        from . import linker

        return getattr(linker, name)
    raise AttributeError(f"module 'splink_tpu' has no attribute {name!r}")
