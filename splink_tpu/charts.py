"""Vega-Lite chart specs for model diagnostics.

Covers the same five diagnostic views the reference ships
(/root/reference/splink/chart_definitions.py): m/u probability distributions,
adjustment factors, lambda history, pi history and log-likelihood history,
plus the per-row adjustment (waterfall-style) chart used by the intuition
report. Specs here are authored for this package; data row formats match the
reference so downstream tooling can consume either.
"""

from __future__ import annotations

import json
import os


def _base(title: str, mark: str, encoding: dict, extra: dict | None = None) -> dict:
    spec = {
        "$schema": "https://vega.github.io/schema/vega-lite/v3.json",
        "title": title,
        "mark": mark,
        "data": {"values": []},
        "encoding": encoding,
    }
    if extra:
        spec.update(extra)
    return spec


probability_distribution_chart_def = _base(
    "Probability distribution of comparison vector values, m=match, u=non-match",
    "bar",
    {
        "x": {"type": "quantitative", "field": "probability"},
        "y": {"type": "nominal", "field": "value_of_gamma", "sort": "descending"},
        "color": {"type": "nominal", "field": "match"},
        "row": {"type": "nominal", "field": "column"},
        "column": {"type": "nominal", "field": "match"},
        "tooltip": [
            {"type": "nominal", "field": "column"},
            {"type": "quantitative", "field": "probability"},
            {"type": "ordinal", "field": "value"},
        ],
    },
    {"resolve": {"scale": {"y": "independent"}}, "height": 100},
)

lambda_iteration_chart_def = _base(
    "Lambda (estimated proportion of matches) by iteration",
    "bar",
    {
        "x": {"type": "ordinal", "field": "iteration"},
        "y": {"type": "quantitative", "field": "λ", "scale": {"domain": [0, 1]}},
        "tooltip": [
            {"type": "quantitative", "field": "λ"},
            {"type": "ordinal", "field": "iteration"},
        ],
    },
)

ll_iteration_chart_def = _base(
    "Log likelihood by iteration",
    "bar",
    {
        "x": {"type": "ordinal", "field": "iteration"},
        "y": {"type": "quantitative", "field": "log_likelihood"},
        "tooltip": [
            {"type": "quantitative", "field": "log_likelihood"},
            {"type": "ordinal", "field": "iteration"},
        ],
    },
)

pi_iteration_chart_def = _base(
    "Estimated m and u probabilities by iteration",
    "bar",
    {
        "x": {"type": "quantitative", "field": "probability"},
        "y": {"type": "nominal", "field": "iteration", "sort": "descending"},
        "color": {"type": "nominal", "field": "match"},
        "row": {"type": "nominal", "field": "value_of_gamma"},
        "column": {"type": "nominal", "field": "column"},
        "tooltip": [
            {"type": "nominal", "field": "column"},
            {"type": "nominal", "field": "value_of_gamma"},
            {"type": "quantitative", "field": "probability"},
            {"type": "ordinal", "field": "iteration"},
        ],
    },
    {"height": 120},
)

adjustment_weight_chart_def = _base(
    "Influence of comparison vector values on match probability",
    "bar",
    {
        "x": {"type": "nominal", "field": "col_name"},
        "y": {
            "type": "quantitative",
            "field": "normalised_adjustment",
            "scale": {"domain": [-0.5, 0.5]},
            "axis": {"title": "match weight (adjustment - 0.5)"},
        },
        "color": {
            "type": "quantitative",
            "field": "normalised_adjustment",
            "scale": {"domain": [-0.5, 0.5], "scheme": "redyellowgreen"},
        },
        "row": {"type": "nominal", "field": "level"},
        "tooltip": [
            {"type": "nominal", "field": "col_name"},
            {"type": "nominal", "field": "level"},
            {"type": "quantitative", "field": "m"},
            {"type": "quantitative", "field": "u"},
            {"type": "quantitative", "field": "adjustment"},
        ],
    },
    {"height": 80},
)

adjustment_factor_chart_def = _base(
    "Per-column adjustment factors for this record comparison",
    "bar",
    {
        "x": {
            "type": "quantitative",
            "field": "normalised",
            "scale": {"domain": [-0.5, 0.5]},
            "axis": {"title": "adjustment factor - 0.5"},
        },
        "y": {"type": "nominal", "field": "col_name"},
        "color": {
            "type": "quantitative",
            "field": "normalised",
            "scale": {"domain": [-0.5, 0.5], "scheme": "redyellowgreen"},
        },
        "tooltip": [
            {"type": "nominal", "field": "col_name"},
            {"type": "quantitative", "field": "value"},
        ],
    },
)

_MULTI_CHART_PAGE = """<!DOCTYPE html>
<html>
<head>
  <script src="https://cdn.jsdelivr.net/npm/vega@{vega_version}"></script>
  <script src="https://cdn.jsdelivr.net/npm/vega-lite@{vegalite_version}"></script>
  <script src="https://cdn.jsdelivr.net/npm/vega-embed@{vegaembed_version}"></script>
</head>
<body>
{divs}
<script>
{embeds}
</script>
</body>
</html>
"""


def render_charts_html(specs_with_data: list[dict],
                       vega_version="5", vegalite_version="3.3.0",
                       vegaembed_version="4") -> str:
    """Render a standalone HTML page embedding every chart spec given."""
    divs, embeds = [], []
    for i, spec in enumerate(specs_with_data):
        divs.append(f'<div id="chart_{i}"></div>')
        embeds.append(
            f"vegaEmbed('#chart_{i}', {json.dumps(spec)}).catch(console.error);"
        )
    return _MULTI_CHART_PAGE.format(
        vega_version=vega_version,
        vegalite_version=vegalite_version,
        vegaembed_version=vegaembed_version,
        divs="\n".join(divs),
        embeds="\n".join(embeds),
    )


def with_data(spec: dict, rows: list[dict]) -> dict:
    out = json.loads(json.dumps(spec))
    out["data"]["values"] = rows
    return out


def try_altair(spec: dict):
    """Return an altair Chart if altair is importable, else the raw spec dict."""
    try:  # pragma: no cover - altair not in the base image
        import altair as alt

        return alt.Chart.from_dict(spec)
    except Exception:
        return spec


def write_html_file(path: str, specs_with_data: list[dict], overwrite: bool = False):
    if os.path.isfile(path) and not overwrite:
        raise ValueError(f"The path {path} already exists. Please provide a different path.")
    with open(path, "w") as f:
        f.write(render_charts_html(specs_with_data))
