from .fellegi_sunter import (
    FSParams,
    SufficientStats,
    em_step,
    gamma_prob_lookup,
    log_bayes_factor,
    log_likelihood,
    match_probability,
    sufficient_stats,
    update_params,
)

__all__ = [
    "FSParams",
    "SufficientStats",
    "em_step",
    "gamma_prob_lookup",
    "log_bayes_factor",
    "log_likelihood",
    "match_probability",
    "sufficient_stats",
    "update_params",
]
