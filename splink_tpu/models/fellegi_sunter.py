"""Fellegi-Sunter model mathematics as pure JAX functions.

This is the model the reference estimates via generated SQL: the E-step's
naive-Bayes match probability (/root/reference/splink/expectation_step.py:167-185)
and the M-step's grouped sufficient statistics
(/root/reference/splink/maximisation_step.py:41-90). Differences from the
reference are deliberate TPU-first choices:

  * Scoring works in log space (the reference multiplies raw doubles and
    needed a tiny-number regression test for underflow; summing log ratios
    plus a sigmoid is exact and underflow-free).
  * The M-step's SQL ``GROUP BY`` over all gamma combinations becomes a
    one-hot reduction (an (n, C, Lmax) mask contracted against the match
    probabilities) which XLA lowers to MXU-friendly reductions and, when the
    pair axis is sharded over a device mesh, to ``psum`` collectives over ICI.
  * gamma = -1 (null) semantics match the reference exactly: nulls contribute
    probability 1 to both numerator and denominator in scoring, and rows are
    excluded from a column's M-step normaliser when that column is null
    (/root/reference/splink/maximisation_step.py:68-69).

Shapes: G is (n_pairs, n_cols) int8 with entries in {-1, 0, .., L_c - 1};
m/u are (n_cols, max_levels); weights is (n_pairs,) with 0 marking padding.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class FSParams(NamedTuple):
    """Device-side Fellegi-Sunter parameters (the traced EM state)."""

    lam: jnp.ndarray  # scalar: prior P(match)
    m: jnp.ndarray  # (C, L): P(gamma = level | match)
    u: jnp.ndarray  # (C, L): P(gamma = level | non-match)


class SufficientStats(NamedTuple):
    """Streaming-accumulable EM sufficient statistics."""

    m_num: jnp.ndarray  # (C, L): sum of p over rows with gamma_c = level
    u_num: jnp.ndarray  # (C, L): sum of 1-p over rows with gamma_c = level
    m_den: jnp.ndarray  # (C,): sum of p over rows with gamma_c != -1
    u_den: jnp.ndarray  # (C,): sum of 1-p over rows with gamma_c != -1
    sum_p: jnp.ndarray  # scalar: sum of p over all rows
    n_rows: jnp.ndarray  # scalar: number of (real) rows

    def __add__(self, other: "SufficientStats") -> "SufficientStats":
        return SufficientStats(*(a + b for a, b in zip(self, other)))

    @staticmethod
    def zeros(n_cols: int, max_levels: int, dtype=jnp.float32) -> "SufficientStats":
        return SufficientStats(
            m_num=jnp.zeros((n_cols, max_levels), dtype),
            u_num=jnp.zeros((n_cols, max_levels), dtype),
            m_den=jnp.zeros((n_cols,), dtype),
            u_den=jnp.zeros((n_cols,), dtype),
            sum_p=jnp.zeros((), dtype),
            n_rows=jnp.zeros((), dtype),
        )


def _safe_log(x):
    return jnp.log(jnp.maximum(x, jnp.finfo(x.dtype).tiny))


def _select_levels(G, table):
    """(n, C) table[c, G[n, c]] via unrolled compare-and-mask (gather-free).

    TPU gathers serialise badly; with max_levels <= ~4 a masked sum over the
    static level axis is pure VPU work: out = sum_l table[:, l] * [G == l].
    Entries where G = -1 come out as 0."""
    L = table.shape[1]
    out = jnp.zeros(G.shape, table.dtype)
    for lv in range(L):
        out = out + jnp.where(G == lv, table[None, :, lv], jnp.zeros((), table.dtype))
    return out


def gamma_log_probs(G, probs):
    """(n, C) log prob of each row's gamma level under `probs`; 0 where null."""
    lp = _select_levels(G, _safe_log(probs))
    return jnp.where(G >= 0, lp, jnp.zeros((), lp.dtype))


def log_bayes_factor(G, params: FSParams):
    """(n,) summed per-column log(m/u) evidence."""
    return jnp.sum(
        gamma_log_probs(G, params.m) - gamma_log_probs(G, params.u), axis=-1
    )


def match_logit(G, params: FSParams):
    """(n,) pre-sigmoid match evidence: logit(lambda) + log Bayes factor.

    The quantity the term-frequency fold adds its per-pair delta to
    (term_frequencies.make_tf_fold_fn): serve and offline both compute
    ``sigmoid(match_logit + tf_sum)`` with the same association order,
    which is what keeps the TF-adjusted scores bit-identical across
    paths."""
    lam = params.lam
    prior_logit = _safe_log(lam) - _safe_log(1.0 - lam)
    return prior_logit + log_bayes_factor(G, params)


def match_probability(G, params: FSParams):
    """E-step: P(match | gamma vector) = sigmoid(logit(lambda) + log BF)."""
    return jax.nn.sigmoid(match_logit(G, params))


def fold_logit(G, params: FSParams):
    """:func:`match_logit` with the log-Bayes-factor accumulated COLUMN BY
    COLUMN, left to right — the exact expression tree of the fused serve
    megakernel (serve/engine.make_score_fused_fn), per-column masked
    level lookups included.

    Mathematically identical to ``match_logit``; bitwise it can differ in
    the last ulp past ~2 comparison columns, because ``jnp.sum``'s
    reduction tree is not the sequential order the fused kernel's running
    accumulator uses. The TF fold therefore anchors on THIS logit on
    every path (fused serve, unfused serve oracle, offline fold kernel) —
    that shared order is what makes the TF-adjusted scores bit-identical
    across all of them at any column count. The unadjusted score keeps
    ``match_probability`` unchanged."""
    lam = params.lam
    prior_logit = _safe_log(lam) - _safe_log(1.0 - lam)
    log_m = _safe_log(params.m)
    log_u = _safe_log(params.u)
    n_levels = log_m.shape[1]
    log_bf = jnp.zeros(G.shape[0], log_m.dtype)
    for ci in range(G.shape[1]):
        g = G[:, ci]
        lp_m = jnp.zeros(g.shape, log_m.dtype)
        lp_u = jnp.zeros(g.shape, log_u.dtype)
        for lv in range(n_levels):
            hit = g == lv
            zero = jnp.zeros((), log_m.dtype)
            lp_m = lp_m + jnp.where(hit, log_m[ci, lv], zero)
            lp_u = lp_u + jnp.where(hit, log_u[ci, lv], zero)
        null = g >= 0
        zero = jnp.zeros((), log_m.dtype)
        log_bf = log_bf + (
            jnp.where(null, lp_m, zero) - jnp.where(null, lp_u, zero)
        )
    return prior_logit + log_bf


def gamma_prob_lookup(G, probs):
    """(n, C) probability of the observed gamma under `probs`, 1.0 where null.

    This is the reference's per-column prob_gamma_* lookup column
    (/root/reference/splink/expectation_step.py:196-221)."""
    p = _select_levels(G, probs)
    return jnp.where(G >= 0, p, jnp.ones((), p.dtype))


def log_likelihood(G, params: FSParams, weights=None):
    """Sum over rows of ln(lam * prod m + (1-lam) * prod u), log-space safe."""
    log_m = jnp.sum(gamma_log_probs(G, params.m), axis=-1)
    log_u = jnp.sum(gamma_log_probs(G, params.u), axis=-1)
    ll_rows = jnp.logaddexp(
        _safe_log(params.lam) + log_m, _safe_log(1.0 - params.lam) + log_u
    )
    if weights is not None:
        ll_rows = ll_rows * weights
    return jnp.sum(ll_rows)


def sufficient_stats(G, p_match, max_levels: int, weights=None) -> SufficientStats:
    """M-step sufficient statistics from one (shard of a) batch of pairs.

    ``max_levels`` must be static (it fixes the stats shape). Every reduction
    is over the pair axis, so under a sharded-pair jit these lower to
    per-device partial sums + psum over the mesh.
    """
    dtype = p_match.dtype
    if weights is None:
        weights = jnp.ones(p_match.shape, dtype)
    pm = p_match * weights
    pu = (1.0 - p_match) * weights

    onehot = (
        G[:, :, None] == jnp.arange(max_levels, dtype=G.dtype)[None, None, :]
    ).astype(dtype)  # (n, C, max_levels)
    m_num = jnp.einsum("ncl,n->cl", onehot, pm)
    u_num = jnp.einsum("ncl,n->cl", onehot, pu)

    valid = (G >= 0).astype(dtype)  # (n, C)
    m_den = jnp.einsum("nc,n->c", valid, pm)
    u_den = jnp.einsum("nc,n->c", valid, pu)

    return SufficientStats(
        m_num=m_num,
        u_num=u_num,
        m_den=m_den,
        u_den=u_den,
        sum_p=jnp.sum(pm),
        n_rows=jnp.sum(weights),
    )


def update_params(stats: SufficientStats) -> FSParams:
    """M-step parameter update from accumulated sufficient statistics.

    Levels never observed get probability exactly 0, reproducing the
    reference's zero-fill for unseen gamma values
    (/root/reference/splink/params.py:256-274).
    """
    eps = jnp.finfo(stats.m_num.dtype).tiny
    new_m = stats.m_num / jnp.maximum(stats.m_den, eps)[:, None]
    new_u = stats.u_num / jnp.maximum(stats.u_den, eps)[:, None]
    new_lam = stats.sum_p / jnp.maximum(stats.n_rows, eps)
    return FSParams(lam=new_lam, m=new_m, u=new_u)


def em_step(G, params: FSParams, max_levels: int, weights=None):
    """One fused E+M step. Returns (new_params, max_pi_delta)."""
    p = match_probability(G, params)
    stats = sufficient_stats(G, p, max_levels, weights)
    new = update_params(stats)
    delta = jnp.maximum(
        jnp.max(jnp.abs(new.m - params.m)), jnp.max(jnp.abs(new.u - params.u))
    )
    return new, delta
