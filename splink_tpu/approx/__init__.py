"""Approximate blocking: device-native minhash-LSH + q-gram similarity tier.

Every exact blocking rule is an equality conjunction over key codes — a
record with a typo in each blocking key is unreachable by both training and
serving (ROADMAP item 2). This package adds the recall tier:

  * :mod:`.minhash` — per-record minhash signatures over q-gram sets
    (reusing the exact gram codes of ``ops/qgram.py``) with LSH banding,
    as jitted fixed-shape kernels;
  * :mod:`.lsh` — LSH-bucket candidate generation through the SAME
    segmented-sort / unit-decode machinery as ``blocking_device.py``, an
    optional q-gram Jaccard verification pass, and progressive emission:
    candidates ranked by estimated similarity and emitted best-first under
    an explicit ``approx_pair_budget``.

Opt in with ``approx_blocking: true`` in the settings; the tier composes
with the exact rules (a pair any exact rule produced is never re-emitted)
and also backs the serve fallback bucket path (``serve/index.py``): a
query whose exact keys hit no bucket falls back to LSH-bucket candidates
tagged ``approx=True`` instead of returning empty. See docs/blocking.md
("Approximate tier").
"""

from .lsh import (  # noqa: F401
    ApproxConfig,
    approx_block_into,
    approx_columns,
    build_approx_plan,
    generate_approx_candidates,
)
from .minhash import (  # noqa: F401
    band_key_arrays,
    factorise_band_codes,
    hash_params,
    make_minhash_fn,
)
