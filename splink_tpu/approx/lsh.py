"""LSH-bucket candidate generation, q-gram verification, progressive budget.

The approximate tier of :func:`..blocking.block_using_rules`:

  1. **signatures** — :mod:`.minhash` band keys over the approx columns,
     factorised to dense int32 codes per band;
  2. **candidates** — each band is a symmetric hash join on its band
     codes, run through the SAME device machinery as the exact tier
     (``blocking_device``'s segmented sort, bounded triangle/rectangle
     units and the chunked pair-emit kernel): band ``b``'s kernel carries
     bands ``0..b-1`` as its sequential-dedup predecessors, so every
     colliding pair is emitted exactly once (by its first colliding band);
     pairs any EXACT rule produced are dropped host-side per chunk via the
     exact ``blocking._rule_holds`` semantics (key equality + residual,
     UNKNOWN counts as not-produced);
  3. **verification / ranking** — a jitted kernel counts each pair's band
     collisions and (when ``approx_threshold > 0``) computes the mean
     q-gram Jaccard over the approx columns via the exact
     ``ops.qgram.qgram_jaccard_masked_single`` kernel vmapped over the
     pair chunk; pairs below the threshold are dropped;
  4. **progressive emission** — survivors rank by estimated similarity
     (verified Jaccard first, band-collision count as the tie-break, then
     (i, j) for determinism) and stream into the sink in budget-ordered
     chunks, BEST PAIRS FIRST, until ``approx_pair_budget`` — the
     Progressive Blocking shape (arXiv:2005.14326): downstream EM runs on
     a fixed compute envelope and sees the most promising pairs first.

One ambient ``blocking_approx`` event records the run (bands, raw LSH
candidates, exact-tier overlap removed, verified survivors, emitted pairs,
budget fill, oversize buckets dropped); ``python -m splink_tpu.obs
summarize`` renders it.
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass, field

import numpy as np

from ..blocking import (
    _key_codes,
    _key_codes_asym,
    _rule_holds,
    _split_join_keys,
    _uid_ranks,
    parse_blocking_rule,
)
from ..blocking_device import _pow2
from ..data import EncodedTable
from ..pairgen import (
    CHUNK,
    _pair_counts,
    _uid_mask_codes,
    _units_for_cross_join,
    _units_for_self_join,
)
from .minhash import band_key_arrays, factorise_band_codes

logger = logging.getLogger("splink_tpu")

# Schema defaults (the schema is the source of truth; these are the
# in-code fallbacks for partially-completed dicts).
DEFAULT_Q = 2
DEFAULT_BANDS = 16
DEFAULT_ROWS_PER_BAND = 2
DEFAULT_BUDGET = 1 << 22

# An LSH bucket larger than this is a degenerate band key (near-constant
# signature): its pairs are the lowest-information candidates and alone
# would dwarf any realistic budget, so the plan drops the bucket and the
# ``blocking_approx`` event reports how many were dropped (no silent cap).
MAX_BUCKET_ROWS = 4096

# Pairs per verification chunk (power-of-two bucketed): bounds the
# transient (chunk, n_windows, n_windows) cross-equality matrix.
VERIFY_CHUNK = 1 << 13

_IMAX = np.iinfo(np.int32).max


def _null_oversize_buckets(band_codes: np.ndarray) -> int:
    """Null (-1) every row of every LSH bucket wider than
    :data:`MAX_BUCKET_ROWS`, IN PLACE, returning the dropped-bucket count.

    Nulling the codes — rather than merely dropping the bucket from its
    band's emission units — is what keeps the cross-band sequential dedup
    honest: the emit kernel masks band b's pairs when an EARLIER band's
    codes collide (``(cl[i] == cr[j]) & (cl[i] >= 0)``), so a bucket
    silently removed from band 0's emission while keeping its codes would
    suppress the same pair in every later band too (lost entirely). With
    the codes nulled the pair emits through its first HEALTHY band, and
    the serve fallback — whose dictionaries simply never resolve an
    oversize bucket — agrees with the offline tier about which pairs
    exist."""
    dropped = 0
    for b in range(band_codes.shape[0]):
        codes = band_codes[b]
        valid = codes >= 0
        if not valid.any():
            continue
        sizes = np.bincount(codes[valid])
        big = np.flatnonzero(sizes > MAX_BUCKET_ROWS)
        if len(big):
            dropped += len(big)
            codes[np.isin(codes, big)] = -1
    return dropped


# --------------------------------------------------------------------------
# Configuration
# --------------------------------------------------------------------------


def approx_columns(settings: dict, table: EncodedTable) -> list[str]:
    """The string columns the approximate tier sketches, in deterministic
    order: the PLAIN string columns named by the blocking rules' equality
    keys (both sides of an asymmetric key), falling back to the string
    comparison columns when no blocking key is a plain string column
    (derived-key-only rules, numeric keys). Empty means the tier is
    unavailable for this job."""
    cols: list[str] = []

    def add(name: str) -> None:
        if name in table.strings and name not in cols:
            cols.append(name)

    for rule in settings.get("blocking_rules") or []:
        try:
            eq_pairs, residual = parse_blocking_rule(rule)
        except Exception:  # noqa: BLE001 - unparseable rule: no columns
            continue
        sym, asym, _ = _split_join_keys(eq_pairs, residual)
        for c in sym:
            add(c)
        for lc, rc in asym:
            add(lc)
            add(rc)
    if not cols:
        for c in settings.get("comparison_columns") or []:
            name = c.get("custom_name") or c.get("col_name")
            if name:
                add(name)
    return cols


@dataclass(frozen=True)
class ApproxConfig:
    cols: tuple[str, ...]
    q: int
    bands: int
    rows_per_band: int
    threshold: float
    budget: int
    # TF-weighted tier (approx_tf_weighting): IDF-weighted minhash
    # sampling + TF-weighted Jaccard verification/ranking
    tf_weighting: bool = False

    @classmethod
    def from_settings(
        cls, settings: dict, table: EncodedTable
    ) -> "ApproxConfig | None":
        """None when the tier is off or no sketchable column exists."""
        if not settings.get("approx_blocking"):
            return None
        cols = approx_columns(settings, table)
        if not cols:
            logger.warning(
                "approx_blocking is on but no blocking key or comparison "
                "column is an encoded string column; the approximate tier "
                "is skipped"
            )
            return None
        q = int(settings.get("approx_q") or DEFAULT_Q)
        if not 1 <= q <= 8:
            raise ValueError(f"approx_q={q} must be in [1, 8]")
        bands = int(settings.get("approx_bands") or DEFAULT_BANDS)
        rpb = int(settings.get("approx_rows_per_band") or DEFAULT_ROWS_PER_BAND)
        if bands < 1 or rpb < 1:
            raise ValueError(
                "approx_bands and approx_rows_per_band must be >= 1"
            )
        thr = float(settings.get("approx_threshold") or 0.0)
        if not 0.0 <= thr <= 1.0:
            raise ValueError(f"approx_threshold={thr} must be in [0, 1]")
        budget = int(settings.get("approx_pair_budget") or DEFAULT_BUDGET)
        if budget < 1:
            raise ValueError("approx_pair_budget must be >= 1")
        return cls(
            cols=tuple(cols), q=q, bands=bands, rows_per_band=rpb,
            threshold=thr, budget=budget,
            tf_weighting=bool(settings.get("approx_tf_weighting")),
        )


def column_arrays(
    table: EncodedTable, cols
) -> list[tuple[np.ndarray, np.ndarray]]:
    """(bytes, lengths) per approx column, null rows forced to length 0 so
    a null value contributes no grams (SQL equality spirit)."""
    out = []
    for name in cols:
        sc = table.strings[name]
        lengths = np.where(sc.null_mask, 0, sc.lengths).astype(np.int32)
        out.append((sc.bytes_, lengths))
    return out


# --------------------------------------------------------------------------
# Verification / ranking kernel
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def make_verify_fn(q: int, bands: int, col_shapes: tuple, with_jaccard: bool,
                   weighted: bool = False):
    """Jitted per-pair estimator: band-collision count and (optionally) the
    mean exact q-gram Jaccard over the approx columns.

    fn(i, j, band_codes, *[bytes_c, len_c, mask_c, count_c per column]
       [, idf]) -> (collisions (n,) int32, sim (n,) float32)

    ``band_codes`` is the (bands, n_rows) int32 code matrix (code -1 never
    collides). The Jaccard reuses ``ops.qgram.qgram_jaccard_masked_single``
    verbatim — the per-side distinct-gram masks/counts are the
    ``qgram_row_aux`` precomputation, so only the cross-equality matrix
    runs per pair; a column null on either side contributes Jaccard 0 (its
    union is empty). ``sim`` is the plain mean over the static column
    count: deterministic, order-free.

    ``weighted=True`` is the TF-WEIGHTED Jaccard (approx_tf_weighting):
    per column ``sum_{g in A∩B} idf(g) / sum_{g in A∪B} idf(g)`` over the
    distinct grams, with ``idf`` gathered at each gram's
    :func:`~.minhash._fold_gram_hash` top bits (the same IDF table the
    weighted sampler draws from). A shared rare gram now certifies a pair
    far more strongly than a shared common one, which is what lets the
    progressive best-first emission put true typo twins ahead of
    common-suffix near-duplicates at a fixed budget.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.qgram import _gram_codes, qgram_jaccard_masked_single
    from .minhash import DF_TABLE_BITS, _fold_gram_hash, column_salts

    n_cols = len(col_shapes)
    salts = column_salts(n_cols)

    def _wjac_single(s1, s2, l1, l2, m1, m2, salt, idf):
        w1, v1 = _gram_codes(s1, l1, q)
        w2, v2 = _gram_codes(s2, l2, q)
        eq12 = jnp.all(w1[:, None, :] == w2[None, :, :], axis=-1) & (
            v1[:, None] & v2[None, :]
        )
        shift = jnp.uint32(32 - DF_TABLE_BITS)
        h1 = _fold_gram_hash(w1, salt)
        h2 = _fold_gram_hash(w2, salt)
        g1 = idf[(h1 >> shift).astype(jnp.int32)]
        g2 = idf[(h2 >> shift).astype(jnp.int32)]
        idx1 = jnp.arange(v1.shape[0], dtype=jnp.int32)
        idx2 = jnp.arange(v2.shape[0], dtype=jnp.int32)
        first1 = (
            (m1[idx1 // 32] >> (idx1 % 32).astype(jnp.uint32)) & 1
        ) == 1
        first2 = (
            (m2[idx2 // 32] >> (idx2 % 32).astype(jnp.uint32)) & 1
        ) == 1
        zero = jnp.float32(0.0)
        inter = jnp.sum(jnp.where(first1 & eq12.any(axis=1), g1, zero))
        u1 = jnp.sum(jnp.where(first1, g1, zero))
        u2 = jnp.sum(jnp.where(first2, g2, zero))
        union = u1 + u2 - inter
        return jnp.where(union > 0, inter / union, 0.0).astype(jnp.float32)

    @jax.jit
    def fn(i, j, band_codes, *colarrs):
        if weighted:
            idf = colarrs[-1]
            colarrs = colarrs[:-1]
        coll = jnp.zeros(i.shape[0], jnp.int32)
        for b in range(bands):
            cb = band_codes[b]
            coll = coll + ((cb[i] == cb[j]) & (cb[i] >= 0)).astype(jnp.int32)
        if not with_jaccard:
            return coll, jnp.zeros(i.shape[0], jnp.float32)
        sims = jnp.zeros(i.shape[0], jnp.float32)
        for c in range(n_cols):
            bytes_, lens, mask, cnt = colarrs[4 * c : 4 * c + 4]
            if weighted:
                salt = jnp.uint32(salts[c])
                jac = jax.vmap(
                    lambda s1, s2, l1, l2, m1, m2: _wjac_single(
                        s1, s2, l1, l2, m1, m2, salt, idf  # noqa: B023
                    )
                )(
                    bytes_[i], bytes_[j], lens[i], lens[j],
                    mask[i], mask[j],
                )
            else:
                jac = jax.vmap(
                    lambda s1, s2, l1, l2, m1, n1, n2:
                    qgram_jaccard_masked_single(
                        s1, s2, l1, l2, m1, n1, n2, q
                    )
                )(
                    bytes_[i], bytes_[j], lens[i], lens[j],
                    mask[i], cnt[i], cnt[j],
                )
            sims = sims + jac
        return coll, sims / jnp.float32(n_cols)

    return fn


def _verify_aux(table: EncodedTable, cfg: ApproxConfig):
    """Per-column (bytes, lengths, first_mask, distinct_count) numpy arrays
    for the verification kernel (``qgram_row_aux`` runs once per unique
    token per column)."""
    from ..ops.qgram import qgram_row_aux

    out = []
    for name, (bytes_, lengths) in zip(
        cfg.cols, column_arrays(table, cfg.cols)
    ):
        token_ids = np.where(
            lengths > 0, table.strings[name].token_ids, -1
        ).astype(np.int32)
        mask, count, _sumsq = qgram_row_aux(bytes_, lengths, token_ids, cfg.q)
        out.append((bytes_, lengths, mask, count))
    return out


# --------------------------------------------------------------------------
# Plan build (band codes -> device join plans, one per band)
# --------------------------------------------------------------------------


@dataclass
class ApproxPlan:
    """Everything the candidate generator needs, band joins included."""

    config: ApproxConfig
    band_codes: np.ndarray  # (bands, n) int32, -1 = no signature
    device_plan: object  # blocking_device.DeviceBlockPlan over the bands
    oversize_buckets: int  # degenerate LSH buckets dropped from the join
    band_uniq_keys: list = field(default_factory=list)  # per-band uint32 keys
    idf: np.ndarray | None = None  # TF-weighting IDF table (minhash.idf_weights)

    @property
    def n_candidates(self) -> int:
        return self.device_plan.n_candidates


def compute_band_codes(
    table: EncodedTable, cfg: ApproxConfig, idf: np.ndarray | None = None
) -> tuple[np.ndarray, list[np.ndarray], np.ndarray | None]:
    """(bands, n_rows) int32 band codes + the per-band ascending unique
    key arrays (the serve bucket dictionaries key on them) + the IDF
    table when TF weighting is on (built from the corpus's hashed gram
    DF sketch unless the caller supplies one — the serve index stores it
    so query-side signatures share the exact weights)."""
    from .minhash import gram_df_table, idf_weights

    columns = column_arrays(table, cfg.cols)
    if cfg.tf_weighting and idf is None:
        df_counts, n_records = gram_df_table(columns, cfg.q)
        idf = idf_weights(df_counts, n_records)
    keys, has = band_key_arrays(
        columns, cfg.q, cfg.bands, cfg.rows_per_band,
        idf=idf if cfg.tf_weighting else None,
    )
    codes, uniqs = factorise_band_codes(keys, has)
    return codes, uniqs, idf if cfg.tf_weighting else None


def build_approx_plan(
    settings: dict,
    table: EncodedTable,
    n_left: int | None = None,
    chunk: int | None = None,
) -> ApproxPlan | None:
    """Build the per-band device join plans, or None when the tier is off /
    unavailable. Mirrors ``blocking_device.build_device_plan``'s symmetric
    and link_only branches with band codes as the join keys; oversize LSH
    buckets (> :data:`MAX_BUCKET_ROWS` rows) are dropped and counted."""
    from ..blocking_device import (
        DeviceBlockPlan,
        DeviceRule,
        make_segment_sort_fn,
    )

    cfg = ApproxConfig.from_settings(settings, table)
    if cfg is None or table.n_rows == 0:
        return None
    chunk = chunk or CHUNK
    link_type = settings["link_type"]
    n = table.n_rows
    band_codes, uniq_keys, idf = compute_band_codes(table, cfg)
    # degenerate (near-constant-signature) buckets null their codes so
    # they neither emit NOR mask later bands' pairs (docstring of
    # _null_oversize_buckets); counted, never silent
    oversize = _null_oversize_buckets(band_codes)

    if link_type == "link_only":
        assert n_left is not None
        ranks = np.zeros(n, np.int32)
        uid_codes = None
    else:
        ranks, _ = _uid_ranks(table, link_type)
        uid_codes = _uid_mask_codes(table, link_type)

    sort_fn = make_segment_sort_fn()
    all_rows = np.arange(n, dtype=np.int32)
    rules: list[DeviceRule] = []
    for b in range(cfg.bands):
        codes = band_codes[b]
        if link_type == "link_only":
            ent_codes = codes
            ent_side = np.zeros(n, np.int32)
            ent_side[n_left:] = 1
            ent_rank = np.zeros(n, np.int32)
            triangle = False
        else:
            # symmetric self-join: ranks as the tertiary sort key orient
            # the triangle decode (rank_i < rank_j for free, the
            # blocking_device symmetric-branch construction)
            ent_codes = codes
            ent_side = np.zeros(n, np.int32)
            ent_rank = ranks.astype(np.int32)
            triangle = True
        m0 = n
        m = _pow2(m0)
        ent_rows = all_rows
        if m != m0:
            pad = m - m0
            ent_codes = np.concatenate([ent_codes, np.full(pad, -1, np.int32)])
            ent_side = np.concatenate([ent_side, np.zeros(pad, np.int32)])
            ent_rank = np.concatenate([ent_rank, np.zeros(pad, np.int32)])
            ent_rows = np.concatenate([ent_rows, np.zeros(pad, np.int32)])
        row_s, seg_start, l_cnt, r_cnt, n_seg, n_valid = sort_fn(
            ent_codes, ent_side, ent_rank, ent_rows
        )
        order = np.asarray(row_s)
        n_seg_h = int(np.asarray(n_seg))
        n_valid_h = int(np.asarray(n_valid))
        starts = np.asarray(seg_start)[:n_seg_h].astype(np.int64)
        lz = np.asarray(l_cnt)[:n_seg_h].astype(np.int64)
        rz = np.asarray(r_cnt)[:n_seg_h].astype(np.int64)
        live = starts < n_valid_h
        starts, lz, rz = starts[live], lz[live], rz[live]
        if triangle:
            units = _units_for_self_join(starts, lz, chunk)
        else:
            both = (lz > 0) & (rz > 0)
            units = _units_for_cross_join(
                starts[both], lz[both], starts[both] + lz[both], rz[both],
                chunk,
            )
        if units is None:  # pragma: no cover - MAX_BUCKET_ROWS forbids it
            return None
        ua, la, ub, lb = units
        rules.append(
            DeviceRule(
                rule=f"approx:band{b}",
                order=np.ascontiguousarray(order, dtype=np.int32),
                ua=ua.astype(np.int32),
                la=la.astype(np.int32),
                ub=ub.astype(np.int32),
                lb=lb.astype(np.int32),
                pc=_pair_counts(ua, la, ub, lb),
                rank_filter=False,
            )
        )
    device_plan = DeviceBlockPlan(
        rules=rules,
        codes_l=band_codes,
        codes_r=band_codes,
        ranks=np.ascontiguousarray(ranks, dtype=np.int32),
        uid_codes=uid_codes,
        res_ops=[],
        chunk=chunk,
    )
    return ApproxPlan(
        config=cfg,
        band_codes=band_codes,
        device_plan=device_plan,
        oversize_buckets=oversize,
        band_uniq_keys=uniq_keys,
        idf=idf,
    )


# --------------------------------------------------------------------------
# Candidate generation + exact-rule dedup + verification
# --------------------------------------------------------------------------


def _exact_rule_predicates(settings: dict, table: EncodedTable):
    """[(codes_l, codes_r, residual)] for every exact blocking rule — the
    predicates the approx tier's candidates are deduplicated against
    (``blocking._rule_holds`` semantics, the reference's ``AND NOT
    ifnull(previous_rule, false)``). Key-code arrays come from the same
    per-table cache the exact tier warmed."""
    out = []
    for rule in settings.get("blocking_rules") or []:
        eq_pairs, residual = parse_blocking_rule(rule)
        sym, asym, residual = _split_join_keys(eq_pairs, residual)
        if not sym and not asym:
            out.append((None, None, residual))
        elif asym:
            cl, cr = _key_codes_asym(table, sym, asym)
            out.append((cl, cr, residual))
        else:
            c = _key_codes(table, sym)
            out.append((c, c, residual))
    return out


def generate_approx_candidates(
    settings: dict,
    table: EncodedTable,
    n_left: int | None = None,
    plan: ApproxPlan | None = None,
):
    """The top LSH candidate pairs with their ranking estimates.

    Returns ``(i, j, collisions, sim, stats)`` host arrays (``sim`` is
    all-zero when ``approx_threshold == 0`` — verification off) with the
    exact-tier overlap already removed and the threshold filter applied.
    The arrays hold at most ~2x ``approx_pair_budget`` candidates: the
    accumulation prunes to the running top-``budget`` under the emission
    ranking whenever it grows past the cap, so host RAM is O(budget), not
    O(all LSH collisions) — and since the top-B of a superset always
    contains the final top-B, the pruning never changes what
    :func:`approx_block_into` emits. ``stats["survivors"]`` counts EVERY
    threshold-surviving candidate, pruned or not. Returns None when the
    tier is unavailable.
    """
    import jax.numpy as jnp

    from ..blocking_device import iter_device_pairs

    if plan is None:
        plan = build_approx_plan(settings, table, n_left)
    if plan is None:
        return None
    cfg = plan.config
    with_jaccard = cfg.threshold > 0.0
    preds = _exact_rule_predicates(settings, table)

    col_shapes = tuple(
        (int(table.strings[c].width),
         "ascii" if table.strings[c].bytes_.dtype == np.uint8 else "wide")
        for c in cfg.cols
    )
    weighted = bool(cfg.tf_weighting and with_jaccard and plan.idf is not None)
    vfn = make_verify_fn(
        cfg.q, cfg.bands, col_shapes, with_jaccard, weighted=weighted
    )
    bc_dev = jnp.asarray(plan.band_codes)
    aux_dev = []
    if with_jaccard:
        for bytes_, lengths, mask, count in _verify_aux(table, cfg):
            aux_dev.extend(
                [jnp.asarray(bytes_), jnp.asarray(lengths),
                 jnp.asarray(mask), jnp.asarray(count)]
            )
        if weighted:
            aux_dev.append(jnp.asarray(plan.idf, jnp.float32))

    chunk_cap = int(settings.get("blocking_chunk_pairs") or 0) or (1 << 22)
    # the budget shapes nothing in the plan (bands/threshold do), so read
    # it from the CALLER's settings — a reused plan composes with a
    # different budget (the bench's unbudgeted-coverage pass relies on it)
    budget = int(settings.get("approx_pair_budget") or cfg.budget)
    # bounded pre-ranking working set: the host accumulates AT MOST
    # ~2x budget candidates — whenever the accumulation exceeds the cap it
    # prunes to the running top-``budget`` under the SAME ranking key the
    # emission uses (the top-B of a superset always contains the final
    # top-B, so pruning never changes what gets emitted). Without this, a
    # corpus with many mid-size LSH buckets could materialise billions of
    # candidates before the final ranking — unbounded host RAM the exact
    # tier's spill machinery exists to avoid.
    prune_cap = budget + max(budget, 4 * VERIFY_CHUNK)
    out_i: list[np.ndarray] = []
    out_j: list[np.ndarray] = []
    out_c: list[np.ndarray] = []
    out_s: list[np.ndarray] = []
    held = 0
    raw = 0
    survivors = 0
    overlap_removed = 0

    def _concat():
        if not out_i:
            z = np.zeros(0, np.int32)
            return z, z.copy(), np.zeros(0, np.int32), np.zeros(0, np.float32)
        return (
            np.concatenate(out_i),
            np.concatenate(out_j),
            np.concatenate(out_c),
            np.concatenate(out_s),
        )

    def _prune():
        nonlocal held
        i, j, c, sm = _concat()
        order = np.lexsort((j, i, -c, -sm))[:budget]
        out_i[:] = [i[order]]
        out_j[:] = [j[order]]
        out_c[:] = [c[order]]
        out_s[:] = [sm[order]]
        held = len(order)

    for _r, ci, cj in iter_device_pairs(plan.device_plan, chunk_cap):
        raw += len(ci)
        keep = np.ones(len(ci), bool)
        for cl, cr, residual in preds:
            keep &= ~_rule_holds(table, cl, cr, residual, ci, cj)
        kept = np.count_nonzero(keep)  # host numpy, no device sync
        overlap_removed += len(ci) - kept
        ci, cj = ci[keep], cj[keep]
        if not len(ci):
            continue
        # estimate in power-of-two bucketed sub-chunks (zero steady-state
        # recompiles; padding pairs are sliced off after the fetch)
        for s in range(0, len(ci), VERIFY_CHUNK):
            e = min(s + VERIFY_CHUNK, len(ci))
            m = _pow2(max(e - s, 1))
            ib = np.zeros(m, np.int32)
            jb = np.zeros(m, np.int32)
            ib[: e - s] = ci[s:e]
            jb[: e - s] = cj[s:e]
            coll, sim = vfn(
                jnp.asarray(ib), jnp.asarray(jb), bc_dev, *aux_dev
            )
            si = ci[s:e]
            sj = cj[s:e]
            sc = np.asarray(coll)[: e - s]
            ss = np.asarray(sim)[: e - s]
            if with_jaccard:
                thr = ss >= np.float32(cfg.threshold)
                si, sj, sc, ss = si[thr], sj[thr], sc[thr], ss[thr]
            survivors += len(si)
            if not len(si):
                continue
            out_i.append(si)
            out_j.append(sj)
            out_c.append(sc)
            out_s.append(ss)
            held += len(si)
            if held > prune_cap:
                _prune()
    i, j, coll, sim = _concat()
    stats = {
        "bands": cfg.bands,
        "rows_per_band": cfg.rows_per_band,
        "q": cfg.q,
        "cols": list(cfg.cols),
        "candidates": raw,
        "exact_overlap_removed": int(overlap_removed),
        "verified": with_jaccard,
        "tf_weighted": weighted,
        "survivors": survivors,
        "oversize_buckets_dropped": plan.oversize_buckets,
    }
    return i, j, coll, sim, stats


def approx_block_into(
    settings: dict,
    table: EncodedTable,
    n_left: int | None,
    sink,
    pair_consumer=None,
) -> int:
    """Run the approximate tier into the caller's sink AFTER the exact
    rules: rank the candidates best-first and emit budget-ordered chunks
    up to ``approx_pair_budget``. Returns the number of pairs emitted (0
    when the tier is unavailable). A tier failure degrades to 0 emitted
    pairs with a warning — it never loses the run (the exact pairs are
    already in the sink).
    """
    from ..obs.events import publish

    try:
        res = generate_approx_candidates(settings, table, n_left)
    except Exception as e:  # noqa: BLE001 - recall tier must not kill the run
        logger.warning(
            "approximate blocking failed (%s: %s); continuing with the "
            "exact tier's pairs only", type(e).__name__, e,
        )
        return 0
    if res is None:
        return 0
    i, j, coll, sim, stats = res
    budget = int(
        settings.get("approx_pair_budget") or DEFAULT_BUDGET
    )
    # progressive ranking: verified Jaccard first (all-zero when
    # verification is off), band-collision count second, (i, j) as the
    # deterministic final tie-break. np.lexsort sorts by the LAST key
    # first.
    order = np.lexsort((j, i, -coll, -sim))
    if len(order) > budget:
        order = order[:budget]
    emitted = len(order)
    chunk_cap = int(settings.get("blocking_chunk_pairs") or 0) or (1 << 22)
    for s in range(0, emitted, chunk_cap):
        sel = order[s : s + chunk_cap]
        ei = i[sel].astype(sink.idx_dtype, copy=False)
        ej = j[sel].astype(sink.idx_dtype, copy=False)
        sink.append(ei, ej)
        if pair_consumer is not None:
            pair_consumer(ei, ej)
    try:
        publish(
            "blocking_approx",
            **stats,
            budget=budget,
            emitted=emitted,
            budget_fill=round(emitted / budget, 4) if budget else 0.0,
        )
    except Exception as e:  # noqa: BLE001 - telemetry must never break emission
        logger.debug("blocking_approx telemetry publish failed: %s", e)
    logger.info(
        "approximate blocking: %d candidate(s), %d emitted under budget %d",
        stats["candidates"], emitted, budget,
    )
    return emitted
