"""Minhash signatures over q-gram sets, as jitted fixed-shape kernels.

Each record's q-gram SET (over the approx columns, union across columns
with a per-column salt so ``"ab"`` in *name* and ``"ab"`` in *city* are
distinct set members) is sketched into ``bands * rows_per_band`` minhash
values, and each band's rows fold into one uint32 band key. Two records
share a band key for band ``b`` with probability ``J^rows_per_band`` where
``J`` is their q-gram Jaccard similarity — so across ``bands`` independent
bands the candidate probability is the classic S-curve
``1 - (1 - J^r)^b`` (ShallowBlocker, arXiv:2312.15835, uses exactly this
recall/cost dial for set-similarity blocking).

Design constraints carried over from the rest of the codebase:

  * exact gram identity — grams are the injective packed codes of
    :func:`..ops.qgram._gram_codes` (no tokenisation, no gram-level hash
    collisions; only the minhash itself is probabilistic);
  * fixed shapes, pinned dtypes — records stream through power-of-two
    bucketed chunks, all arithmetic is uint32/int32 (the forced-x64 audit
    tier traces the identical jaxpr), so steady-state signature
    computation never recompiles;
  * determinism — hash parameters derive from a FIXED seed
    (:data:`APPROX_SEED`); the same corpus yields the same band keys in
    every process, which is what makes the candidate set reproducible and
    the serve fallback index rebuildable.
"""

from __future__ import annotations

import functools

import numpy as np

from ..blocking_device import _pow2  # the ONE pow2 shape-bucketing helper

# Fixed seed for the universal-hash parameters: band keys must be
# deterministic across processes (index build vs query side, run vs rerun).
APPROX_SEED = 0x0A99B10C

# Records per signature chunk (power-of-two bucketed): bounds the transient
# (chunk, n_windows, n_hashes) uint32 intermediate to a few tens of MB.
SIG_CHUNK = 1 << 13

_U32 = np.uint32
_NO_SIG = np.uint32(0xFFFFFFFF)


def hash_params(n_hashes: int) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic per-hash multiply/add parameters: ``a`` odd (a bijection
    over Z_2^32), ``b`` arbitrary. Seeded by :data:`APPROX_SEED` only."""
    rng = np.random.default_rng(APPROX_SEED)
    a = rng.integers(0, 1 << 32, size=n_hashes, dtype=np.uint64).astype(_U32) | _U32(1)
    b = rng.integers(0, 1 << 32, size=n_hashes, dtype=np.uint64).astype(_U32)
    return a, b


def column_salts(n_cols: int) -> np.ndarray:
    """Deterministic per-column salts: the same gram in different columns
    must be a different set member (column identity is part of the key)."""
    rng = np.random.default_rng(APPROX_SEED ^ 0x5A17)
    return rng.integers(1, 1 << 32, size=n_cols, dtype=np.uint64).astype(_U32)


@functools.lru_cache(maxsize=64)
def make_minhash_fn(q: int, bands: int, rows_per_band: int, col_shapes: tuple):
    """Jitted minhash-signature + LSH-band kernel for one static column
    layout.

    ``col_shapes`` is a tuple of ``(width, kind)`` per column (``kind`` is
    ``"ascii"`` or ``"wide"`` — it fixes the bytes dtype the caller
    uploads, and with it the bits-per-char of the gram packing).

    fn(bytes_0, .., bytes_{C-1}, len_0, .., len_{C-1}, a, b, salts)
        -> (band_keys (n, bands) uint32, has_sig (n,) bool)

    Per record: every valid q-gram window of every column packs to its
    exact integer code (:func:`..ops.qgram._gram_codes`), folds through a
    salted uint32 mix, and each of the ``bands * rows_per_band`` hash
    functions takes the min over ALL columns' grams; each band's
    ``rows_per_band`` signature lanes then FNV-fold into the band key.
    ``has_sig`` is False when no column contributes a single valid window
    (null / shorter-than-q values) — such records are unreachable by the
    approx tier, exactly as a null key never joins in exact blocking.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.qgram import _gram_codes

    n_cols = len(col_shapes)
    n_hashes = bands * rows_per_band

    def record_sig(cols, lens, a, b, salts):
        sig = jnp.full((n_hashes,), _NO_SIG, jnp.uint32)
        has = jnp.zeros((), bool)
        for c in range(n_cols):
            words, valid = _gram_codes(cols[c], lens[c], q)
            # fold the gram's code words into one salted uint32 value
            h = jnp.broadcast_to(salts[c], (words.shape[0],))
            for w in range(words.shape[1]):
                h = (h ^ words[:, w]) * jnp.uint32(0x9E3779B1)
                h = h ^ (h >> 15)
            # per-hash-function value: multiply/add then a murmur-style
            # finalisation (a is odd, so h -> h*a is a bijection and the
            # min over grams is a faithful minhash of the gram set)
            hk = h[:, None] * a[None, :] + b[None, :]
            hk = hk ^ (hk >> 13)
            hk = hk * jnp.uint32(0x85EBCA6B)
            hk = hk ^ (hk >> 16)
            hk = jnp.where(valid[:, None], hk, _NO_SIG)
            sig = jnp.minimum(sig, jnp.min(hk, axis=0))
            has = has | jnp.any(valid)
        # band keys: FNV-fold the band's signature lanes + a band salt
        bk = sig.reshape(bands, rows_per_band)
        key = jnp.full((bands,), jnp.uint32(0x811C9DC5), jnp.uint32)
        for r in range(rows_per_band):
            key = (key ^ bk[:, r]) * jnp.uint32(0x01000193)
        key = key ^ (key >> 16)
        key = key ^ (
            jnp.arange(bands, dtype=jnp.int32).astype(jnp.uint32)
            * jnp.uint32(0x9E3779B1)
        )
        return key, has

    @jax.jit
    def fn(*args):
        cols = args[:n_cols]
        lens = args[n_cols : 2 * n_cols]
        a, b, salts = args[2 * n_cols :]
        return jax.vmap(
            lambda *rec: record_sig(rec[:n_cols], rec[n_cols:], a, b, salts)
        )(*cols, *lens)

    return fn


def band_key_arrays(
    columns: list[tuple[np.ndarray, np.ndarray]],
    q: int,
    bands: int,
    rows_per_band: int,
    chunk: int = SIG_CHUNK,
) -> tuple[np.ndarray, np.ndarray]:
    """Host driver: LSH band keys for every record.

    ``columns`` is a list of ``(bytes_, lengths)`` pairs — the encoded
    fixed-width representation of each approx column (null rows carry
    length 0). Records stream through power-of-two bucketed chunks of the
    jitted kernel (at most two distinct shapes per call: the full chunk
    and one padded tail), so repeated runs perform zero steady-state
    recompiles.

    Returns ``(keys (n, bands) uint32, has_sig (n,) bool)``.
    """
    import jax.numpy as jnp

    if not columns:
        raise ValueError("minhash needs at least one column")
    n = len(columns[0][1])
    col_shapes = tuple(
        (int(b.shape[1]), "ascii" if b.dtype == np.uint8 else "wide")
        for b, _ in columns
    )
    fn = make_minhash_fn(q, bands, rows_per_band, col_shapes)
    a, b_par = hash_params(bands * rows_per_band)
    salts = column_salts(len(columns))
    a_dev = jnp.asarray(a)
    b_dev = jnp.asarray(b_par)
    s_dev = jnp.asarray(salts)
    keys = np.empty((n, bands), _U32)
    has = np.empty(n, bool)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        m = _pow2(max(e - s, 1))
        args = []
        for bytes_, _ in columns:
            buf = np.zeros((m, bytes_.shape[1]), bytes_.dtype)
            buf[: e - s] = bytes_[s:e]
            args.append(jnp.asarray(buf))
        for _, lengths in columns:
            lbuf = np.zeros(m, np.int32)
            lbuf[: e - s] = lengths[s:e]
            args.append(jnp.asarray(lbuf))
        k, h = fn(*args, a_dev, b_dev, s_dev)
        keys[s:e] = np.asarray(k)[: e - s]
        has[s:e] = np.asarray(h)[: e - s]
    return keys, has


def factorise_band_codes(
    keys: np.ndarray, has_sig: np.ndarray
) -> tuple[np.ndarray, list[np.ndarray]]:
    """Factorise per-band uint32 keys into dense int32 codes for the
    segmented-sort join: ``(codes (bands, n) int32, uniq_keys per band)``.
    Code ``-1`` marks records without a signature (never join). The unique
    key arrays are ascending, so code order == ascending band-key order —
    the property the serve bucket dictionaries rely on."""
    n, bands = keys.shape
    codes = np.full((bands, n), -1, np.int32)
    uniqs: list[np.ndarray] = []
    valid = np.flatnonzero(has_sig)
    for b in range(bands):
        if len(valid):
            uniq, inv = np.unique(keys[valid, b], return_inverse=True)
            codes[b, valid] = inv.astype(np.int32)
        else:
            uniq = np.zeros(0, _U32)
        uniqs.append(uniq)
    return codes, uniqs
