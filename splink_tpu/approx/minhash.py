"""Minhash signatures over q-gram sets, as jitted fixed-shape kernels.

Each record's q-gram SET (over the approx columns, union across columns
with a per-column salt so ``"ab"`` in *name* and ``"ab"`` in *city* are
distinct set members) is sketched into ``bands * rows_per_band`` minhash
values, and each band's rows fold into one uint32 band key. Two records
share a band key for band ``b`` with probability ``J^rows_per_band`` where
``J`` is their q-gram Jaccard similarity — so across ``bands`` independent
bands the candidate probability is the classic S-curve
``1 - (1 - J^r)^b`` (ShallowBlocker, arXiv:2312.15835, uses exactly this
recall/cost dial for set-similarity blocking).

Design constraints carried over from the rest of the codebase:

  * exact gram identity — grams are the injective packed codes of
    :func:`..ops.qgram._gram_codes` (no tokenisation, no gram-level hash
    collisions; only the minhash itself is probabilistic);
  * fixed shapes, pinned dtypes — records stream through power-of-two
    bucketed chunks, all arithmetic is uint32/int32 (the forced-x64 audit
    tier traces the identical jaxpr), so steady-state signature
    computation never recompiles;
  * determinism — hash parameters derive from a FIXED seed
    (:data:`APPROX_SEED`); the same corpus yields the same band keys in
    every process, which is what makes the candidate set reproducible and
    the serve fallback index rebuildable.
"""

from __future__ import annotations

import functools

import numpy as np

from ..blocking_device import _pow2  # the ONE pow2 shape-bucketing helper

# Fixed seed for the universal-hash parameters: band keys must be
# deterministic across processes (index build vs query side, run vs rerun).
APPROX_SEED = 0x0A99B10C

# Records per signature chunk (power-of-two bucketed): bounds the transient
# (chunk, n_windows, n_hashes) uint32 intermediate to a few tens of MB.
SIG_CHUNK = 1 << 13

# Hashed gram document-frequency sketch: grams histogram into
# 2^DF_TABLE_BITS buckets by the top bits of their salted fold hash. An
# occurrence-count approximation (bucket collisions and within-record
# repeats both inflate a bucket), good enough for IDF *weighting* — the
# signal is orders-of-magnitude rarity, not exact counts.
DF_TABLE_BITS = 16
DF_TABLE_SIZE = 1 << DF_TABLE_BITS

# IDF floor: even the most common gram keeps a positive sampling weight
# (a zero weight would delete it from the weighted-Jaccard universe).
IDF_MIN = np.float32(0.05)

_U32 = np.uint32
_NO_SIG = np.uint32(0xFFFFFFFF)


def _fold_gram_hash(words, salt):
    """Salted uint32 fold of a gram's packed code words — the ONE gram
    identity hash shared by the minhash kernel, the DF-sketch kernel and
    the TF-weighted verify kernel (their IDF lookups must address the
    same buckets)."""
    import jax.numpy as jnp

    h = jnp.broadcast_to(salt, (words.shape[0],))
    for w in range(words.shape[1]):
        h = (h ^ words[:, w]) * jnp.uint32(0x9E3779B1)
        h = h ^ (h >> 15)
    return h


def hash_params(n_hashes: int) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic per-hash multiply/add parameters: ``a`` odd (a bijection
    over Z_2^32), ``b`` arbitrary. Seeded by :data:`APPROX_SEED` only."""
    rng = np.random.default_rng(APPROX_SEED)
    a = rng.integers(0, 1 << 32, size=n_hashes, dtype=np.uint64).astype(_U32) | _U32(1)
    b = rng.integers(0, 1 << 32, size=n_hashes, dtype=np.uint64).astype(_U32)
    return a, b


def column_salts(n_cols: int) -> np.ndarray:
    """Deterministic per-column salts: the same gram in different columns
    must be a different set member (column identity is part of the key)."""
    rng = np.random.default_rng(APPROX_SEED ^ 0x5A17)
    return rng.integers(1, 1 << 32, size=n_cols, dtype=np.uint64).astype(_U32)


@functools.lru_cache(maxsize=64)
def make_minhash_fn(q: int, bands: int, rows_per_band: int, col_shapes: tuple,
                    weighted: bool = False):
    """Jitted minhash-signature + LSH-band kernel for one static column
    layout.

    ``col_shapes`` is a tuple of ``(width, kind)`` per column (``kind`` is
    ``"ascii"`` or ``"wide"`` — it fixes the bytes dtype the caller
    uploads, and with it the bits-per-char of the gram packing).

    fn(bytes_0, .., bytes_{C-1}, len_0, .., len_{C-1}, a, b, salts[, idf])
        -> (band_keys (n, bands) uint32, has_sig (n,) bool)

    Per record: every valid q-gram window of every column packs to its
    exact integer code (:func:`..ops.qgram._gram_codes`), folds through a
    salted uint32 mix, and each of the ``bands * rows_per_band`` hash
    functions takes the min over ALL columns' grams; each band's
    ``rows_per_band`` signature lanes then FNV-fold into the band key.
    ``has_sig`` is False when no column contributes a single valid window
    (null / shorter-than-q values) — such records are unreachable by the
    approx tier, exactly as a null key never joins in exact blocking.

    ``weighted=True`` is the TF-weighted sampler (approx_tf_weighting):
    each gram draws an exponential race value ``-log(u) / w`` where ``u``
    derives from the gram's per-hash uniform hash and ``w`` is its IDF
    weight (``idf`` gathered at the gram hash's top
    :data:`DF_TABLE_BITS` bits — the one extra gather), and the signature
    lane takes the WINNING GRAM'S identity hash. Two records agree on a
    lane with probability equal to their IDF-weighted Jaccard (the
    exponential-race construction): rare grams — the ones that identify a
    record — win proportionally more lanes, the ShallowBlocker
    rarity-weighting (arXiv:2312.15835). ``weighted=False`` traces the
    EXACT kernel previous rounds shipped, bit for bit.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.qgram import _gram_codes

    n_cols = len(col_shapes)
    n_hashes = bands * rows_per_band

    def record_sig(cols, lens, a, b, salts, idf):
        sig = jnp.full((n_hashes,), _NO_SIG, jnp.uint32)
        best_e = (
            jnp.full((n_hashes,), jnp.float32(np.inf), jnp.float32)
            if weighted
            else None
        )
        has = jnp.zeros((), bool)
        for c in range(n_cols):
            words, valid = _gram_codes(cols[c], lens[c], q)
            # fold the gram's code words into one salted uint32 value
            h = _fold_gram_hash(words, salts[c])
            # per-hash-function value: multiply/add then a murmur-style
            # finalisation (a is odd, so h -> h*a is a bijection and the
            # min over grams is a faithful minhash of the gram set)
            hk = h[:, None] * a[None, :] + b[None, :]
            hk = hk ^ (hk >> 13)
            hk = hk * jnp.uint32(0x85EBCA6B)
            hk = hk ^ (hk >> 16)
            if not weighted:
                hk = jnp.where(valid[:, None], hk, _NO_SIG)
                sig = jnp.minimum(sig, jnp.min(hk, axis=0))
            else:
                # exponential race: e = -log(u) / w, u in (0, 1) from the
                # per-hash uniform, w the gram's IDF — min over grams
                # samples gram g with probability w_g / sum(w); the lane
                # carries the WINNER'S identity so two records agree iff
                # the same gram wins in both
                w = idf[(h >> jnp.uint32(32 - DF_TABLE_BITS)).astype(
                    jnp.int32
                )]
                u = (hk.astype(jnp.float32) + jnp.float32(0.5)) * jnp.float32(
                    2.0 ** -32
                )
                e = -jnp.log(u) / w[:, None]
                e = jnp.where(valid[:, None], e, jnp.float32(np.inf))
                col_min = jnp.min(e, axis=0)  # (n_hashes,)
                col_id = jnp.min(
                    jnp.where(
                        (e == col_min[None, :]) & valid[:, None],
                        h[:, None],
                        _NO_SIG,
                    ),
                    axis=0,
                )
                take = (col_min < best_e) | (
                    (col_min == best_e) & (col_id < sig)
                )
                best_e = jnp.where(take, col_min, best_e)
                sig = jnp.where(take, col_id, sig)
            has = has | jnp.any(valid)
        # band keys: FNV-fold the band's signature lanes + a band salt
        bk = sig.reshape(bands, rows_per_band)
        key = jnp.full((bands,), jnp.uint32(0x811C9DC5), jnp.uint32)
        for r in range(rows_per_band):
            key = (key ^ bk[:, r]) * jnp.uint32(0x01000193)
        key = key ^ (key >> 16)
        key = key ^ (
            jnp.arange(bands, dtype=jnp.int32).astype(jnp.uint32)
            * jnp.uint32(0x9E3779B1)
        )
        return key, has

    @jax.jit
    def fn(*args):
        cols = args[:n_cols]
        lens = args[n_cols : 2 * n_cols]
        if weighted:
            a, b, salts, idf = args[2 * n_cols :]
        else:
            a, b, salts = args[2 * n_cols :]
            idf = None
        return jax.vmap(
            lambda *rec: record_sig(
                rec[:n_cols], rec[n_cols:], a, b, salts, idf
            )
        )(*cols, *lens)

    return fn


@functools.lru_cache(maxsize=64)
def make_gram_df_fn(q: int, col_shapes: tuple):
    """Jitted hashed gram document-frequency accumulation for one static
    column layout: ``fn(acc, bytes.., len.., salts) -> acc`` scatter-adds
    every valid gram of every column into the (DF_TABLE_SIZE,) int32
    table at the top :data:`DF_TABLE_BITS` bits of its
    :func:`_fold_gram_hash` — the same address the weighted sampler and
    the weighted verifier gather their IDF weights from."""
    import jax
    import jax.numpy as jnp

    from ..ops.qgram import _gram_codes

    n_cols = len(col_shapes)

    @jax.jit
    def fn(acc, *args):
        cols = args[:n_cols]
        lens = args[n_cols : 2 * n_cols]
        salts = args[2 * n_cols]
        # per column: vmapped (n, windows) slot matrix, then ONE shared
        # scatter-add — never a per-record histogram (a vmapped
        # (chunk, DF_TABLE_SIZE) intermediate would be ~2 GiB per
        # dispatch for a 256 KB output)
        for c in range(n_cols):
            salt = salts[c]

            def rec_slots(s, length, salt=salt):
                words, valid = _gram_codes(s, length, q)
                h = _fold_gram_hash(words, salt)
                return jnp.where(
                    valid,
                    (h >> jnp.uint32(32 - DF_TABLE_BITS)).astype(
                        jnp.int32
                    ),
                    jnp.int32(DF_TABLE_SIZE),  # dropped by mode="drop"
                )

            slots = jax.vmap(rec_slots)(cols[c], lens[c]).reshape(-1)
            acc = acc.at[slots].add(1, mode="drop")
        return acc

    return fn


def gram_df_table(
    columns: list[tuple[np.ndarray, np.ndarray]],
    q: int,
    chunk: int = SIG_CHUNK,
) -> tuple[np.ndarray, int]:
    """(DF_TABLE_SIZE,) int64 hashed gram occurrence counts over the
    corpus plus the record count — the raw material of
    :func:`idf_weights`. Streams power-of-two bucketed chunks like
    :func:`band_key_arrays` (zero steady-state recompiles)."""
    import jax.numpy as jnp

    if not columns:
        raise ValueError("gram DF table needs at least one column")
    n = len(columns[0][1])
    col_shapes = tuple(
        (int(b.shape[1]), "ascii" if b.dtype == np.uint8 else "wide")
        for b, _ in columns
    )
    fn = make_gram_df_fn(q, col_shapes)
    s_dev = jnp.asarray(column_salts(len(columns)))
    out = np.zeros(DF_TABLE_SIZE, np.int64)
    acc = jnp.zeros(DF_TABLE_SIZE, jnp.int32)
    flushed = 0
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        m = _pow2(max(e - s, 1))
        args = []
        for bytes_, _ in columns:
            buf = np.zeros((m, bytes_.shape[1]), bytes_.dtype)
            buf[: e - s] = bytes_[s:e]
            args.append(jnp.asarray(buf))
        for _, lengths in columns:
            lbuf = np.zeros(m, np.int32)
            lbuf[: e - s] = lengths[s:e]
            args.append(jnp.asarray(lbuf))
        acc = fn(acc, *args, s_dev)
        flushed += m
        if flushed >= (1 << 22):  # int32 headroom: flush to host int64
            out += np.asarray(acc, np.int64)
            acc = jnp.zeros(DF_TABLE_SIZE, jnp.int32)
            flushed = 0
    out += np.asarray(acc, np.int64)
    return out, n


def idf_weights(df_counts: np.ndarray, n_records: int) -> np.ndarray:
    """(DF_TABLE_SIZE,) float32 IDF weights from the hashed DF sketch:
    ``max(log((n + 1) / (df + 1)), IDF_MIN)`` — strictly positive (every
    gram stays in the weighted universe), monotone in rarity, computed
    ONCE host-side so index build and serve-side query signatures gather
    identical weights."""
    df = np.asarray(df_counts, np.float64)
    w = np.log((float(n_records) + 1.0) / (df + 1.0))
    return np.maximum(w, float(IDF_MIN)).astype(np.float32)


def band_key_arrays(
    columns: list[tuple[np.ndarray, np.ndarray]],
    q: int,
    bands: int,
    rows_per_band: int,
    chunk: int = SIG_CHUNK,
    idf: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Host driver: LSH band keys for every record.

    ``columns`` is a list of ``(bytes_, lengths)`` pairs — the encoded
    fixed-width representation of each approx column (null rows carry
    length 0). Records stream through power-of-two bucketed chunks of the
    jitted kernel (at most two distinct shapes per call: the full chunk
    and one padded tail), so repeated runs perform zero steady-state
    recompiles.

    ``idf`` (the :func:`idf_weights` table) selects the TF-weighted
    sampler — the caller passes the SAME table on the index-build and
    query sides so their band keys agree for shared values.

    Returns ``(keys (n, bands) uint32, has_sig (n,) bool)``.
    """
    import jax.numpy as jnp

    if not columns:
        raise ValueError("minhash needs at least one column")
    n = len(columns[0][1])
    col_shapes = tuple(
        (int(b.shape[1]), "ascii" if b.dtype == np.uint8 else "wide")
        for b, _ in columns
    )
    fn = make_minhash_fn(
        q, bands, rows_per_band, col_shapes, weighted=idf is not None
    )
    a, b_par = hash_params(bands * rows_per_band)
    salts = column_salts(len(columns))
    a_dev = jnp.asarray(a)
    b_dev = jnp.asarray(b_par)
    s_dev = jnp.asarray(salts)
    extra = () if idf is None else (jnp.asarray(idf, jnp.float32),)
    keys = np.empty((n, bands), _U32)
    has = np.empty(n, bool)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        m = _pow2(max(e - s, 1))
        args = []
        for bytes_, _ in columns:
            buf = np.zeros((m, bytes_.shape[1]), bytes_.dtype)
            buf[: e - s] = bytes_[s:e]
            args.append(jnp.asarray(buf))
        for _, lengths in columns:
            lbuf = np.zeros(m, np.int32)
            lbuf[: e - s] = lengths[s:e]
            args.append(jnp.asarray(lbuf))
        k, h = fn(*args, a_dev, b_dev, s_dev, *extra)
        keys[s:e] = np.asarray(k)[: e - s]
        has[s:e] = np.asarray(h)[: e - s]
    return keys, has


def factorise_band_codes(
    keys: np.ndarray, has_sig: np.ndarray
) -> tuple[np.ndarray, list[np.ndarray]]:
    """Factorise per-band uint32 keys into dense int32 codes for the
    segmented-sort join: ``(codes (bands, n) int32, uniq_keys per band)``.
    Code ``-1`` marks records without a signature (never join). The unique
    key arrays are ascending, so code order == ascending band-key order —
    the property the serve bucket dictionaries rely on."""
    n, bands = keys.shape
    codes = np.full((bands, n), -1, np.int32)
    uniqs: list[np.ndarray] = []
    valid = np.flatnonzero(has_sig)
    for b in range(bands):
        if len(valid):
            uniq, inv = np.unique(keys[valid, b], return_inverse=True)
            codes[b, valid] = inv.astype(np.int32)
        else:
            uniq = np.zeros(0, _U32)
        uniqs.append(uniq)
    return codes, uniqs
