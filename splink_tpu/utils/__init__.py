from .logging_utils import format_stage_log
from .profiling import StageTimer, stage_timings

__all__ = ["format_stage_log", "StageTimer", "stage_timings"]
