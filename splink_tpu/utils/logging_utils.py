"""Structured debug logging for pipeline stages.

The reference logs each generated SQL string at debug level
(/root/reference/splink/logging_utils.py:10). The splink_tpu analogue is to log
the *compiled artifact*: each stage can log its jaxpr / lowered HLO text plus
shapes at debug level, which serves the same "inspect exactly what will run"
purpose.
"""

from __future__ import annotations

import logging
import warnings

logger = logging.getLogger("splink_tpu")


def format_stage_log(stage: str, **info) -> str:
    parts = ", ".join(f"{k}={v}" for k, v in info.items())
    return f"[{stage}] {parts}"


class DegradationWarning(UserWarning):
    """An execution path degraded to a slower but working alternative
    (resident EM -> streamed EM, accelerator -> CPU). The job still
    completes with the same results; the warning records why it was
    slower than expected."""


def warn_degraded(from_mode: str, to_mode: str, reason: str, **info) -> None:
    """Emit the structured degradation record: one parseable log line plus
    a DegradationWarning (so tests and callers can assert on it)."""
    line = format_stage_log(
        "degrade", **{"from": from_mode, "to": to_mode, "reason": reason}, **info
    )
    logger.warning("%s", line)
    from ..obs.events import publish

    publish(
        "degradation",
        **{"from": from_mode, "to": to_mode, "reason": reason},
        **info,
    )
    warnings.warn(
        f"execution degraded from {from_mode} to {to_mode}: {reason}",
        DegradationWarning,
        stacklevel=2,
    )


def log_jaxpr(stage: str, fn, *example_args) -> None:
    """Log the jaxpr of a stage function at debug level (cheap no-op otherwise)."""
    if logger.isEnabledFor(logging.DEBUG):
        import jax

        try:
            jaxpr = jax.make_jaxpr(fn)(*example_args)
            logger.debug("[%s] jaxpr:\n%s", stage, jaxpr)
        except Exception as e:  # pragma: no cover - logging must never break the run
            logger.debug("[%s] jaxpr unavailable: %s", stage, e)
