"""Execution-environment fingerprints for compiled-artifact invalidation.

Two consumers bind compiled XLA artifacts to the machine that produced
them:

  * the AOT executable sidecar (:mod:`..serve.aot`) — a serialized
    executable is literal machine code; restoring one compiled for a
    different ISA is a SIGILL, not a slowdown, so the sidecar is rejected
    unless the full environment fingerprint matches;
  * the persistent XLA compilation cache (:func:`..linker._enable_compilation_cache`)
    — jax's own cache key covers the program and compile options but not
    the host CPU's target features, and XLA CPU compiles for the host ISA
    (``-march=native`` semantics). The linker therefore keys the cache
    directory on :func:`cpu_target_fingerprint`, which is what makes CPU-
    tier caching safe to enable (a shared cache volume mounted on
    heterogeneous machines partitions per CPU type instead of serving
    foreign code).

Everything here is stdlib-only until a fingerprint actually needs the jax
backend probe.
"""

from __future__ import annotations

import hashlib
import platform


def cpu_target_fingerprint() -> str:
    """Stable hex fingerprint of the host CPU's instruction-set surface:
    the architecture plus the feature flags the kernel reports
    (``flags`` on x86, ``Features`` on ARM). Two hosts with the same
    fingerprint can safely exchange XLA-CPU-compiled code; the flag SET is
    order-normalised so kernel-version reordering does not split the
    key."""
    parts = [platform.machine() or "unknown"]
    flags = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as fh:
            for line in fh:
                key, _, val = line.partition(":")
                if key.strip().lower() in ("flags", "features"):
                    flags = " ".join(sorted(val.split()))
                    break
    except OSError:  # non-Linux: coarser, but still arch-bound
        flags = platform.processor() or ""
    parts.append(flags)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def backend_target_fingerprint(backend: str | None = None) -> str:
    """Target fingerprint for the active jax backend: the CPU feature
    fingerprint on the CPU tier, the device kind + platform version on
    accelerators (a v4 executable must not restore on a v5 replica)."""
    import jax

    backend = backend or jax.default_backend()
    if backend == "cpu":
        return cpu_target_fingerprint()
    dev = jax.devices(backend)[0]
    kind = getattr(dev, "device_kind", backend)
    version = getattr(dev.client, "platform_version", "")
    return hashlib.sha256(f"{backend}|{kind}|{version}".encode()).hexdigest()


def environment_fingerprint() -> dict:
    """The full invalidation identity of this process's compile
    environment: jax/jaxlib versions (the serialization format owners),
    the backend, its target fingerprint, and the x64 switch (an x64
    process lowers different programs)."""
    import jax
    import jaxlib

    backend = jax.default_backend()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": backend,
        "target": backend_target_fingerprint(backend),
        "x64": bool(jax.config.jax_enable_x64),
    }
