"""Per-stage wall-clock timing and optional jax profiler trace hooks."""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

_TIMINGS: dict[str, list[float]] = defaultdict(list)


class StageTimer(contextlib.AbstractContextManager):
    """Context manager recording wall time for a named pipeline stage.

    Usage::

        with StageTimer("blocking"):
            ...
    """

    def __init__(self, stage: str, trace_dir: str | None = None):
        self.stage = stage
        self.trace_dir = trace_dir
        self._trace = None

    def __enter__(self):
        if self.trace_dir:
            import jax

            self._trace = jax.profiler.trace(self.trace_dir)
            self._trace.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        _TIMINGS[self.stage].append(self.elapsed)
        if self._trace is not None:
            self._trace.__exit__(*exc)
        return False


def stage_timings() -> dict[str, list[float]]:
    """All recorded stage timings for this process (stage -> list of seconds)."""
    return dict(_TIMINGS)


def reset_timings() -> None:
    _TIMINGS.clear()
