"""Per-stage wall-clock timing and optional jax profiler trace hooks."""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

_TIMINGS: dict[str, list[float]] = defaultdict(list)

# Process-wide profiler target (set from settings["profile_dir"] by the
# linker): device-heavy stages then capture a perfetto/tensorboard trace
# under <dir>/<stage>. One flag -> utilisation data for an EM pass, the
# analogue of inspecting a Spark UI stage timeline.
_TRACE_DIR: str | None = None
_TRACED_STAGES = {"gammas", "gammas_patterns", "em", "em_streamed"}
_TRACE_ACTIVE = False  # jax.profiler.trace cannot nest


def set_trace_dir(path: str | None) -> None:
    """Enable (or disable with None) jax profiler traces for device-heavy
    stages. Called by the linker when settings["profile_dir"] is set."""
    global _TRACE_DIR
    _TRACE_DIR = path


class StageTimer(contextlib.AbstractContextManager):
    """Context manager recording wall time for a named pipeline stage.

    Usage::

        with StageTimer("blocking"):
            ...
    """

    def __init__(self, stage: str, trace_dir: str | None = None):
        self.stage = stage
        if trace_dir is None and _TRACE_DIR and stage in _TRACED_STAGES:
            import os

            trace_dir = os.path.join(_TRACE_DIR, stage)
        self.trace_dir = trace_dir
        self._trace = None

    def __enter__(self):
        global _TRACE_ACTIVE
        if self.trace_dir and not _TRACE_ACTIVE:
            import jax

            self._trace = jax.profiler.trace(self.trace_dir)
            self._trace.__enter__()
            _TRACE_ACTIVE = True
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        global _TRACE_ACTIVE
        self.elapsed = time.perf_counter() - self._t0
        _TIMINGS[self.stage].append(self.elapsed)
        if self._trace is not None:
            self._trace.__exit__(*exc)
            _TRACE_ACTIVE = False
        return False


def stage_timings() -> dict[str, list[float]]:
    """All recorded stage timings for this process (stage -> list of seconds)."""
    return dict(_TIMINGS)


def reset_timings() -> None:
    _TIMINGS.clear()
