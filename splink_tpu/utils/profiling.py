"""Per-stage wall-clock timing and optional jax profiler trace hooks.

Timings and profiler-trace targets are keyed by RUN, not process: each
linker registers its own run scope at construction (:func:`begin_run`), so
two linkers in one process no longer interleave their stage timings or
clobber each other's ``profile_dir`` (the process-global ``_TIMINGS`` /
``_TRACE_DIR`` of earlier builds). ``stage_timings()`` keeps its historical
signature and returns the CURRENT run's timings; pass ``run=`` to read a
specific linker's (``Splink._obs.run_id``).

StageTimers constructed outside any run scope (ad-hoc profiling, tests)
land in a default scope, which behaves exactly like the old process-global
one.
"""

from __future__ import annotations

import contextlib
import os
import time

# run key -> stage -> [seconds]; "" is the default (no-linker) scope
_DEFAULT_RUN = ""
_TIMINGS: dict[str, dict[str, list[float]]] = {_DEFAULT_RUN: {}}

# Per-run profiler target (from settings["profile_dir"]): device-heavy
# stages then capture a perfetto/tensorboard trace under <dir>/<stage>. One
# flag -> utilisation data for an EM pass, the analogue of inspecting a
# Spark UI stage timeline.
_TRACE_DIRS: dict[str, str | None] = {_DEFAULT_RUN: None}
_CURRENT_RUN = _DEFAULT_RUN
_TRACED_STAGES = {"gammas", "gammas_patterns", "em", "em_streamed"}
# jax.profiler.trace cannot nest — ONE process-wide flag regardless of run
_TRACE_ACTIVE = False


# Retained run scopes are bounded: a long-lived service constructing one
# linker per request must not grow _TIMINGS forever (the per-process leak
# this module's run-scoping was built to fix). Oldest completed scopes are
# evicted FIFO past this cap; the default scope and the current run are
# never evicted.
_MAX_RETAINED_RUNS = 64


def begin_run(run_id: str, trace_dir: str | None = None) -> str:
    """Open (and make current) a run scope with fresh timings. Called by
    the linker at construction; a later linker beginning its own run leaves
    this one's timings and trace dir untouched (until it ages past the
    ``_MAX_RETAINED_RUNS`` eviction window)."""
    global _CURRENT_RUN
    _TIMINGS[run_id] = {}
    _TRACE_DIRS[run_id] = trace_dir or None
    _CURRENT_RUN = run_id
    while len(_TIMINGS) > _MAX_RETAINED_RUNS + 1:  # +1: the default scope
        oldest = next(
            (k for k in _TIMINGS if k not in (_DEFAULT_RUN, _CURRENT_RUN)),
            None,
        )
        if oldest is None:  # pragma: no cover - cap >= 1 prevents this
            break
        _TIMINGS.pop(oldest, None)
        _TRACE_DIRS.pop(oldest, None)
    return run_id


def discard_run(run_id: str) -> None:
    """Drop a run scope's recorded state (tests / long-lived processes)."""
    global _CURRENT_RUN
    if run_id == _DEFAULT_RUN:
        _TIMINGS[_DEFAULT_RUN] = {}
        _TRACE_DIRS[_DEFAULT_RUN] = None
        return
    _TIMINGS.pop(run_id, None)
    _TRACE_DIRS.pop(run_id, None)
    if _CURRENT_RUN == run_id:
        _CURRENT_RUN = _DEFAULT_RUN


def set_trace_dir(path: str | None) -> None:
    """Enable (or disable with None) jax profiler traces for device-heavy
    stages of the CURRENT run scope."""
    _TRACE_DIRS[_CURRENT_RUN] = path or None


class StageTimer(contextlib.AbstractContextManager):
    """Context manager recording wall time for a named pipeline stage.

    Usage::

        with StageTimer("blocking"):
            ...

    Args:
        stage: stage name the elapsed time is recorded under.
        trace_dir: capture a jax profiler trace of the stage here
            (overrides the run's profile_dir resolution).
        run: run scope to record into (default: the current scope).
        telemetry: optional ``obs.runtime.RunContext`` — the stage is also
            emitted as a telemetry span with its compile/execute split and
            a device-memory snapshot at the boundary.
    """

    def __init__(
        self,
        stage: str,
        trace_dir: str | None = None,
        run: str | None = None,
        telemetry=None,
    ):
        self.stage = stage
        self.run = _CURRENT_RUN if run is None else run
        self.telemetry = telemetry
        if trace_dir is None:
            run_dir = _TRACE_DIRS.get(self.run)
            if run_dir and stage in _TRACED_STAGES:
                trace_dir = os.path.join(run_dir, stage)
        self.trace_dir = trace_dir
        self._trace = None
        self._token = None

    def __enter__(self):
        global _TRACE_ACTIVE
        if self.trace_dir and not _TRACE_ACTIVE:
            import jax

            trace = jax.profiler.trace(self.trace_dir)
            trace.__enter__()
            # only mark active once the profiler actually started: a failed
            # trace.__enter__ must not leave the flag stuck True
            self._trace = trace
            _TRACE_ACTIVE = True
        if self.telemetry is not None:
            self._token = self.telemetry.stage_enter(self.stage)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        global _TRACE_ACTIVE
        self.elapsed = time.perf_counter() - self._t0
        _TIMINGS.setdefault(self.run, {}).setdefault(self.stage, []).append(
            self.elapsed
        )
        try:
            if self._trace is not None:
                trace, self._trace = self._trace, None
                try:
                    trace.__exit__(*exc)
                finally:
                    # exception-safe: a raising profiler exit must still
                    # release the process-wide flag or no later stage could
                    # ever trace again
                    _TRACE_ACTIVE = False
        finally:
            if self.telemetry is not None:
                self.telemetry.stage_exit(
                    self._token, self.stage, self.elapsed,
                    failed=exc[0] is not None,
                )
        return False


def stage_timings(run: str | None = None) -> dict[str, list[float]]:
    """Recorded stage timings (stage -> list of seconds) for the current
    run scope, or for ``run`` when given."""
    key = _CURRENT_RUN if run is None else run
    return {k: list(v) for k, v in _TIMINGS.get(key, {}).items()}


def reset_timings(run: str | None = None) -> None:
    key = _CURRENT_RUN if run is None else run
    _TIMINGS[key] = {}
