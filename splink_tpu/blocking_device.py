"""Device-native blocking: on-device hash-join candidate generation.

blocking.py's host joins were the last pipeline stage computed entirely on
the host — np.argsort over every rule's key codes, np.repeat/np.cumsum pair
expansion, 8.2M pairs/s single-threaded while the chip scores 28M+/s
(BENCHMARKS.md). This module moves the join itself onto the device as a
sort-based hash join over the SAME packed key codes blocking.py builds
(HyperBlocker, arXiv:2410.04349, maps rule-based blocking onto exactly this
kind of accelerator parallelism):

  1. segmented sort — one ``lax.sort`` of ``(key_code, side, rank)``
     carrying row ids: equal keys become contiguous segments, group members
     arrive pre-sorted by uid rank (orientation comes out of the join for
     free, `_self_join`'s trick), and the two sides of a link / asymmetric
     join interleave as (code, side) runs;
  2. run-length segment detection — boundary flags + a pinned int32 cumsum
     give each position its segment id; per-segment starts and per-side
     extents compact through scatter-min/scatter-add. Only this compact
     O(segments) table crosses back to the host;
  3. pair expansion — the host splits segments into the SAME bounded
     triangle/rectangle units as the virtual pair index (pairgen's f32-exact
     decode, reused verbatim via ``pairgen.unit_decode``) and the emission
     kernel decodes each chunk of global pair positions into (i, j) row
     pairs ON DEVICE, applies the sequential-rule dedup mask (earlier-rule
     key equality + compiled residuals — the reference's ``AND NOT
     ifnull(prev, false)`` — mirroring pairgen's mask semantics), the
     duplicate-uid mask and the asymmetric-rule rank orientation filter,
     then compacts survivors with an int32 rank-scatter;
  4. chunked emission under an explicit pair budget
     (``blocking_chunk_pairs``) — a huge block streams as fixed-shape
     chunks instead of OOMing, the Progressive-Blocking shape
     (arXiv:2005.14326) of emitting candidates under a budget rather than
     all-at-once. Chunk shapes are power-of-two stable, so steady-state
     emission never recompiles.

The host path in blocking.py is retained as the fallback (cartesian rules,
residuals the device compiler rejects, degenerate near-constant keys,
>=2^31 key codes) and as the parity oracle: the device pair set is
bit-equal AS A SET to the host pair set on every supported shape
(tests/test_blocking_device.py; ``make blocking-smoke`` gates it).

serve/index.py reuses the segmented sort through :func:`build_bucket_csr`
to build its per-rule bucket CSR (rows_sorted/starts/sizes/row_bucket) on
device instead of the host argsort.

All kernels are registered in the three audit layers: jaxlint (AST),
trace_audit (``block_segment_sort``, ``block_bucket_csr``,
``block_pair_emit`` — x64-forced dtype/const/callback/determinism budgets)
and shard_audit (``block_pair_decode_sharded`` — the decode+mask body is
embarrassingly parallel over positions and lowers collective-free with
sharded outputs; the compaction cumsum is single-device by design, the
host compacts per shard).
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass, field

import numpy as np

from .blocking import (
    _key_codes,
    _key_codes_asym,
    _split_join_keys,
    _uid_ranks,
    parse_blocking_rule,
)
from .data import EncodedTable
from .pairgen import (
    CHUNK,
    _pair_counts,
    _uid_mask_codes,
    _unit_batch_meta,
    _units_for_cross_join,
    _units_for_self_join,
    compile_residual_device,
    unit_decode,
)

logger = logging.getLogger("splink_tpu")

# Default emission chunk (pairs per device batch) when the settings carry no
# blocking_chunk_pairs; also the schema default. Bounds the transient device
# footprint of one chunk (~9 int32 lanes x chunk) and the host RAM of one
# downloaded chunk.
DEFAULT_CHUNK_PAIRS = 1 << 22

# "auto" mode engages the device tier only past this estimated pair count:
# below it the host join finishes in milliseconds and the jit warmup would
# dominate (the same shape as device_pair_generation's auto gate).
AUTO_MIN_PAIRS = 1 << 21

# Concurrent chunk downloads in flight (pairgen._D2H_DEPTH rationale: D2H
# round trips overlap the next chunk's kernel instead of serialising it).
_D2H_DEPTH = 2

_IMAX = np.iinfo(np.int32).max


def _pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the shape-bucketing that keeps
    jit specialisations shared across tables of similar size."""
    return 1 << max(int(n) - 1, 0).bit_length()


# --------------------------------------------------------------------------
# Kernels
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def make_segment_sort_fn():
    """Jitted segmented sort + run-length segment detection.

    fn(codes, side, rank, row) ->
        (rows_sorted, seg_start, l_cnt, r_cnt, n_seg, n_valid)

    Entries sort by (key, side, rank) with null keys (code < 0 — including
    the power-of-two padding) remapped to int32 max so they collapse into
    one trailing segment the host drops (``seg_start >= n_valid``). Segment
    starts compact via scatter-min over the per-position segment id,
    per-side extents via scatter-add — all shapes static, all dtypes pinned
    int32 (the TPU production width; x64 audit tier traces identically).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def fn(codes, side, rank, row):
        m = codes.shape[0]
        imax = jnp.int32(_IMAX)
        key = jnp.where(codes < 0, imax, codes)
        key_s, side_s, _, row_s = lax.sort(
            (key, side, rank, row), num_keys=3, is_stable=True
        )
        n_valid = jnp.sum((codes >= 0).astype(jnp.int32), dtype=jnp.int32)
        pos = jnp.arange(m, dtype=jnp.int32)
        boundary = jnp.concatenate(
            [jnp.ones((1,), bool), key_s[1:] != key_s[:-1]]
        )
        seg_of = jnp.cumsum(boundary.astype(jnp.int32), dtype=jnp.int32) - 1
        # n_seg as a reduction, NOT seg_of[-1]: a traced negative index
        # lowers through an int64 dynamic_slice under x64 (TA-DTYPE)
        n_seg = jnp.sum(boundary.astype(jnp.int32), dtype=jnp.int32)
        seg_start = jnp.full(m, imax, jnp.int32).at[seg_of].min(pos)
        l_cnt = (
            jnp.zeros(m, jnp.int32)
            .at[seg_of]
            .add((side_s == 0).astype(jnp.int32))
        )
        r_cnt = (
            jnp.zeros(m, jnp.int32)
            .at[seg_of]
            .add((side_s == 1).astype(jnp.int32))
        )
        return row_s, seg_start, l_cnt, r_cnt, n_seg, n_valid

    return fn


@functools.lru_cache(maxsize=1)
def make_bucket_csr_fn():
    """Jitted bucket-CSR build for the serving index: fn(codes) ->
    (rows_sorted, starts, sizes, row_bucket, n_seg, n_valid), bit-equal to
    the host ``blocking._sort_groups`` construction (stable sort keeps rows
    ascending within a bucket; buckets ordered by ascending key code).
    row_bucket is -1 for null-key rows, exactly the serving contract."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def fn(codes):
        m = codes.shape[0]
        imax = jnp.int32(_IMAX)
        key = jnp.where(codes < 0, imax, codes)
        rows = jnp.arange(m, dtype=jnp.int32)
        key_s, row_s = lax.sort((key, rows), num_keys=1, is_stable=True)
        n_valid = jnp.sum((codes >= 0).astype(jnp.int32), dtype=jnp.int32)
        boundary = jnp.concatenate(
            [jnp.ones((1,), bool), key_s[1:] != key_s[:-1]]
        )
        seg_of = jnp.cumsum(boundary.astype(jnp.int32), dtype=jnp.int32) - 1
        # reduction, not seg_of[-1] (int64 dynamic_slice under x64)
        n_seg = jnp.sum(boundary.astype(jnp.int32), dtype=jnp.int32)
        starts = jnp.full(m, imax, jnp.int32).at[seg_of].min(rows)
        sizes = jnp.zeros(m, jnp.int32).at[seg_of].add(jnp.int32(1))
        valid_entry = rows < n_valid
        dest = jnp.where(valid_entry, row_s, m)
        row_bucket = (
            jnp.full(m, -1, jnp.int32).at[dest].set(seg_of, mode="drop")
        )
        return row_s, starts, sizes, row_bucket, n_seg, n_valid

    return fn


def make_pair_emit_fn(batch_size: int, n_prev: int, has_uid_mask: bool,
                      rank_filter: bool, own_res=None, prev_res=(),
                      mesh=None, compact: bool = True):
    """Jitted emission kernel: decode one chunk of global pair positions
    into (i, j) row pairs and compact the survivors.

    Composes pairgen's ``unit_decode`` (the same f32-exact
    triangle/rectangle math the virtual pattern kernel runs), then masks:
    tail padding (``pos >= valid``), the asymmetric-rule rank orientation
    filter (``rank[i] < rank[j]`` — the reference's l.key < r.key on a
    cross join of the table against itself), the duplicate-uid drop, the
    rule's own residual and every EARLIER rule's predicate (key equality on
    that rule's l/r codes AND its residual, UNKNOWN counting as
    not-produced — blocking._rule_holds semantics). Survivors compact via
    an int32 rank-scatter (cumsum of the keep mask), so the host downloads
    ``count`` real pairs in the first ``count`` lanes.

    With ``mesh`` the kernel returns the UNCOMPACTED (i, j, keep) triple
    sharded along the position axis — compaction is a prefix sum, which
    would force cross-shard comms; each shard's survivors compact host-side
    instead. The sharded body is collective-free (shard_audit pins it).

    ``compact=False`` returns the same uncompacted triple on a single
    device: XLA's CPU scatter lowering is a serial loop (measured ~4x the
    whole decode for a 4M chunk), so the CPU-backend driver compacts
    host-side with vectorised numpy instead — on accelerator backends the
    on-device compaction stands, because there the scarce resource is D2H
    bytes over the (tunnelled) link, and compaction halves them.
    """
    import jax
    import jax.numpy as jnp

    jit_kwargs = {}
    if mesh is not None:
        from .parallel.mesh import pair_sharding

        shard = pair_sharding(mesh)
        jit_kwargs = {"out_shardings": (shard, shard, shard)}

    # a kernel with NO mask terms needs no keep vector at all: the only
    # dropped positions are the tail past `valid`, and the DRIVER knows
    # valid (it built the meta row) — it slices the download instead. This
    # skips the keep compute, its D2H and the host compress for every
    # maskless rule (typically the first, largest rule of a run).
    maskless = (
        mesh is None
        and n_prev == 0
        and not has_uid_mask
        and not rank_filter
        and own_res is None
    )

    @functools.partial(jax.jit, **jit_kwargs)
    def fn(pos, order, ua, la, ub, lb, ranks, prev_l, prev_r, uid_codes,
           res_ops, meta):
        i, j, valid = unit_decode(
            pos, order, ua, la, ub, lb, meta, mesh_ladder=mesh is not None
        )
        if maskless:
            return i, j, None
        keep = pos < valid
        if rank_filter:
            keep = keep & (ranks[i] < ranks[j])
        if has_uid_mask:
            keep = keep & (uid_codes[i] != uid_codes[j])
        if own_res is not None:
            v, unk = own_res(i, j, res_ops)
            keep = keep & v & ~unk
        for p in range(n_prev):
            cl = prev_l[p]
            cr = prev_r[p]
            holds = (cl[i] == cr[j]) & (cl[i] >= 0)
            if prev_res and prev_res[p] is not None:
                v, unk = prev_res[p](i, j, res_ops)
                holds = holds & v & ~unk
            keep = keep & ~holds
        if mesh is not None or not compact:
            return i, j, keep
        kcum = jnp.cumsum(keep.astype(jnp.int32), dtype=jnp.int32)
        dest = jnp.where(keep, kcum - 1, jnp.int32(batch_size))
        out_i = jnp.zeros(batch_size, jnp.int32).at[dest].set(i, mode="drop")
        out_j = jnp.zeros(batch_size, jnp.int32).at[dest].set(j, mode="drop")
        # count rides as the last lane of a (batch_size + 1,) array so one
        # download carries pairs AND count (the tunnelled-link round trip
        # costs more than the lane)
        out_i = jnp.concatenate([out_i, kcum[-1:]])
        return out_i, out_j, keep

    return fn


# --------------------------------------------------------------------------
# Plan build (host: key codes -> device sort -> bounded units)
# --------------------------------------------------------------------------


@dataclass
class DeviceRule:
    """One rule's device join structure."""

    rule: str
    order: np.ndarray  # (M,) int32 pow2-padded sorted entry rows
    ua: np.ndarray  # (U,) int32 unit a-side start into `order`
    la: np.ndarray  # (U,) int32 a-side extent (<= chunk)
    ub: np.ndarray  # (U,) int32 b-side start (== ua for triangles)
    lb: np.ndarray  # (U,) int32 b-side extent
    pc: np.ndarray  # (U+1,) int64 cumulative pair counts
    rank_filter: bool  # asymmetric self-join: keep rank[i] < rank[j]
    residual: str | None = None
    residual_fn: object = None

    @property
    def total(self) -> int:
        return int(self.pc[-1]) if len(self.pc) else 0


@dataclass
class DeviceBlockPlan:
    rules: list[DeviceRule]
    codes_l: np.ndarray  # (R, n) int32 per-rule l-side codes (dedup mask)
    codes_r: np.ndarray  # (R, n) int32 r-side codes (== l row when symmetric)
    ranks: np.ndarray  # (n,) int32 uid ranks (zeros for link_only)
    uid_codes: np.ndarray | None  # (n,) int32 when duplicate uids exist
    res_ops: list[np.ndarray] = field(default_factory=list)
    chunk: int = CHUNK  # unit extent bound (int32/f32-exactness margin)
    # jitted emission kernels keyed by (rule, batch, mesh): reusing the
    # closure is what makes a warmup emission actually warm the next one
    kernel_cache: dict = field(default_factory=dict)

    @property
    def n_candidates(self) -> int:
        return sum(rp.total for rp in self.rules)


def build_device_plan(
    settings: dict, table: EncodedTable, n_left: int | None = None,
    chunk: int | None = None,
) -> DeviceBlockPlan | None:
    """Build the device join plan, or None when a rule needs the host path
    (cartesian, an uncompilable residual, >=2^31 key codes, or a
    near-constant key exceeding the per-group unit cap)."""
    chunk = chunk or CHUNK
    link_type = settings["link_type"]
    rules = settings.get("blocking_rules") or []
    if not rules or table.n_rows == 0:
        return None
    n = table.n_rows
    if link_type == "link_only":
        assert n_left is not None
        ranks = np.zeros(n, np.int32)  # orientation fixed by construction
        uid_codes = None
    else:
        ranks, _ = _uid_ranks(table, link_type)
        uid_codes = _uid_mask_codes(table, link_type)

    res_ops: list[np.ndarray] = []
    res_idx: dict = {}
    res_aux: dict = {}
    parsed = []
    for rule in rules:
        eq_pairs, residual = parse_blocking_rule(rule)
        sym, asym, residual = _split_join_keys(eq_pairs, residual)
        if not sym and not asym:
            return None  # cartesian rule: host path (with its warning)
        if asym:
            codes_l, codes_r = _key_codes_asym(table, sym, asym)
        else:
            codes_l = codes_r = _key_codes(table, sym)
        if len(codes_l) and (
            int(codes_l.max()) >= _IMAX or int(codes_r.max()) >= _IMAX
        ):
            return None  # codes must fit the int32 device lanes
        res_fn = None
        if residual is not None:
            res_fn = compile_residual_device(
                table, residual, res_ops, res_idx, res_aux
            )
            if res_fn is None:
                return None
        parsed.append((codes_l, codes_r, bool(asym), residual, res_fn))
    if res_aux.get("numeric_used"):
        import jax

        if not jax.config.jax_enable_x64:
            logger.warning(
                "device blocking: a blocking residual contains numeric "
                "arithmetic, which evaluates in float32 on TPU (no f64) — "
                "a pair exactly on a threshold may land differently than "
                "the float64 host path. Set device_blocking='off' for "
                "bit-identical host blocking."
            )

    sort_fn = make_segment_sort_fn()
    all_rows = np.arange(n, dtype=np.int32)
    plans: list[DeviceRule] = []
    codes_l_all = np.empty((len(rules), n), np.int32)
    codes_r_all = np.empty((len(rules), n), np.int32)
    for r, (codes_l, codes_r, is_asym, residual, res_fn) in enumerate(parsed):
        codes_l_all[r] = codes_l.astype(np.int32)
        codes_r_all[r] = codes_r.astype(np.int32)
        rank_filter = False
        if link_type == "link_only":
            # left input rows read the l-side codes, right rows the r-side
            # (identical arrays for a symmetric key); rectangles by
            # construction keep the left input on the l side
            ent_codes = np.concatenate(
                [codes_l_all[r][:n_left], codes_r_all[r][n_left:]]
            )
            ent_side = np.zeros(n, np.int32)
            ent_side[n_left:] = 1
            ent_rank = np.zeros(n, np.int32)
            ent_rows = all_rows
            triangle = False
        elif is_asym:
            # f(l) = g(r) over one table: every row enters once per side;
            # the reference's cross join of the table against itself with
            # the l.key < r.key where-condition — here the rank filter mask
            ent_codes = np.concatenate([codes_l_all[r], codes_r_all[r]])
            ent_side = np.concatenate(
                [np.zeros(n, np.int32), np.ones(n, np.int32)]
            )
            ent_rank = np.concatenate([ranks, ranks]).astype(np.int32)
            ent_rows = np.concatenate([all_rows, all_rows])
            triangle = False
            rank_filter = True
        else:
            # symmetric self-join: rank is the sort's tertiary key, so the
            # triangle decode's a < b IS rank_i < rank_j (ranks are a
            # permutation — duplicates only among uid COLLISIONS, which the
            # uid mask drops)
            ent_codes = codes_l_all[r]
            ent_side = np.zeros(n, np.int32)
            ent_rank = ranks.astype(np.int32)
            ent_rows = all_rows
            triangle = True
        m0 = len(ent_codes)
        m = _pow2(m0)
        if m != m0:  # pad with null keys: they join the dropped segment
            pad = m - m0
            ent_codes = np.concatenate(
                [ent_codes, np.full(pad, -1, np.int32)]
            )
            ent_side = np.concatenate([ent_side, np.zeros(pad, np.int32)])
            ent_rank = np.concatenate([ent_rank, np.zeros(pad, np.int32)])
            ent_rows = np.concatenate([ent_rows, np.zeros(pad, np.int32)])
        row_s, seg_start, l_cnt, r_cnt, n_seg, n_valid = sort_fn(
            ent_codes, ent_side, ent_rank, ent_rows
        )
        order = np.asarray(row_s)
        seg_start = np.asarray(seg_start)
        l_cnt = np.asarray(l_cnt)
        r_cnt = np.asarray(r_cnt)
        n_seg_h = int(np.asarray(n_seg))
        n_valid_h = int(np.asarray(n_valid))
        starts = seg_start[:n_seg_h].astype(np.int64)
        lz = l_cnt[:n_seg_h].astype(np.int64)
        rz = r_cnt[:n_seg_h].astype(np.int64)
        live = starts < n_valid_h  # drop the trailing null/pad segment
        starts, lz, rz = starts[live], lz[live], rz[live]
        if triangle:
            units = _units_for_self_join(starts, lz, chunk)
        else:
            both = (lz > 0) & (rz > 0)
            units = _units_for_cross_join(
                starts[both], lz[both], starts[both] + lz[both], rz[both],
                chunk,
            )
        if units is None:
            return None  # monster group: host blocking is the right tool
        ua, la, ub, lb = units
        plans.append(
            DeviceRule(
                rule=rules[r],
                order=np.ascontiguousarray(order, dtype=np.int32),
                ua=ua.astype(np.int32),
                la=la.astype(np.int32),
                ub=ub.astype(np.int32),
                lb=lb.astype(np.int32),
                pc=_pair_counts(ua, la, ub, lb),
                rank_filter=rank_filter,
                residual=residual,
                residual_fn=res_fn,
            )
        )
    return DeviceBlockPlan(
        rules=plans,
        codes_l=codes_l_all,
        codes_r=codes_r_all,
        ranks=np.ascontiguousarray(ranks, dtype=np.int32),
        uid_codes=uid_codes,
        res_ops=res_ops,
        chunk=chunk,
    )


# --------------------------------------------------------------------------
# Chunked emission
# --------------------------------------------------------------------------


def _emission_context(plan: DeviceBlockPlan, batch_size: int, mesh):
    """Shared device setup for the TWO emission drivers
    (:func:`iter_device_pairs` — streaming — and
    :func:`emit_pairs_sharded` — the spill write path): the int32-safe
    batch clamp, mesh padding, the replicated put, the
    compaction-placement decision and the plan-constant uploads. One
    implementation, because the drivers are documented pair-set twins and
    a one-sided change to any of these invariants would silently diverge
    them."""
    import jax
    import jax.numpy as jnp

    # int32-safe bound, same margin as pairgen: batch-relative pc entries
    # can overshoot the batch end by up to one unit's pair count
    safe = (1 << 31) - 1 - plan.chunk * plan.chunk
    batch_size = min(max(int(batch_size), 64), safe)
    shard = None
    if mesh is not None:
        from .parallel.mesh import (
            pad_to_multiple,
            pair_sharding,
            replicated,
        )

        msz = mesh.devices.size
        batch_size = pad_to_multiple(batch_size, msz)
        if batch_size > safe:
            batch_size = max(safe // msz, 1) * msz
        shard = pair_sharding(mesh)
        repl = replicated(mesh)
        put = lambda a: jax.device_put(jnp.asarray(a), repl)  # noqa: E731
    else:
        put = jnp.asarray
    # on-device compaction only where it pays: it saves D2H bytes on
    # accelerator links but runs as a serial scatter loop on the XLA CPU
    # backend (make_pair_emit_fn docstring) — there the host compacts
    compact_dev = mesh is None and jax.default_backend() != "cpu"
    return {
        "batch_size": batch_size,
        "put": put,
        "shard": shard,
        "compact_dev": compact_dev,
        "ranks": put(plan.ranks),
        "codes_l": put(
            plan.codes_l if len(plan.codes_l) else np.zeros((1, 1), np.int32)
        ),
        "codes_r": put(
            plan.codes_r if len(plan.codes_r) else np.zeros((1, 1), np.int32)
        ),
        "uid": put(
            plan.uid_codes if plan.uid_codes is not None
            else np.zeros(1, np.int32)
        ),
        "res_ops": tuple(put(a) for a in plan.res_ops),
    }


def _rule_emit_setup(plan, r, rp, ctx, mesh, pos_cache):
    """Per-rule shared setup for both drivers: the pow2-clamped rule batch
    (mesh-padded), the cached position iota, the uploaded plan arrays and
    the cached emission kernel (one specialisation per (rule, batch,
    mesh, compaction) — the kernel_cache key both drivers share, so a
    warmup through one driver warms the other)."""
    import jax
    import jax.numpy as jnp

    rule_bs = min(ctx["batch_size"], _pow2(max(rp.total, 64)))
    if mesh is not None:
        from .parallel.mesh import pad_to_multiple

        rule_bs = pad_to_multiple(rule_bs, mesh.devices.size)
    pos_rule = pos_cache.get(rule_bs)
    if pos_rule is None:
        if mesh is not None:
            pos_rule = jax.device_put(
                np.arange(rule_bs, dtype=np.int32), ctx["shard"]
            )
        else:
            pos_rule = jnp.arange(rule_bs, dtype=jnp.int32)
        pos_cache[rule_bs] = pos_rule
    put = ctx["put"]
    order_dev = put(rp.order)
    units_dev = tuple(put(a) for a in (rp.ua, rp.la, rp.ub, rp.lb))
    kkey = (
        r, rule_bs, None if mesh is None else id(mesh), ctx["compact_dev"],
    )
    fn = plan.kernel_cache.get(kkey)
    if fn is None:
        fn = plan.kernel_cache[kkey] = make_pair_emit_fn(
            rule_bs,
            n_prev=r,
            has_uid_mask=plan.uid_codes is not None,
            rank_filter=rp.rank_filter,
            own_res=rp.residual_fn,
            prev_res=tuple(p.residual_fn for p in plan.rules[:r]),
            mesh=mesh,
            compact=ctx["compact_dev"],
        )
    return rule_bs, pos_rule, order_dev, units_dev, fn


def iter_device_pairs(plan: DeviceBlockPlan, batch_size: int, mesh=None):
    """Drive the emission kernels over every rule, yielding
    ``(rule_index, i, j)`` host int32 chunks of at most ``batch_size``
    pairs in rule order (the same rule order the host sink sees).

    Chunk downloads run on a small thread pool ``_D2H_DEPTH`` deep (yield
    order stays submission order) so a chunk's D2H round trip overlaps the
    next chunk's kernel. Chunk shapes are power-of-two bucketed per rule —
    a steady-state emission loop compiles nothing after the first chunk of
    each rule.

    Telemetry: the driver accumulates host-side emission stats — chunks,
    pairs, pairs/sec, per-chunk budget fill and D2H thread-pool occupancy —
    and publishes ONE ambient ``blocking_device`` event when the stream
    ends (``python -m splink_tpu.obs summarize`` renders it). Pure host
    counters on the driver loop: the kernels and their jaxprs are
    untouched, and with no sink registered the publish is one falsy check.
    """
    import time as _time
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from .obs.events import publish

    if plan.n_candidates == 0:
        return
    ctx = _emission_context(plan, batch_size, mesh)
    batch_size = ctx["batch_size"]
    compact_dev = ctx["compact_dev"]
    ranks_dev = ctx["ranks"]
    codes_l_dev = ctx["codes_l"]
    codes_r_dev = ctx["codes_r"]
    uid_dev = ctx["uid"]
    res_ops_dev = ctx["res_ops"]
    pos_cache: dict = {}
    pool = ThreadPoolExecutor(max_workers=_D2H_DEPTH)
    inflight: deque = deque()
    # emission telemetry (host counters; published once in the finally).
    # fill/occupancy accumulate at SUBMIT time, so their means divide by
    # the submitted count — on an abandoned stream (the [abandoned] case
    # summarize flags) up to _D2H_DEPTH chunks are submitted but never
    # yielded, and dividing by the yield-time chunk count would inflate
    # exactly the diagnostics the event exists for
    stats = {"chunks": 0, "submitted": 0, "pairs": 0, "candidates": 0,
             "fill_sum": 0.0, "occ_sum": 0, "occ_max": 0,
             "completed": False}
    per_rule: dict[int, list] = {}
    t_start = _time.perf_counter()

    def account(res):
        r_idx, i, _j = res
        stats["chunks"] += 1
        stats["pairs"] += len(i)
        rr = per_rule.setdefault(r_idx, [0, 0])
        rr[0] += 1
        rr[1] += len(i)
        return res

    def own(arr, lanes):
        """Slice views into downloaded chunk buffers are zero-copy; when a
        slice keeps under half the buffer, copy so the consumer's sink
        doesn't pin the whole chunk buffer for a sliver of survivors."""
        return arr.copy() if 2 * len(arr) < lanes else arr

    def fetch(r, out_i, out_j, keep, n_valid):
        if keep is None:  # maskless kernel: only the tail drops
            return (
                r,
                own(np.asarray(out_i)[:n_valid], out_i.shape[0]),
                own(np.asarray(out_j)[:n_valid], out_j.shape[0]),
            )
        if compact_dev:
            ih = np.asarray(out_i)
            jh = np.asarray(out_j)
            cnt = int(ih[-1])
            return r, own(ih[:cnt], len(ih)), own(jh[:cnt], len(jh))
        if mesh is None:
            # uncompacted CPU backend: compact host-side. Rule overlap is
            # rare in practice, so most chunks keep everything — detect
            # the all-keep case and return zero-copy slices instead of
            # paying the boolean-indexed copy
            kh = np.asarray(keep)[:n_valid]
            ih = np.asarray(out_i)[:n_valid]
            jh = np.asarray(out_j)[:n_valid]
            if kh.all():
                return r, own(ih, out_i.shape[0]), own(jh, out_j.shape[0])
            return r, ih[kh], jh[kh]  # boolean indexing already copies
        # mesh: padded tail positions carry keep=False, compact directly
        kh = np.asarray(keep)
        return r, np.asarray(out_i)[kh], np.asarray(out_j)[kh]

    try:
        for r, rp in enumerate(plan.rules):
            if rp.total == 0:
                continue
            # rule batch clamped to the rule total (pow2 bucket): a
            # 38k-pair rule must not pad to a multi-M batch of dead lanes
            rule_bs, pos_rule, order_dev, units_dev, fn = _rule_emit_setup(
                plan, r, rp, ctx, mesh, pos_cache
            )
            for p0, p1, meta in _unit_batch_meta(rp.pc, rp.total, rule_bs):
                meta_dev = ctx["put"](meta)
                out_i, out_j, keep = fn(
                    pos_rule, order_dev, *units_dev, ranks_dev,
                    codes_l_dev, codes_r_dev, uid_dev, res_ops_dev,
                    meta_dev,
                )
                stats["submitted"] += 1
                stats["candidates"] += p1 - p0
                stats["fill_sum"] += (p1 - p0) / rule_bs
                inflight.append(
                    pool.submit(fetch, r, out_i, out_j, keep, p1 - p0)
                )
                occ = len(inflight)
                stats["occ_sum"] += occ
                if occ > stats["occ_max"]:
                    stats["occ_max"] = occ
                while len(inflight) > _D2H_DEPTH:
                    yield account(inflight.popleft().result())
        while inflight:
            yield account(inflight.popleft().result())
        stats["completed"] = True
    finally:
        # the consumer may abandon the generator mid-stream (a sink error):
        # do not leak pool threads or pinned buffers
        pool.shutdown(wait=False, cancel_futures=True)
        try:
            elapsed = max(_time.perf_counter() - t_start, 1e-9)
            n_sub = stats["submitted"] or 1
            publish(
                "blocking_device",
                rules=len(plan.rules),
                chunks=stats["chunks"],
                pairs=stats["pairs"],
                candidates=stats["candidates"],
                elapsed_s=round(elapsed, 4),
                pairs_per_sec=round(stats["pairs"] / elapsed),
                chunk_budget=batch_size,
                mean_chunk_fill=round(stats["fill_sum"] / n_sub, 4),
                d2h_occupancy_mean=round(stats["occ_sum"] / n_sub, 3),
                d2h_occupancy_max=stats["occ_max"],
                d2h_depth=_D2H_DEPTH,
                completed=stats["completed"],
                per_rule=[
                    {
                        "rule": plan.rules[r_idx].rule,
                        "chunks": c,
                        "pairs": p,
                    }
                    for r_idx, (c, p) in sorted(per_rule.items())
                ],
            )
        except Exception as e:  # noqa: BLE001 - telemetry must never break emission
            logger.debug("blocking_device telemetry publish failed: %s", e)


def device_block_rules(
    settings: dict,
    table: EncodedTable,
    n_left: int | None,
    sink,
    pair_consumer=None,
    mode: str = "auto",
    finish: bool = True,
):
    """The device tier of :func:`blocking.block_using_rules`: build the
    plan, stream chunked emission into the caller's sink, and return the
    finished PairIndex — or None to fall back to the host join (unsupported
    shape, or an "auto"-mode job too small to pay the jit warmup). A plan
    that FAILS to build never aborts the run (the host path is always
    there); an emission failure propagates — the sink already holds pairs.
    ``finish=False`` leaves the sink open (and returns it unfinished) so
    the caller can append a further tier — the approximate LSH tier rides
    through this.
    """
    if mode == "auto":
        import jax

        from .blocking import estimate_pair_upper_bound

        if jax.default_backend() == "cpu":
            # measured (BENCHMARKS.md round 8, 2-core container): the
            # XLA-CPU tier ties the numpy host join and trails the native
            # C++ one ~0.75x — on the CPU backend auto keeps the host
            # path; 'on' still forces the device tier (tests, parity)
            return None
        # exact-rules-only bound: this gate weighs the EXACT tier's jit
        # warmup against its join size, so the approx tier's budget (which
        # runs its own kernels regardless) must not inflate the decision
        if estimate_pair_upper_bound(
            settings, table, n_left, include_approx=False
        ) < AUTO_MIN_PAIRS:
            return None
    try:
        plan = build_device_plan(settings, table, n_left)
    except Exception as e:  # noqa: BLE001 - never lose a run to the new tier
        logger.warning(
            "device blocking plan build failed (%s: %s); falling back to "
            "host blocking", type(e).__name__, e,
        )
        return None
    if plan is None:
        return None
    batch = int(
        settings.get("blocking_chunk_pairs") or DEFAULT_CHUNK_PAIRS
    )
    logger.info(
        "device blocking: %d candidate positions, %d rules",
        plan.n_candidates, len(plan.rules),
    )
    for _r, i, j in iter_device_pairs(plan, batch):
        sink.append(i, j)
        if pair_consumer is not None:
            pair_consumer(
                i.astype(sink.idx_dtype, copy=False),
                j.astype(sink.idx_dtype, copy=False),
            )
    return sink.finish() if finish else sink


# --------------------------------------------------------------------------
# Sharded, out-of-core, resumable emission (the billion-row write path)
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=8)
def make_chunk_digest_fn(mesh=None):
    """Jitted transfer-integrity digest over one emitted pair chunk:
    fn(i, j, keep) -> uint32 scalar, the wraparound sum of a per-lane
    multiplicative mix of (i, j) over the kept lanes.

    Computed ON DEVICE right after the emission kernel (the pairs are
    already resident), then re-derived on the host from the downloaded
    arrays (spill.chunk_digest_host) — a mismatch catches corruption in
    the D2H path itself, the failure mode a tunnelled accelerator link
    adds on top of disk rot (which the manifest's sha256 covers). The sum
    is order-independent, which is exactly right: compaction reorders
    nothing but drops masked lanes, so the kept-lane multiset is the
    written multiset. Under a mesh the lane mixes are embarrassingly
    parallel along the sharded position axis and the sum lowers to one
    declared psum (shard_audit: spill_chunk_digest_sharded)."""
    import jax
    import jax.numpy as jnp

    from .spill import DIGEST_ADD, DIGEST_MUL

    jit_kwargs = {}
    if mesh is not None:
        from .parallel.mesh import replicated

        jit_kwargs = {"out_shardings": replicated(mesh)}

    @functools.partial(jax.jit, **jit_kwargs)
    def fn(i, j, keep):
        mixed = (i.astype(jnp.uint32) * jnp.uint32(DIGEST_MUL)) ^ (
            j.astype(jnp.uint32) + jnp.uint32(DIGEST_ADD)
        )
        mixed = mixed ^ (mixed >> jnp.uint32(15))
        return jnp.sum(
            jnp.where(keep, mixed, jnp.uint32(0)), dtype=jnp.uint32
        )

    return fn


@functools.lru_cache(maxsize=1)
def make_chunk_digest_compact_fn():
    """The transfer digest for COMPACTED emission chunks (the accelerator
    path, where on-device compaction halves D2H bytes): fn(i_ext, j, pos)
    -> uint32, with ``i_ext`` carrying the survivor count as its last lane
    (the emit kernel's compacted layout) and ``pos < count`` selecting
    exactly the survivor lanes. Same mix and sum as
    :func:`make_chunk_digest_fn`, so the host mirror over the downloaded
    prefix verifies it unchanged — without this twin, the very backends
    whose tunnelled D2H link the digest exists to check would commit
    segments unverified."""
    import jax
    import jax.numpy as jnp

    from .spill import DIGEST_ADD, DIGEST_MUL

    @jax.jit
    def fn(i_ext, j, pos):
        # static python index, NOT i_ext[-1]: a traced negative index
        # lowers through an int64 dynamic_slice under x64 (TA-DTYPE — the
        # same hazard the segment-sort kernel documents)
        cnt = i_ext[i_ext.shape[0] - 1]
        i = i_ext[:-1]
        keep = pos < cnt
        mixed = (i.astype(jnp.uint32) * jnp.uint32(DIGEST_MUL)) ^ (
            j.astype(jnp.uint32) + jnp.uint32(DIGEST_ADD)
        )
        mixed = mixed ^ (mixed >> jnp.uint32(15))
        return jnp.sum(
            jnp.where(keep, mixed, jnp.uint32(0)), dtype=jnp.uint32
        )

    return fn


def _shard_unit_ranges(pc: np.ndarray, n_shards: int) -> list[tuple[int, int]]:
    """Partition a rule's units into ``n_shards`` contiguous [lo, hi) index
    ranges balanced by CUMULATIVE PAIR COUNT (not unit count — unit pair
    sizes vary by orders of magnitude, and a row-count split would leave
    one shard holding every monster rectangle). Contiguity is what makes a
    shard's position space a simple offset slice of the rule's pc table,
    so each shard drives the SAME emission kernel over its own
    batch-relative metadata."""
    n_units = len(pc) - 1
    total = int(pc[-1])
    if n_units <= 0 or total == 0:
        return [(0, 0)] * n_shards
    cuts = [
        int(np.searchsorted(pc, (total * k) // n_shards, side="left"))
        for k in range(n_shards + 1)
    ]
    cuts[0], cuts[-1] = 0, n_units
    # monotone repair: searchsorted on a heavily skewed pc can cross
    for k in range(1, n_shards + 1):
        cuts[k] = min(max(cuts[k], cuts[k - 1]), n_units)
    return [(cuts[k], cuts[k + 1]) for k in range(n_shards)]


def emit_pairs_sharded(
    plan: DeviceBlockPlan,
    store,
    batch_size: int,
    n_shards: int = 1,
    mesh=None,
    budget: int | None = None,
    fault_plan=None,
    shard_filter: tuple[int, int] | None = None,
):
    """Drive the sharded, resumable emission of ``plan`` into a
    :class:`~.spill.PairSpillStore`.

    Each rule's triangle/rectangle units partition into ``n_shards``
    contiguous pair-count-balanced ranges (:func:`_shard_unit_ranges`);
    every (rule, shard) streams fixed-shape pow2 chunks through the SAME
    emission kernels as :func:`iter_device_pairs` (one specialisation per
    rule — shard metadata rows are floored to the rule-wide kpad so a
    shard switch never recompiles), each chunk committing as one manifest
    segment. With ``mesh`` the chunk decode shards over the data axis via
    the collective-free ``block_pair_decode_sharded`` kernel and the host
    compacts per shard.

    Determinism is the resumability contract: segments enumerate in fixed
    (rule, shard, seq) order with deterministic contents, so a driver
    relaunched over a half-built store SKIPS the committed prefix (no
    kernel runs for it) and appends byte-identical segments from there —
    the approx tier's progressive-budget discipline applied globally:
    ``budget`` caps total emitted pairs across all rules and shards, the
    final segment truncating exactly at the envelope.

    ``shard_filter=(p, P)`` emits only shards with ``shard % P == p`` —
    the multi-controller partition: each host drives its own subset of
    every rule's shards into its own per-process store, and the spill-fed
    EM's cross-process stats reduction makes the union behave as one
    global pair set (the same contract as global_pair_slice over a
    materialised G). ``budget`` is enforced against THIS driver's
    committed store — i.e. PER PROCESS under a shard filter (each
    controller's envelope, not a cross-process global; a global cap wants
    ``budget // P`` per process).

    Returns a stats dict (segments, skipped, pairs, exhausted). The caller
    finalizes the store.
    """
    import time as _time
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp

    from .obs.events import publish
    from .resilience import faults as _faults

    if fault_plan is None:
        fault_plan = _faults.active_plan()

    safe = (1 << 31) - 1 - plan.chunk * plan.chunk
    batch_size = min(max(int(batch_size), 64), safe)
    if mesh is not None:
        from .parallel.mesh import pad_to_multiple, pair_sharding, replicated

        msz = mesh.devices.size
        batch_size = pad_to_multiple(batch_size, msz)
        if batch_size > safe:
            batch_size = max(safe // msz, 1) * msz
        shard_s = pair_sharding(mesh)
        repl = replicated(mesh)
        put = lambda a: jax.device_put(jnp.asarray(a), repl)  # noqa: E731
    else:
        put = jnp.asarray

    compact_dev = mesh is None and jax.default_backend() != "cpu"
    ranks_dev = put(plan.ranks)
    codes_l_dev = put(
        plan.codes_l if len(plan.codes_l) else np.zeros((1, 1), np.int32)
    )
    codes_r_dev = put(
        plan.codes_r if len(plan.codes_r) else np.zeros((1, 1), np.int32)
    )
    uid_dev = put(
        plan.uid_codes if plan.uid_codes is not None
        else np.zeros(1, np.int32)
    )
    res_ops_dev = tuple(put(a) for a in plan.res_ops)
    digest_fn = (
        make_chunk_digest_compact_fn()
        if compact_dev
        else make_chunk_digest_fn(mesh)
    )
    pos_cache: dict = {}
    pool = ThreadPoolExecutor(max_workers=_D2H_DEPTH)
    inflight: deque = deque()
    stats = {"segments": 0, "skipped": 0, "pairs": 0, "exhausted": False}
    # resumed stores already carry pairs toward the budget envelope
    emitted = sum(s.pairs for s in store.segments)
    t_start = _time.perf_counter()

    def fetch(out_i, out_j, keep, n_valid, dig):
        """Download + host-compact one chunk (the iter_device_pairs fetch
        logic, minus zero-copy slicing — segment bytes are written
        immediately, so owning copies buy nothing)."""
        if keep is None:
            return (
                np.asarray(out_i)[:n_valid].copy(),
                np.asarray(out_j)[:n_valid].copy(),
                None,
            )
        if compact_dev:
            ih = np.asarray(out_i)
            jh = np.asarray(out_j)
            cnt = int(ih[-1])
            d = None if dig is None else int(np.asarray(dig))
            return ih[:cnt].copy(), jh[:cnt].copy(), d
        if mesh is None:
            kh = np.asarray(keep)[:n_valid]
            ih = np.asarray(out_i)[:n_valid]
            jh = np.asarray(out_j)[:n_valid]
            d = None if dig is None else int(np.asarray(dig))
            if kh.all():
                return ih.copy(), jh.copy(), d
            return ih[kh], jh[kh], d
        kh = np.asarray(keep)
        d = None if dig is None else int(np.asarray(dig))
        return np.asarray(out_i)[kh], np.asarray(out_j)[kh], d

    def drain_one():
        nonlocal emitted
        r, s, k, fut = inflight.popleft()
        i, j, dig = fut.result()
        if budget is not None and emitted + len(i) > budget:
            keep = max(budget - emitted, 0)
            i, j, dig = i[:keep], j[:keep], None
            stats["exhausted"] = True
        emitted += len(i)
        stats["pairs"] += len(i)
        stats["segments"] += 1
        store.write_segment(
            r, s, k, i, j, digest=dig,
            fault_hook=lambda: fault_plan.fire(
                "emit_segment", rule=r, shard=s, seq=k
            ),
        )

    try:
        for r, rp in enumerate(plan.rules):
            if rp.total == 0:
                continue
            rule_bs = min(batch_size, _pow2(max(rp.total, 64)))
            if mesh is not None:
                rule_bs = pad_to_multiple(rule_bs, mesh.devices.size)
            ranges = _shard_unit_ranges(rp.pc, n_shards)
            # two-pass metadata build: learn each shard's natural kpad,
            # then floor every shard at the rule-wide max so all segments
            # of a rule share ONE kernel specialisation
            shard_metas: list[list] = []
            for lo, hi in ranges:
                if hi <= lo:
                    shard_metas.append([])
                    continue
                pc_rel = rp.pc[lo : hi + 1] - rp.pc[lo]
                shard_metas.append(
                    _unit_batch_meta(pc_rel, int(pc_rel[-1]), rule_bs)
                )
            kpad_rule = max(
                (m[0][2].shape[0] - 2 for m in shard_metas if m), default=0
            )
            for s_idx, (lo, hi) in enumerate(ranges):
                if shard_metas[s_idx] and (
                    shard_metas[s_idx][0][2].shape[0] - 2 < kpad_rule
                ):
                    pc_rel = rp.pc[lo : hi + 1] - rp.pc[lo]
                    shard_metas[s_idx] = _unit_batch_meta(
                        pc_rel, int(pc_rel[-1]), rule_bs, kpad_min=kpad_rule
                    )
            pos_rule = pos_cache.get(rule_bs)
            if pos_rule is None:
                if mesh is not None:
                    pos_rule = jax.device_put(
                        np.arange(rule_bs, dtype=np.int32), shard_s
                    )
                else:
                    pos_rule = jnp.arange(rule_bs, dtype=jnp.int32)
                pos_cache[rule_bs] = pos_rule
            order_dev = put(rp.order)
            units_dev = tuple(put(a) for a in (rp.ua, rp.la, rp.ub, rp.lb))
            kkey = (
                r, rule_bs, None if mesh is None else id(mesh), compact_dev,
            )
            fn = plan.kernel_cache.get(kkey)
            if fn is None:
                fn = plan.kernel_cache[kkey] = make_pair_emit_fn(
                    rule_bs,
                    n_prev=r,
                    has_uid_mask=plan.uid_codes is not None,
                    rank_filter=rp.rank_filter,
                    own_res=rp.residual_fn,
                    prev_res=tuple(p.residual_fn for p in plan.rules[:r]),
                    mesh=mesh,
                    compact=compact_dev,
                )
            for s_idx, (lo, _hi) in enumerate(ranges):
                if shard_filter is not None and (
                    s_idx % shard_filter[1] != shard_filter[0]
                ):
                    continue
                for k, (_p0, p1, meta) in enumerate(shard_metas[s_idx]):
                    if store.segment_done(r, s_idx, k):
                        stats["skipped"] += 1
                        continue
                    if budget is not None:
                        # budget runs drain sequentially: the stop decision
                        # must depend only on COMMITTED pair counts, or a
                        # resumed run (which sees committed counts, not
                        # optimistic in-flight ones) would dispatch a
                        # different segment set than the uninterrupted one
                        while inflight:
                            drain_one()
                        if emitted >= budget:
                            stats["exhausted"] = True
                            raise StopIteration
                    meta = meta.copy()
                    meta[0] += lo  # shard units index the FULL unit tables
                    meta_dev = put(meta)
                    out_i, out_j, keep = fn(
                        pos_rule, order_dev, *units_dev, ranks_dev,
                        codes_l_dev, codes_r_dev, uid_dev, res_ops_dev,
                        meta_dev,
                    )
                    dig = None
                    if keep is not None:
                        # compact layout passes positions (the count rides
                        # as out_i's last lane); uncompacted passes the
                        # keep mask directly
                        dig = (
                            digest_fn(out_i, out_j, pos_rule)
                            if compact_dev
                            else digest_fn(out_i, out_j, keep)
                        )
                    inflight.append(
                        (r, s_idx, k,
                         pool.submit(fetch, out_i, out_j, keep, p1 - _p0, dig))
                    )
                    while len(inflight) > _D2H_DEPTH:
                        drain_one()
        while inflight:
            drain_one()
    except StopIteration:
        while inflight:
            drain_one()
    finally:
        pool.shutdown(wait=False, cancel_futures=True)
        try:
            elapsed = max(_time.perf_counter() - t_start, 1e-9)
            publish(
                "blocking_spill",
                rules=len(plan.rules),
                shards=n_shards,
                segments=stats["segments"],
                skipped=stats["skipped"],
                pairs=stats["pairs"],
                pairs_per_sec=round(stats["pairs"] / elapsed),
                chunk_budget=batch_size,
                budget=budget,
                exhausted=stats["exhausted"],
                elapsed_s=round(elapsed, 4),
            )
        except Exception as e:  # noqa: BLE001 - telemetry must never break emission
            logger.debug("blocking_spill telemetry publish failed: %s", e)
    return stats


def spill_block_rules(
    settings: dict,
    table: EncodedTable,
    n_left: int | None,
    build_dir: str,
    budget: int | None = None,
):
    """The build_spill_dir write path: sharded resumable emission into
    ``<build_dir>/pairs``, returning a durable store-backed PairIndex — or
    None when the job's rule shapes need the host join (the caller falls
    back to the ordinary, non-resumable path with its own warning).

    A store already finalized for this exact job returns instantly (the
    idempotent-restart property a relaunch-loop harness needs); a
    half-built one resumes from its last committed segment.
    """
    import os

    from .parallel.mesh import mesh_from_settings
    from .resilience.checkpoint import settings_state_hash
    from .spill import PairSpillStore

    try:
        plan = build_device_plan(settings, table, n_left)
    except Exception as e:  # noqa: BLE001 - never lose a run to the new tier
        logger.warning(
            "spill emission plan build failed (%s: %s); falling back to "
            "the non-resumable blocking path", type(e).__name__, e,
        )
        return None
    if plan is None:
        return None
    from .blocking import _idx_dtype

    import jax

    from .parallel.distributed import distributed_is_initialized

    p_idx, p_cnt = 0, 1
    if distributed_is_initialized():
        p_idx, p_cnt = jax.process_index(), jax.process_count()
    mesh = mesh_from_settings(settings)
    n_shards = int(settings.get("emit_shard_chunks") or 0)
    if n_shards <= 0:
        n_shards = (mesh.devices.size if mesh is not None else 1) * p_cnt
    n_shards = max(n_shards, p_cnt)
    batch = int(settings.get("blocking_chunk_pairs") or DEFAULT_CHUNK_PAIRS)
    state_hash = settings_state_hash(
        settings, extra={"artifact": "pair_spill", "n_rows": int(table.n_rows)}
    )
    meta = {
        "state_hash": state_hash,
        "n_shards": n_shards,
        "chunk_pairs": batch,
        "budget": budget,
        "process_index": p_idx,
        "process_count": p_cnt,
        "rule_totals": [int(rp.total) for rp in plan.rules],
    }
    store = PairSpillStore.attach(
        os.path.join(build_dir, "pairs"), _idx_dtype(table.n_rows), meta
    )
    if store.completed:
        logger.info(
            "spill store at %s already finalized (%d pairs); reusing",
            store.directory, store.total_pairs,
        )
        return store.as_pair_index()
    with store:
        stats = emit_pairs_sharded(
            plan, store, batch, n_shards=n_shards, mesh=mesh,
            budget=budget,
            shard_filter=None if p_cnt == 1 else (p_idx, p_cnt),
        )
    store.finalize(exhausted=stats["exhausted"])
    logger.info(
        "spill emission: %d pairs in %d segments (%d resumed) at %s",
        store.total_pairs, len(store.segments), stats["skipped"],
        store.directory,
    )
    return store.as_pair_index()


# --------------------------------------------------------------------------
# Serving bucket CSR (serve/index.py)
# --------------------------------------------------------------------------


def build_bucket_csr(codes: np.ndarray):
    """Device bucket-CSR build over one rule's key codes for the serving
    index: (rows_sorted, starts, sizes, row_bucket) int32 arrays bit-equal
    to the host ``_sort_groups`` + scatter construction, or None when the
    codes don't fit the device lanes (the caller falls back to the host
    build)."""
    n = len(codes)
    if n == 0 or int(codes.max(initial=0)) >= _IMAX:
        return None
    m = _pow2(n)
    padded = codes.astype(np.int32)
    if m != n:
        padded = np.concatenate([padded, np.full(m - n, -1, np.int32)])
    fn = make_bucket_csr_fn()
    row_s, starts, sizes, row_bucket, n_seg, n_valid = fn(padded)
    n_valid_h = int(np.asarray(n_valid))
    n_seg_h = int(np.asarray(n_seg))
    starts = np.asarray(starts)[:n_seg_h]
    sizes = np.asarray(sizes)[:n_seg_h]
    live = starts < n_valid_h  # drop the trailing null/pad segment
    return (
        np.asarray(row_s)[:n_valid_h],
        starts[live],
        sizes[live],
        np.asarray(row_bucket)[:n],
    )
