"""Model parameter state for the Fellegi-Sunter model.

Keeps the exact serialised layout of the reference implementation
(/root/reference/splink/params.py:34-336): a ``λ`` scalar plus a ``π`` nested
dict with per-column, per-level match/non-match probabilities, a per-iteration
history, and JSON persistence as ``{current_params, historical_params,
settings}`` so models saved by either implementation can be loaded by the
other. On top of that it provides lossless conversion to/from dense
``(n_cols, max_levels)`` arrays, which is the form the jitted EM loop works
with (params stay on device across iterations; this object is only touched at
the host boundary).
"""

from __future__ import annotations

import copy
import json
import logging
import os

import numpy as np

from . import charts
from .settings import complete_settings_dict, comparison_column_name

logger = logging.getLogger("splink_tpu")


class Params:
    """Current model parameters plus the values from every previous iteration."""

    def __init__(self, settings: dict, complete: bool = True):
        self.param_history: list[dict] = []
        self.iteration = 1
        self.settings = complete_settings_dict(settings) if complete else settings
        self.params = {"λ": self.settings["proportion_of_matches"], "π": {}}
        self.log_likelihood_exists = False
        # Optional dict in the same layout as self.params holding the true
        # data-generating parameters (for charts on synthetic data).
        self.real_params = None
        self._generate_param_dict()

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------

    def _generate_param_dict(self) -> None:
        for col_dict in self.settings["comparison_columns"]:
            col_name = comparison_column_name(col_dict)
            key = f"gamma_{col_name}"
            num_levels = col_dict["num_levels"]

            entry = {
                "gamma_index": col_dict["gamma_index"],
                "desc": f"Comparison of {col_name}",
                "column_name": col_name,
            }
            if "custom_name" in col_dict:
                entry["custom_comparison"] = True
                entry["custom_columns_used"] = col_dict["custom_columns_used"]
            else:
                entry["custom_comparison"] = False
            entry["num_levels"] = num_levels

            m = _normalised(col_dict["m_probabilities"])
            u = _normalised(col_dict["u_probabilities"])
            entry["prob_dist_match"] = {
                f"level_{lv}": {"value": lv, "probability": m[lv]}
                for lv in range(num_levels)
            }
            entry["prob_dist_non_match"] = {
                f"level_{lv}": {"value": lv, "probability": u[lv]}
                for lv in range(num_levels)
            }
            self.params["π"][key] = entry

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    @property
    def _gamma_cols(self):
        return list(self.params["π"].keys())

    def describe_gammas(self) -> dict:
        return {k: v["desc"] for k, v in self.params["π"].items()}

    @property
    def max_levels(self) -> int:
        return max(v["num_levels"] for v in self.params["π"].values())

    # ------------------------------------------------------------------
    # Array <-> dict conversion (the device-facing view)
    # ------------------------------------------------------------------

    def to_arrays(self, dtype=np.float64):
        """Return (lam, m, u, level_mask).

        m/u have shape (n_cols, max_levels); rows are padded with zeros past a
        column's num_levels, and level_mask marks the valid entries.
        """
        cols = self._gamma_cols
        n_cols, max_levels = len(cols), self.max_levels
        m = np.zeros((n_cols, max_levels), dtype=dtype)
        u = np.zeros((n_cols, max_levels), dtype=dtype)
        mask = np.zeros((n_cols, max_levels), dtype=bool)
        for c, key in enumerate(cols):
            entry = self.params["π"][key]
            for lv in range(entry["num_levels"]):
                m[c, lv] = entry["prob_dist_match"][f"level_{lv}"]["probability"]
                u[c, lv] = entry["prob_dist_non_match"][f"level_{lv}"]["probability"]
                mask[c, lv] = True
        return np.asarray(self.params["λ"], dtype=dtype), m, u, mask

    def update_from_arrays(self, lam, m, u) -> None:
        """One EM update: archive current params then install the new values.

        Matches the reference's update cycle (save -> reset -> populate with
        zero-fill for unseen levels -> increment iteration,
        /root/reference/splink/params.py:248-285). Unseen levels arrive here
        as exact zeros from the M-step, which reproduces the reference's
        zero-fill behaviour; gamma = -1 pseudo-levels are excluded upstream.
        """
        self._save_params_to_iteration_history()
        self.params["λ"] = float(lam)
        m = np.asarray(m)
        u = np.asarray(u)
        for c, key in enumerate(self._gamma_cols):
            entry = self.params["π"][key]
            for lv in range(entry["num_levels"]):
                entry["prob_dist_match"][f"level_{lv}"]["probability"] = float(m[c, lv])
                entry["prob_dist_non_match"][f"level_{lv}"]["probability"] = float(u[c, lv])
        self.iteration += 1

    def _save_params_to_iteration_history(self) -> None:
        self.param_history.append(copy.deepcopy(self.params))
        if "log_likelihood" in self.params:
            self.log_likelihood_exists = True

    # ------------------------------------------------------------------
    # Convergence
    # ------------------------------------------------------------------

    def is_converged(self) -> bool:
        """Max absolute change in any π probability below em_convergence.

        Like the reference (/root/reference/splink/params.py:316-336) this
        inspects the π probabilities only; λ is tracked in history but does
        not gate convergence.
        """
        threshold = self.settings["em_convergence"]
        new = _pi_probabilities(self.params)
        old = _pi_probabilities(self.param_history[-1])
        biggest_change, biggest_key = 0.0, ""
        for k, v in new.items():
            change = abs(v - old[k])
            if change > biggest_change:
                biggest_change, biggest_key = change, k
        logger.info(
            "The maximum change in parameters was %s for key %s",
            biggest_change,
            biggest_key,
        )
        return biggest_change < threshold

    # ------------------------------------------------------------------
    # Persistence
    # ------------------------------------------------------------------

    def _to_dict(self) -> dict:
        return {
            "current_params": self.params,
            "historical_params": self.param_history,
            "settings": _jsonable_settings(self.settings),
        }

    def save_params_to_json_file(self, path=None, overwrite=False) -> None:
        if not path:
            raise ValueError("Must provide a path to write to")
        if os.path.isfile(path) and not overwrite:
            raise ValueError(
                f"The path {path} already exists. Please provide a different path."
            )
        with open(path, "w") as f:
            json.dump(self._to_dict(), f, indent=4)

    # ------------------------------------------------------------------
    # History views (chart data)
    # ------------------------------------------------------------------

    @staticmethod
    def _convert_params_dict_to_dataframe(params, iteration_num=None) -> list[dict]:
        rows = []
        for gamma_str, gamma_dict in params["π"].items():
            for match_flag, dist in (
                (1, "prob_dist_match"),
                (0, "prob_dist_non_match"),
            ):
                for level_str, level_dict in gamma_dict[dist].items():
                    row = {}
                    if iteration_num is not None:
                        row["iteration"] = iteration_num
                    row.update(
                        gamma=gamma_str,
                        match=match_flag,
                        value_of_gamma=level_str,
                        probability=level_dict["probability"],
                        value=level_dict["value"],
                        column=gamma_dict["column_name"],
                    )
                    rows.append(row)
        return rows

    def _convert_params_dict_to_normalised_adjustment_data(self) -> list[dict]:
        rows = []
        for gamma_str, entry in self.params["π"].items():
            for lv in range(entry["num_levels"]):
                level = f"level_{lv}"
                m = entry["prob_dist_match"][level]["probability"]
                u = entry["prob_dist_non_match"][level]["probability"]
                row = {"level": level, "col_name": entry["column_name"], "m": m, "u": u}
                if (m or 0) + (u or 0) > 0:
                    row["adjustment"] = m / (m + u)
                    row["normalised_adjustment"] = row["adjustment"] - 0.5
                else:
                    row["adjustment"] = None
                    row["normalised_adjustment"] = None
                rows.append(row)
        return rows

    def _iteration_history_df_gammas(self) -> list[dict]:
        rows = []
        it = -1
        for it, historical in enumerate(self.param_history):
            rows.extend(self._convert_params_dict_to_dataframe(historical, it))
        rows.extend(self._convert_params_dict_to_dataframe(self.params, it + 1))
        return rows

    def _iteration_history_df_lambdas(self) -> list[dict]:
        rows = [
            {"λ": h["λ"], "iteration": it} for it, h in enumerate(self.param_history)
        ]
        rows.append({"λ": self.params["λ"], "iteration": len(self.param_history)})
        return rows

    def _iteration_history_df_log_likelihood(self) -> list[dict]:
        rows = [
            {"log_likelihood": h.get("log_likelihood"), "iteration": it}
            for it, h in enumerate(self.param_history)
        ]
        rows.append(
            {
                "log_likelihood": self.params.get("log_likelihood"),
                "iteration": len(self.param_history),
            }
        )
        return rows

    # ------------------------------------------------------------------
    # Charts
    # ------------------------------------------------------------------

    def pi_iteration_chart(self):  # pragma: no cover - presentational
        data = self._iteration_history_df_gammas()
        if self.real_params:
            data.extend(
                self._convert_params_dict_to_dataframe(self.real_params, "real_param")
            )
        return charts.try_altair(charts.with_data(charts.pi_iteration_chart_def, data))

    def lambda_iteration_chart(self):  # pragma: no cover - presentational
        data = self._iteration_history_df_lambdas()
        if self.real_params:
            data.append({"λ": self.real_params["λ"], "iteration": "real_param"})
        return charts.try_altair(
            charts.with_data(charts.lambda_iteration_chart_def, data)
        )

    def ll_iteration_chart(self):  # pragma: no cover - presentational
        if not self.log_likelihood_exists:
            raise RuntimeError(
                "Log likelihood not calculated. Pass compute_ll=True to iterate()."
            )
        data = self._iteration_history_df_log_likelihood()
        return charts.try_altair(charts.with_data(charts.ll_iteration_chart_def, data))

    def probability_distribution_chart(self):  # pragma: no cover - presentational
        data = self._convert_params_dict_to_dataframe(self.params)
        return charts.try_altair(
            charts.with_data(charts.probability_distribution_chart_def, data)
        )

    def adjustment_factor_chart(self):  # pragma: no cover - presentational
        data = self._convert_params_dict_to_normalised_adjustment_data()
        return charts.try_altair(
            charts.with_data(charts.adjustment_weight_chart_def, data)
        )

    def all_charts_write_html_file(self, filename="splink_charts.html", overwrite=False):
        specs = [
            charts.with_data(
                charts.probability_distribution_chart_def,
                self._convert_params_dict_to_dataframe(self.params),
            ),
            charts.with_data(
                charts.adjustment_weight_chart_def,
                self._convert_params_dict_to_normalised_adjustment_data(),
            ),
            charts.with_data(
                charts.lambda_iteration_chart_def, self._iteration_history_df_lambdas()
            ),
            charts.with_data(
                charts.pi_iteration_chart_def, self._iteration_history_df_gammas()
            ),
        ]
        if self.log_likelihood_exists:
            specs.append(
                charts.with_data(
                    charts.ll_iteration_chart_def,
                    self._iteration_history_df_log_likelihood(),
                )
            )
        charts.write_html_file(filename, specs, overwrite=overwrite)

    # ------------------------------------------------------------------
    # Text rendering
    # ------------------------------------------------------------------

    def _print_m_u_probs(self):  # pragma: no cover - presentational
        for key, entry in self.params["π"].items():
            m = [v["probability"] for v in entry["prob_dist_match"].values()]
            u = [v["probability"] for v in entry["prob_dist_non_match"].values()]
            print(key)
            print(f'"m_probabilities": {m},')
            print(f'"u_probabilities": {u}')

    def __repr__(self):
        p = self.params
        lines = [f"λ (proportion of matches) = {p['λ']}"]
        for gamma_str, entry in p["π"].items():
            lines.append("------------------------------------")
            lines.append(f"{gamma_str}: {entry['desc']}")
            for label, dist in (
                ("matches", "prob_dist_match"),
                ("non-matches", "prob_dist_non_match"),
            ):
                lines.append(f"Probability distribution of gamma values amongst {label}:")
                n = entry["num_levels"]
                for lv in range(n):
                    prob = entry[dist][f"level_{lv}"]["probability"]
                    prob_str = f"{prob:4f}" if prob else "None"
                    note = ""
                    if lv == 0:
                        note = " (lowest similarity)"
                    elif lv == n - 1:
                        note = " (highest similarity)"
                    lines.append(f"    value {lv}: {prob_str}{note}")
        return "\n".join(lines)


# ----------------------------------------------------------------------
# Module-level helpers
# ----------------------------------------------------------------------


def _normalised(probs):
    s = sum(probs)
    if s <= 0:
        # an all-zero distribution (every level zero-filled) carries no
        # information; renormalise to uniform rather than dividing by 0
        return [1.0 / len(probs)] * len(probs)
    return [p / s for p in probs]


def _pi_probabilities(params: dict) -> dict:
    """Flatten π into {col/dist/level: probability}."""
    out = {}
    for gamma_str, entry in params["π"].items():
        for dist in ("prob_dist_match", "prob_dist_non_match"):
            for level_str, level_dict in entry[dist].items():
                out[f"{gamma_str}.{dist}.{level_str}"] = level_dict["probability"]
    return out


def _jsonable_settings(settings: dict) -> dict:
    """Strip non-serialisable values (e.g. custom comparison callables)."""

    def default(o):
        return f"<<non-serialisable: {type(o).__name__}>>"

    return json.loads(json.dumps(settings, default=default))


def load_params_from_dict(param_dict: dict) -> Params:
    expected = {"current_params", "settings", "historical_params"}
    if set(param_dict.keys()) != expected:
        raise ValueError("Your saved params seem to be corrupted")
    p = Params(settings=param_dict["settings"])
    p.params = param_dict["current_params"]
    p.param_history = param_dict["historical_params"]
    p.iteration = len(p.param_history) + 1
    p.log_likelihood_exists = any(
        "log_likelihood" in h for h in p.param_history
    ) or "log_likelihood" in p.params
    return p


def load_params_from_json(path: str) -> Params:
    with open(path) as f:
        return load_params_from_dict(json.load(f))
