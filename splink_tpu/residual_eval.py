"""Safe, vectorised evaluator for residual blocking predicates.

`compat_sql.sql_predicate_to_python` translates the non-equality part of a
blocking rule into a small python expression over ``l``/``r`` column
namespaces. Round 1 ran that expression through ``eval`` over object arrays;
this module replaces it with a typed AST interpreter:

  * only a whitelisted node grammar is accepted (no ``eval``, no attribute
    access, no arbitrary calls) — the expression is config-derived, but it
    deserves an interpreter, not a prayer;
  * string columns compare through cached lexicographic *rank* arrays
    (float64, NaN for null; splink_tpu/data.py ``string_ranks``), so =, <>,
    <, <= etc. run as numeric SIMD compares instead of per-element python
    object comparisons — order-isomorphic to the string comparison SQL would
    do. String literals map to a (possibly half-integer) virtual rank by
    binary search. Cross-column string compares (different vocabularies)
    fall back to object arrays with explicit null masks;
  * comparisons follow SQL three-valued logic: any null operand makes the
    atom UNKNOWN, and UNKNOWN propagates through AND/OR/NOT by Kleene rules,
    with rows kept only when the predicate is known-true. (This also fixes
    ``l.x <> r.x`` keeping null rows, which numpy's NaN != NaN would do.)
  * SQL scalar functions (substr, lower/upper, trim, concat / ``||``,
    coalesce/ifnull, length, left/right, reverse, dmetaphone, round, cast,
    ...) evaluate through derived_keys.PairEval — the SAME implementation
    that computes derived blocking join keys and the device residual
    compiler's precomputed operands, so one definition of each function's
    (null) semantics serves all three consumers.

The reference gets all of this from the SQL engine for free
(/root/reference/splink/blocking.py:141-158); here it is ~200 lines that run
at memory bandwidth on the host.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

import numpy as np

from .data import EncodedTable


class ResidualEvalError(ValueError):
    pass


@dataclass
class Kleene:
    """A vector of SQL booleans: value + unknown mask."""

    val: np.ndarray  # bool
    unk: np.ndarray  # bool

    def __and__(self, other: "Kleene") -> "Kleene":
        false_a = ~self.val & ~self.unk
        false_b = ~other.val & ~other.unk
        unk = (self.unk | other.unk) & ~false_a & ~false_b
        return Kleene(self.val & other.val & ~unk, unk)

    def __or__(self, other: "Kleene") -> "Kleene":
        true_a = self.val & ~self.unk
        true_b = other.val & ~other.unk
        unk = (self.unk | other.unk) & ~true_a & ~true_b
        return Kleene((self.val | other.val) & ~unk, unk)

    def __invert__(self) -> "Kleene":
        return Kleene(~self.val & ~self.unk, self.unk)

    @property
    def known_true(self) -> np.ndarray:
        return self.val & ~self.unk


class StrOperand:
    """A string column's pair-gathered values, compared by rank when possible."""

    def __init__(self, table: EncodedTable, col: str, rows: np.ndarray):
        self.table = table
        self.col = col
        self.rows = rows
        self._ranks = None
        self._values = None

    @property
    def ranks(self) -> np.ndarray:
        if self._ranks is None:
            ranks, _ = self.table.string_ranks(self.col)
            self._ranks = ranks[self.rows]
        return self._ranks

    @property
    def vocab(self) -> np.ndarray:
        return self.table.string_ranks(self.col)[1]

    @property
    def values(self) -> np.ndarray:
        if self._values is None:
            vals = np.array(self.table.column_values(self.col), dtype=object)
            self._values = vals[self.rows]
        return self._values

    @property
    def null(self) -> np.ndarray:
        return self.table.is_null(self.col)[self.rows]

    def literal_rank(self, s: str) -> float:
        """Rank of a string literal in this column's vocabulary; absent
        literals get the half-integer insertion rank, which orders correctly
        against every real rank and equals none of them."""
        pos = int(np.searchsorted(self.vocab, s))
        if pos < len(self.vocab) and self.vocab[pos] == s:
            return float(pos)
        return pos - 0.5


class RawOperand:
    """Passthrough (non-encoded) column: object arrays, explicit null mask."""

    def __init__(self, table: EncodedTable, col: str, rows: np.ndarray):
        self.table = table
        self.col = col
        self.rows = rows
        self._values = None

    @property
    def values(self) -> np.ndarray:
        if self._values is None:
            vals = np.array(self.table.column_values(self.col), dtype=object)
            self._values = vals[self.rows]
        return self._values

    @property
    def null(self) -> np.ndarray:
        return self.table.is_null(self.col)[self.rows]


class Materialized:
    """A computed string vector (the result of a SQL scalar function like
    substr/lower/concat, evaluated by derived_keys.PairEval): object values
    plus an explicit null mask. Compares like a raw column."""

    def __init__(self, values: np.ndarray, null: np.ndarray):
        self.values = values
        self.null = null


# Operands that carry (values, null) object vectors
_OBJECT_OPERANDS = (StrOperand, RawOperand, Materialized)


_CMP = {
    ast.Eq: np.equal,
    ast.NotEq: np.not_equal,
    ast.Lt: np.less,
    ast.LtE: np.less_equal,
    ast.Gt: np.greater,
    ast.GtE: np.greater_equal,
}

_ARITH = {
    ast.Add: np.add,
    ast.Sub: np.subtract,
    ast.Mult: np.multiply,
    ast.Div: np.divide,
    # fmod, not mod: SQL's % takes the dividend's sign (Spark: -7 % 3 = -1)
    ast.Mod: np.fmod,
    ast.Pow: np.power,
}


class _Evaluator:
    def __init__(self, table: EncodedTable, i: np.ndarray, j: np.ndarray):
        self.table = table
        self.namespaces = {"l": i, "r": j}
        self.n = len(i)

    # -- boolean level ---------------------------------------------------

    def bool_eval(self, node: ast.AST) -> Kleene:
        if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.BitAnd, ast.BitOr)):
            a = self.bool_eval(node.left)
            b = self.bool_eval(node.right)
            return (a & b) if isinstance(node.op, ast.BitAnd) else (a | b)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Invert):
            return ~self.bool_eval(node.operand)
        if isinstance(node, ast.Compare):
            return self.compare(node)
        if isinstance(node, ast.Call):
            return self.isna_call(node)
        if isinstance(node, ast.Constant) and isinstance(node.value, bool):
            full = np.full(self.n, bool(node.value))
            return Kleene(full, np.zeros(self.n, bool))
        raise ResidualEvalError(
            f"Unsupported boolean construct in residual predicate: "
            f"{ast.dump(node)[:80]}"
        )

    def isna_call(self, node: ast.Call) -> Kleene:
        if not (isinstance(node.func, ast.Name) and node.func.id == "_isna"):
            raise ResidualEvalError(
                "Only _isna(...) may appear as a boolean call in a residual"
            )
        (arg,) = node.args
        operand = self.value_eval(arg)
        if isinstance(operand, _OBJECT_OPERANDS):
            null = operand.null
        elif isinstance(operand, np.ndarray):
            null = np.isnan(operand)
        else:
            raise ResidualEvalError("_isna of a literal is not meaningful")
        return Kleene(null.copy(), np.zeros(self.n, bool))

    # -- comparison level ------------------------------------------------

    def compare(self, node: ast.Compare) -> Kleene:
        operands = [node.left, *node.comparators]
        out: Kleene | None = None
        for op, ln, rn in zip(node.ops, operands, operands[1:]):
            if type(op) not in _CMP:
                raise ResidualEvalError(
                    f"Unsupported comparison operator {type(op).__name__}"
                )
            atom = self.compare_pair(_CMP[type(op)], ln, rn)
            out = atom if out is None else (out & atom)
        assert out is not None
        return out

    def compare_pair(self, ufunc, left_node, right_node) -> Kleene:
        lv = self.value_eval(left_node)
        rv = self.value_eval(right_node)

        # string column vs string column
        if isinstance(lv, StrOperand) and isinstance(rv, StrOperand):
            if lv.col == rv.col and lv.table is rv.table:
                return self._numeric_cmp(ufunc, lv.ranks, rv.ranks)
            # different vocabularies: object fallback with explicit nulls
            return self._object_cmp(ufunc, lv.values, lv.null, rv.values, rv.null)
        # string column vs string literal
        if isinstance(lv, StrOperand) and isinstance(rv, str):
            return self._numeric_cmp(ufunc, lv.ranks, lv.literal_rank(rv))
        if isinstance(rv, StrOperand) and isinstance(lv, str):
            return self._numeric_cmp(ufunc, rv.literal_rank(lv), rv.ranks)
        # raw / computed string operand involved: object comparison
        if isinstance(lv, (RawOperand, Materialized)) or isinstance(
            rv, (RawOperand, Materialized)
        ):
            lvals, lnull = self._raw_side(lv)
            rvals, rnull = self._raw_side(rv)
            return self._object_cmp(ufunc, lvals, lnull, rvals, rnull)
        # numeric vs numeric (arrays and/or scalars)
        if isinstance(lv, (np.ndarray, float, int)) and isinstance(
            rv, (np.ndarray, float, int)
        ):
            return self._numeric_cmp(ufunc, lv, rv)
        raise ResidualEvalError(
            f"Type mismatch in residual comparison: {type(lv).__name__} vs "
            f"{type(rv).__name__} (e.g. a numeric column against a string "
            "literal)"
        )

    def _object_cmp(self, ufunc, lvals, lnull, rvals, rnull) -> Kleene:
        """Elementwise object comparison restricted to rows where both sides
        are known — comparing None against a value would TypeError for
        ordering operators."""
        unk = lnull | rnull
        val = np.zeros(self.n, bool)
        known = ~unk
        if known.any():
            try:
                with np.errstate(invalid="ignore"):
                    val[known] = np.asarray(
                        ufunc(lvals[known], rvals[known]), dtype=bool
                    )
            except TypeError as e:
                # e.g. ordering a float column against a computed string —
                # surface a typed error instead of a raw numpy TypeError
                raise ResidualEvalError(
                    f"Incomparable operand types in residual comparison: {e}"
                ) from None
        return Kleene(val, unk)

    def _raw_side(self, v):
        if isinstance(v, _OBJECT_OPERANDS):
            return v.values, v.null
        arr = np.full(self.n, v, dtype=object)
        return arr, np.zeros(self.n, bool)

    def _numeric_cmp(self, ufunc, a, b) -> Kleene:
        with np.errstate(invalid="ignore"):
            val = ufunc(a, b)
        unk = np.zeros(self.n, bool)
        for side in (a, b):
            if isinstance(side, np.ndarray):
                unk |= np.isnan(side)
            elif isinstance(side, float) and np.isnan(side):
                unk |= True
        val = np.broadcast_to(np.asarray(val, bool), (self.n,)).copy()
        return Kleene(val & ~unk, unk)

    # -- value level -----------------------------------------------------

    def value_eval(self, node: ast.AST):
        if isinstance(node, ast.Subscript):
            return self.column(node)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, float, str)):
                return node.value
            raise ResidualEvalError(f"Unsupported literal {node.value!r}")
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = self.value_eval(node.operand)
            if isinstance(v, (np.ndarray, int, float)):
                return -v
            raise ResidualEvalError("Unary minus on a non-numeric operand")
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
            # `@` is compat_sql's translation of SQL's `||` concat operator
            return self._derived(node)
        if isinstance(node, ast.BinOp) and type(node.op) in _ARITH:
            a = self._numeric_value(node.left)
            b = self._numeric_value(node.right)
            with np.errstate(invalid="ignore", divide="ignore"):
                return _ARITH[type(node.op)](a, b)
        if isinstance(node, ast.Call):
            return self.value_call(node)
        raise ResidualEvalError(
            f"Unsupported value construct: {ast.dump(node)[:80]}"
        )

    def value_call(self, node: ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id == "abs":
            (arg,) = node.args
            return np.abs(self._numeric_value(arg))
        return self._derived(node)

    def _derived(self, node: ast.AST):
        """SQL scalar functions (substr/lower/upper/trim/concat/coalesce/
        length/left/right/reverse/dmetaphone/round/cast, plus ``@`` = SQL
        ``||``) evaluate through derived_keys.PairEval — ONE implementation
        of the function semantics shared with blocking join keys and the
        device residual compiler (pairgen._ResCompiler)."""
        from .derived_keys import DerivedKeyError, PairEval, pyast_to_keynode

        try:
            knode = pyast_to_keynode(node)
            kind, vals, null = PairEval(
                self.table, self.namespaces["l"], self.namespaces["r"]
            ).eval(knode)
        except DerivedKeyError as e:
            raise ResidualEvalError(str(e)) from None
        if kind == "num":
            out = vals.copy()
            out[null] = np.nan
            return out
        return Materialized(vals, null)

    def _numeric_value(self, node: ast.AST) -> np.ndarray | float | int:
        v = self.value_eval(node)
        if isinstance(v, (np.ndarray, int, float)):
            return v
        if isinstance(v, _OBJECT_OPERANDS):
            # SQL implicitly casts in numeric contexts (CAST(col AS DOUBLE));
            # unparseable values and nulls become NaN -> comparison unknown.
            import pandas as pd

            vals = pd.to_numeric(
                pd.Series(v.values), errors="coerce"
            ).to_numpy(dtype=np.float64, copy=True)
            vals[v.null] = np.nan
            return vals
        raise ResidualEvalError(
            f"Expected a numeric operand, got {type(v).__name__}"
        )

    def column(self, node: ast.Subscript):
        if not (
            isinstance(node.value, ast.Name)
            and node.value.id in self.namespaces
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
        ):
            raise ResidualEvalError("Only l[\"col\"] / r[\"col\"] subscripts allowed")
        col = node.slice.value
        rows = self.namespaces[node.value.id]
        table = self.table
        if col in table.strings:
            return StrOperand(table, col, rows)
        if col in table.numerics:
            nc = table.numerics[col]
            vals = nc.values_f64[rows].copy()
            vals[nc.null_mask[rows]] = np.nan
            return vals
        if col in table.raw:
            return RawOperand(table, col, rows)
        raise ResidualEvalError(f"Unknown column {col!r} in residual predicate")


def evaluate_residual(
    table: EncodedTable, residual: str, i: np.ndarray, j: np.ndarray
) -> np.ndarray:
    """Boolean keep-mask for candidate pairs (i, j) under the translated
    residual predicate, with SQL null semantics (UNKNOWN rows dropped)."""
    try:
        tree = ast.parse(residual, mode="eval")
    except SyntaxError as e:  # pragma: no cover - translation produces valid py
        raise ResidualEvalError(f"Cannot parse residual: {residual!r}") from e
    result = _Evaluator(table, i, j).bool_eval(tree.body)
    return result.known_true
