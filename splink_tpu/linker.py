"""User-facing linker: the TPU-native counterpart of the reference's Splink
class (/root/reference/splink/__init__.py:33-195).

Same API shape — ``Splink(settings, df=... | df_l=..., df_r=...)``,
``get_scored_comparisons()``, ``manually_apply_fellegi_sunter_weights()``,
``make_term_frequency_adjustments()``, ``save_model_as_json()`` and module
level ``load_from_json`` — but the inputs/outputs are pandas DataFrames and
the execution pipeline is: host encode -> host hash-join blocking -> device
gamma program -> one fused jitted EM -> device scoring, instead of generated
Spark SQL.
"""

from __future__ import annotations

import copy
import logging
import os
import warnings
from typing import Callable

import jax.numpy as jnp
import numpy as np

from .blocking import PairIndex, block_using_rules
from .check_types import check_types
from .data import EncodedTable, concat_tables, encode_table
from .em import (
    run_em,
    run_em_checkpointed,
    score_pairs,
    score_pairs_with_intermediates,
    score_pairs_with_intermediates_logits,
    score_pairs_with_logits,
)
from .gammas import GammaProgram, register_comparison  # noqa: F401 (re-export)
from .models.fellegi_sunter import FSParams
from .params import Params, load_params_from_json
from .parallel.mesh import mesh_from_settings, shard_pairs
from .settings import comparison_column_name, complete_settings_dict
from .utils.profiling import StageTimer

logger = logging.getLogger("splink_tpu")

# RAM caps (candidate counts) for keeping the virtual pass's per-candidate
# pattern ids for a later score stream: 2^32 uint16 ids = 8.6 GB, 2^31
# int32 ids = 8.6 GB. Above these the stream recomputes ids chunk-wise
# instead (virtual_materialise_ids="on" overrides).
_MAX_RESIDENT_IDS_U16 = 1 << 32
_MAX_RESIDENT_IDS_I32 = 1 << 31

_compilation_cache_applied: str | None = None


def _enable_compilation_cache(path, explicit: bool = False) -> None:
    """Point jax at a persistent XLA compilation cache directory.

    Re-jitting the same program shapes is the dominant cold-start cost on
    the TPU path (each per-rule virtual kernel or EM program costs tens
    of seconds to compile through a tunnelled device; BENCHMARKS.md
    config-1's 13.8s wall is mostly one EM compile). The cache persists
    compiled executables across PROCESSES, so a second run of the same
    job shapes skips straight to execution — the analogue of the
    reference's Spark reusing a warmed JVM.

    Precedence: a JAX_COMPILATION_CACHE_DIR env var wins outright (the
    setting is never applied over it); otherwise the FIRST linker in the
    process applies its setting and later linkers never re-apply — jax
    binds its cache object to the first directory it initialises with,
    so a mid-process dir change would make jax.config report one path
    while entries keep landing in another. Empty/None disables.

    On the CPU backend the cache directory is keyed by the host's
    target-feature fingerprint (``cpu-<fp16>/`` subdirectory,
    utils/envfp.py): XLA:CPU entries embed exact machine features and
    reloading one compiled under different target flags "could lead to
    SIGILL" (jax's own warning) — the fingerprint key means entries never
    cross CPU types, which is what makes the cache safe to leave ON for
    the CPU tier (it used to be accelerator-only by default; the serve
    warmup and cold-EM compiles the BENCHMARKS.md cold-start rounds
    measure are exactly what it now absorbs)."""
    global _compilation_cache_applied
    if not path:
        return
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        logger.debug(
            "JAX_COMPILATION_CACHE_DIR is set; leaving the env-configured "
            "compilation cache in place"
        )
        return
    path = os.path.expanduser(path)
    try:
        import jax

        if jax.default_backend() == "cpu":
            from .utils.envfp import cpu_target_fingerprint

            path = os.path.join(
                path, f"cpu-{cpu_target_fingerprint()[:16]}"
            )
    except Exception:  # noqa: BLE001 - backend probe must not fail init
        if not explicit:
            return
    if _compilation_cache_applied is not None:
        if _compilation_cache_applied != path:
            logger.debug(
                "compilation cache already initialised at %s; ignoring %s "
                "(first linker wins for the process)",
                _compilation_cache_applied, path,
            )
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache small programs too (the per-rule kernels are what
        # repeat) — but never clobber a user's own env-var tuning
        if "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES" not in os.environ:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5
            )
        _compilation_cache_applied = path
        logger.debug("persistent compilation cache at %s", path)
    except Exception as e:  # noqa: BLE001 - cache is an optimisation only
        logger.warning("compilation cache unavailable: %s", e)

try:  # pandas is required for the linker facade (not for the kernels)
    import pandas as pd
except ImportError:  # pragma: no cover
    pd = None


def _gamma_histograms(settings, G, weights=None, chunk: int = 1 << 22) -> dict:
    """Per-comparison-column gamma-level histogram (telemetry record):
    column name -> [count at level -1 (null), level 0, ..., level L-1].
    ``G`` is either the per-pair gamma matrix or — with ``weights`` (the
    pattern-count vector) — the pattern matrix. Chunked so the int64
    promotion temporaries stay O(chunk): the streamed regime reaches here
    with a G that is huge by definition, and observability must not
    multiply that path's host footprint."""
    cols = settings["comparison_columns"]
    acc = [np.zeros(int(col["num_levels"]) + 1, np.float64) for col in cols]
    for s in range(0, len(G), chunk):
        Gc = G[s : s + chunk]
        w = weights[s : s + chunk] if weights is not None else None
        for c, col in enumerate(cols):
            levels = int(col["num_levels"])
            g = np.asarray(Gc[:, c], np.int64) + 1  # -1 (null) -> bin 0
            acc[c] += np.bincount(
                np.clip(g, 0, levels), weights=w, minlength=levels + 1
            )[: levels + 1]
    return {
        comparison_column_name(col): [int(v) for v in acc[c]]
        for c, col in enumerate(cols)
    }


class Splink:
    @check_types
    def __init__(
        self,
        settings: dict,
        df=None,
        df_l=None,
        df_r=None,
        save_state_fn: Callable = None,
        spark=None,  # accepted and ignored: reference-API compatibility
    ):
        """TPU-native probabilistic data linker.

        Args:
            settings: splink settings dictionary (same schema as the
                reference plus TPU keys; see files/settings_jsonschema.json).
            df: the single input DataFrame when link_type == dedupe_only.
            df_l, df_r: the two inputs for link_only / link_and_dedupe.
            save_state_fn: callable(params, settings) run after every EM
                iteration — the restart hook for very large jobs
                (/root/reference/splink/iterate.py:54-55).
            spark: ignored (the reference's SparkSession slot).
        """
        # The persistent compilation cache is on for EVERY backend (the
        # CPU tier keys entries by target-feature fingerprint, see
        # _enable_compilation_cache). Completion never auto-fills this key
        # (settings.py): the default resolves lazily so a reused settings
        # dict never looks explicitly configured; explicit (non-default)
        # values are tracked only to survive a failed backend probe.
        from .validate import get_default_value

        _cache_default = get_default_value(
            "compilation_cache_dir", is_column_setting=False
        )
        _cache_explicit = (
            "compilation_cache_dir" in settings
            and settings["compilation_cache_dir"] != _cache_default
        )
        self.settings = complete_settings_dict(settings)
        backend = self.settings["backend"]
        if backend != "jax":  # schema enum also rejects; double-checked here
            raise ValueError(
                f"Unsupported backend {backend!r}: this build executes the "
                "compute path with jax/XLA only."
            )
        logger.debug("execution backend: %s", backend)
        self._float_dtype_cache = None
        self.params = Params(self.settings, complete=False)
        self.df = df
        self.df_l = df_l
        self.df_r = df_r
        self._n_left_released: int | None = None
        self.save_state_fn = save_state_fn
        self._check_args()
        # Per-run observability scope: stage timings and the profiler-trace
        # target are keyed by this run's id (a later linker no longer
        # clears or pollutes an earlier one's), and the telemetry context
        # is live iff settings["telemetry_dir"] is set — disabled, it adds
        # no host callbacks and compiled programs are unchanged.
        from .obs.runtime import RunContext
        from .utils.profiling import begin_run

        self._obs = RunContext.from_settings(self.settings)
        begin_run(self._obs.run_id, self.settings.get("profile_dir") or None)
        _cache_dir = self.settings.get("compilation_cache_dir")
        if _cache_dir is None:  # resolve the schema default lazily
            _cache_dir = _cache_default
        _enable_compilation_cache(_cache_dir, explicit=_cache_explicit)

        self._table: EncodedTable | None = None
        self._pairs: PairIndex | None = None
        self._G: np.ndarray | None = None
        self._G_dev = None  # device-resident copy (resident regime only)
        self._P: np.ndarray | None = None  # per-pair pattern ids (streamed)
        self._pattern_counts: np.ndarray | None = None
        self._pattern_program = None
        self._virtual = None  # pairgen.VirtualPlan (device pair generation)
        self._virtual_checked = False
        # per-candidate pattern ids from the virtual pass (sentinel kept),
        # materialised when a score stream is known to follow — one kernel
        # pass instead of two (see _virtual_ids_policy)
        self._P_virtual: np.ndarray | None = None
        self._virtual_want_ids = False
        self._pair_bound: int | None = None  # estimate_pair_upper_bound memo
        # last EMResult replayed into Params (EM diagnostics attach its
        # trimmed trajectory: per-iteration ll lives only device-side)
        self._last_em_result = None
        # memoised TF u-probability fold context (term_frequencies
        # docstring): (spec, token ids, log tables) or False = inactive
        self._tf_fold_cache = None
        # checkpoint/resume state for the current estimate_parameters call
        # (argument overrides; the settings keys are the fallback)
        self._ckpt_dir_arg: str | None = None
        self._ckpt_resume = False

    # ------------------------------------------------------------------

    @property
    def run_id(self) -> str:
        """This linker's telemetry/profiling run id (the key for
        ``utils.profiling.stage_timings(run=...)`` and the suffix of the
        run's telemetry JSONL file name)."""
        return self._obs.run_id

    def _stage(self, name: str) -> StageTimer:
        """A StageTimer bound to this linker's run scope: records wall
        time under this run id, resolves this run's profile_dir, and (when
        telemetry is enabled) emits the stage span with its
        compile-vs-execute split and a device-memory snapshot."""
        return StageTimer(name, run=self._obs.run_id, telemetry=self._obs)

    def _check_args(self):
        link_type = self.settings["link_type"]
        is_df = lambda x: pd is not None and isinstance(x, pd.DataFrame)  # noqa: E731
        if link_type == "dedupe_only":
            if not (is_df(self.df) and self.df_l is None and self.df_r is None):
                raise ValueError(
                    "For link_type = 'dedupe_only', pass a single DataFrame via "
                    "df=; omit df_l and df_r. e.g. Splink(settings, df=my_df)"
                )
        else:
            if not (is_df(self.df_l) and is_df(self.df_r) and self.df is None):
                raise ValueError(
                    f"For link_type = '{link_type}', pass two DataFrames via "
                    "df_l= and df_r=; omit df. "
                    "e.g. Splink(settings, df_l=first, df_r=second)"
                )

    # ------------------------------------------------------------------
    # Pipeline stages
    # ------------------------------------------------------------------

    @property
    def _float_dtype(self):
        """Resolved compute dtype for EM/scoring, honouring ``float64``.

        Resolved lazily (first compute) because checking the backend
        initialises it. float64 on a non-TPU backend enables jax x64 mode —
        a PROCESS-WIDE, irreversible switch (jax has no per-computation
        dtype mode); without it jax silently downcasts every float64 array
        to float32 and the setting would be a no-op. TPU has no float64, so
        there the setting warns and falls back to float32 as documented in
        the settings schema.
        """
        if self._float_dtype_cache is None:
            resolved = np.float32
            if self.settings["float64"]:
                import jax

                # resolve fully before caching: an exception here (flaky
                # backend init, interrupt) must not poison the cache with
                # the float32 fallback
                if jax.default_backend() == "tpu":
                    warnings.warn(
                        "float64 requested but the TPU backend has no "
                        "float64 support; running in float32"
                    )
                else:
                    if not jax.config.jax_enable_x64:
                        jax.config.update("jax_enable_x64", True)
                        logger.info(
                            "float64 requested: enabled jax x64 mode "
                            "(process-wide)"
                        )
                    resolved = np.float64
            self._float_dtype_cache = resolved
        return self._float_dtype_cache

    @property
    def _n_left(self) -> int | None:
        if self.settings["link_type"] == "dedupe_only":
            return None
        if self.df_l is not None:
            return len(self.df_l)
        return self._n_left_released

    def release_input(self) -> None:
        """Encode the input dataframe(s), then drop the linker's references to
        them so the raw pandas data can be garbage-collected by the caller.

        Everything downstream (blocking, scoring, retained output columns)
        reads from the columnar :class:`EncodedTable` built here, so the
        original frames are not needed again. Useful before streaming very
        large jobs to halve peak host memory.
        """
        self._ensure_encoded()
        if self.df_l is not None:
            self._n_left_released = len(self.df_l)
        self.df = None
        self.df_l = None
        self.df_r = None

    def _checkpoint_config(self):
        """(checkpoint_dir | None, resume, interval): the argument to
        estimate_parameters wins, else the settings keys."""
        ckpt_dir = self._ckpt_dir_arg or self.settings.get("checkpoint_dir") or None
        return (
            ckpt_dir,
            self._ckpt_resume,
            int(self.settings.get("checkpoint_interval", 5) or 5),
        )

    def _load_validated_checkpoint(self, ckpt_dir, state_hash, resume):
        """Resume's load/validate dance, shared by the fused and streamed
        paths: hash-checked load, cross-process presence agreement, then
        topology validation. Returns the checkpoint or None. Resume with
        no checkpoint on disk yet is the normal FIRST launch of a
        relaunch-loop harness, so it warns and trains fresh rather than
        raising."""
        if not resume:
            return None
        from .parallel.distributed import (
            validate_resume_presence,
            validate_resume_topology,
        )
        from .resilience.checkpoint import load_checkpoint

        ckpt = load_checkpoint(ckpt_dir, expect_hash=state_hash)
        validate_resume_presence(ckpt is not None)
        if ckpt is None:
            logger.warning(
                "resume=True but no checkpoint exists in %s yet; training "
                "from scratch (first launch of a relaunch loop?)",
                ckpt_dir,
            )
            return None
        validate_resume_topology(ckpt.process_count, state_hash, ckpt.iteration)
        return ckpt

    def _em_state_hash(self) -> str:
        from .resilience.checkpoint import settings_state_hash

        # bind the checkpoint to the input data as well as the settings:
        # identical settings over a different dataframe must NOT resume
        # (the histories would describe someone else's trajectory). The
        # encoded row count is a cheap fingerprint that catches the
        # common cases (new extract, different table) without hashing
        # multi-GB column data.
        table = self._ensure_encoded()
        return settings_state_hash(
            self.settings, extra={"n_rows": int(table.n_rows)}
        )

    def _ensure_encoded(self) -> EncodedTable:
        if self._table is None:
            # last rung of the degradation ladder: a dead accelerator
            # falls back to CPU (with a structured warning) before any
            # device work is attempted
            from .resilience.retry import ensure_devices

            ensure_devices()
            with self._stage("encode"):
                if self.settings["link_type"] == "dedupe_only":
                    self._table = encode_table(self.df, self.settings)
                else:
                    self._table = concat_tables(self.df_l, self.df_r, self.settings)
            self._obs.count("rows_encoded", int(self._table.n_rows))
        return self._table

    def _ensure_pairs(self) -> PairIndex:
        if self._pairs is None:
            table = self._ensure_encoded()
            build_dir = self.settings.get("build_spill_dir") or None
            if build_dir and self.settings.get("approx_blocking"):
                # the spill driver emits EXACT-rule pairs only; when the
                # approximate LSH tier can actually run, taking it would
                # silently drop every approx pair — the recall feature the
                # setting opts into (the same hazard gate _virtual_plan
                # applies to the virtual pair index)
                from .approx.lsh import approx_columns

                if approx_columns(self.settings, table):
                    from .utils.logging_utils import warn_degraded

                    warn_degraded(
                        "spill_blocking", "host_blocking",
                        "approx_blocking needs materialised blocking (the "
                        "spill emission driver has no approximate tier)",
                    )
                    build_dir = None
            if build_dir:
                # The durable write path (docs/blocking.md#offline-scale):
                # sharded, manifest-committed, RESUMABLE emission into the
                # caller-owned spill store. Overlap scoring is off here by
                # design — a resumed build skips committed segments, so no
                # per-chunk consumer can be fed consistently; the streamed
                # EM consumes the manifest afterwards instead.
                from .blocking_device import spill_block_rules
                from .parallel.distributed import spill_shard_dir

                with self._stage("blocking"):
                    pairs = spill_block_rules(
                        self.settings, table, self._n_left,
                        spill_shard_dir(build_dir),
                    )
                if pairs is not None:
                    self._pairs = pairs
                    logger.info(
                        "blocking produced %d candidate pairs (spill store)",
                        pairs.n_pairs,
                    )
                    self._obs.count("pairs_blocked", int(pairs.n_pairs))
                    from .blocking import clear_key_code_cache

                    clear_key_code_cache(table)
                    return self._pairs
                from .utils.logging_utils import warn_degraded

                warn_degraded(
                    "spill_blocking", "host_blocking",
                    "rule shapes unsupported by the device emission plan",
                )
            stream = self._overlap_stream(table)
            with self._stage("blocking"):
                self._pairs = block_using_rules(
                    self.settings,
                    table,
                    self._n_left,
                    pair_consumer=stream.feed if stream is not None else None,
                )
            logger.info("blocking produced %d candidate pairs", self._pairs.n_pairs)
            self._obs.count("pairs_blocked", int(self._pairs.n_pairs))
            if self._obs.enabled:
                # block-size skew telemetry rides the still-warm key-code
                # cache; freed with it just below
                from .blocking import block_size_stats

                self._obs.record(
                    "largest_blocks",
                    block_size_stats(self.settings, table, self._n_left),
                )
            self._maybe_spill_pairs()
            if stream is not None:
                self._finish_overlap(stream)
            from .blocking import clear_key_code_cache

            clear_key_code_cache(table)
        return self._pairs

    def _overlap_stream(self, table: EncodedTable):
        """Device-scoring consumer fed DURING blocking (VERDICT round 2 #2:
        end-to-end wall ≈ max(blocking, scoring), not their sum). jax
        dispatch is async, so the accelerator computes rule k's
        gammas/pattern ids while the host joins rule k+1; the second sweep
        over the (possibly disk-spilled) pair index disappears. Spark
        gets the same overlap from lazy evaluation
        (/root/reference/splink/blocking.py:210).

        The regime is chosen BEFORE blocking from a cheap O(n) upper bound
        on the pair count (per-rule key-group histograms): resident-size
        jobs stream the gamma matrix and keep it device-resident for EM
        (no pattern-decode/re-upload penalty); larger jobs stream 3-byte
        pattern ids, which serve both the streamed LUT regime and — decoded
        through the pattern matrix — the resident one if dedup shrank the
        run after all. Custom kernels and pattern-space overflow always
        take GammaStream."""
        if not self.settings.get("overlap_blocking", True):
            return None
        from .gammas import GammaStream, PatternStream

        program = GammaProgram(
            self.settings, table, float_dtype=self._float_dtype
        )
        mesh = mesh_from_settings(self.settings)
        max_resident = int(self.settings["max_resident_pairs"])
        bound = self._estimate_pair_bound(table)
        # clamp the device batch to the job bound (like the sequential
        # paths clamp to n) so a small job doesn't pad its single batch up
        # to pair_batch_size
        batch = int(self.settings["pair_batch_size"])
        batch = max(min(batch, -(-max(bound, 1) // 8) * 8), 1024)
        # _pattern_capable covers the custom-kernel and pattern-space
        # conditions; under a mesh the PatternStream shards its batches
        # over the data axis (gammas.PatternStream mesh support)
        if bound > max_resident and self._pattern_capable():
            self._pattern_program = program
            return PatternStream(program, batch, mesh=self._pattern_mesh())
        keep_limit = max_resident if mesh is None else 0
        return GammaStream(program, batch, keep_device_limit=keep_limit)

    def _finish_overlap(self, stream) -> None:
        from .gammas import PatternStream

        if isinstance(stream, PatternStream):
            with self._stage("gammas_patterns"):
                self._P, self._pattern_counts = stream.finish()
        else:
            with self._stage("gammas"):
                self._G, self._G_dev = stream.finish()

    def _maybe_spill_pairs(self) -> None:
        """Note the blocking-created spill dir (streamed regime): blocking's
        pair sink streams every pair chunk straight to disk-backed memmaps
        when spill_dir is set — rule path and cartesian fallback alike — so
        there is nothing left to copy here. The PairIndex owns the directory
        lifetime via its weakref finalizer; the stale-orphan sweep ran before
        any bytes were written."""
        if self._pairs.spill_tmp is not None:
            self._spill_tmp = self._pairs.spill_tmp
            logger.info("pair index spilled to %s (streamed)", self._spill_tmp)

    def _ensure_gammas(self) -> np.ndarray:
        if self._G is None:
            table = self._ensure_encoded()
            pairs = self._ensure_pairs()  # overlap may set _G or _P here
            if self._multihost_spill_store(pairs) is not None:
                # this process's store holds ONLY its shard subset — a
                # gamma matrix over it would feed scoring/EM paths that
                # assume the FULL pair set, silently producing divergent
                # parameters or subset-only output frames per controller.
                # Training is supported (estimate_parameters routes to the
                # manifest-fed streamed EM with cross-process reduction);
                # scoring output is a single-controller operation.
                raise RuntimeError(
                    "this pair index is a per-process spill shard subset "
                    "(multi-controller emission): scoring APIs need the "
                    "full pair set and are single-controller — train with "
                    "estimate_parameters here, then score in a "
                    "single-process run over the saved model"
                )
            if self._G is not None:
                return self._G
            if self._P is not None:
                # overlap streamed pattern ids but the run ended small
                # enough for the resident regime: decode the gamma matrix
                # from the pattern LUT (bit-identical to recomputation —
                # the pattern id IS the gamma vector in mixed radix)
                with self._stage("gammas"):
                    PM = self._pattern_program.patterns_matrix()
                    self._G = PM[self._P]  # fancy-index accepts uint16/int32
                return self._G
            # In the resident regime (and without a mesh, which shards its
            # own upload), keep the device-side gamma batches so EM doesn't
            # re-upload the matrix that was just computed there.
            keep = (
                pairs.n_pairs <= int(self.settings["max_resident_pairs"])
                and mesh_from_settings(self.settings) is None
            )
            with self._stage("gammas"):
                program = GammaProgram(
                    self.settings, table, float_dtype=self._float_dtype
                )
                self._G, self._G_dev = program.compute_with_device(
                    pairs.idx_l,
                    pairs.idx_r,
                    batch_size=self.settings["pair_batch_size"],
                    keep_device=keep,
                )
        return self._G

    def _pattern_capable(self) -> bool:
        """Static part of the pattern-pipeline test: bounded pattern space
        and no custom comparison kernels — a registered kernel could emit
        gammas outside [-1, num_levels-1], which would alias pattern ids.
        A mesh does NOT disqualify: both the virtual pair index
        (pairgen.make_virtual_pattern_fn) and the materialised pattern
        pass (GammaProgram._pattern_batch_for_mesh, PatternStream) shard
        their batches over the mesh's data axis."""
        from .gammas import MAX_PATTERNS, pattern_strides_for

        for c in self.settings["comparison_columns"]:
            if (c.get("comparison") or {}).get("kind") == "custom":
                return False
        level_counts = [
            int(c["num_levels"]) for c in self.settings["comparison_columns"]
        ]
        _, n_patterns = pattern_strides_for(level_counts)
        return n_patterns <= MAX_PATTERNS

    @property
    def device_pair_generation_active(self) -> bool:
        """Whether this run used (or will use) the virtual pair index —
        pairs decoded on device with no host materialisation. Public
        accessor for diagnostics/examples; the plan itself is internal."""
        return self._virtual_plan() is not None

    def _estimate_pair_bound(self, table: EncodedTable) -> int:
        if self._pair_bound is None:
            from .blocking import estimate_pair_upper_bound

            self._pair_bound = estimate_pair_upper_bound(
                self.settings, table, self._n_left
            )
        return self._pair_bound

    def _virtual_plan(self):
        """The device-pair-generation plan, or None (pairgen module
        docstring has the full story). Checked once: the plan build does
        the per-rule key/sort work host blocking would do anyway, so a
        rejected plan costs nothing extra overall."""
        if self._virtual_checked:
            return self._virtual
        self._virtual_checked = True
        mode = self.settings.get("device_pair_generation", "auto")
        if mode == "off" or not self._pattern_capable():
            return None
        if self.settings.get("approx_blocking"):
            # the virtual pair index enumerates EXACT-rule pairs only; the
            # approximate LSH tier emits through materialised blocking, so
            # taking the virtual path here would silently drop every
            # approx pair — the recall feature the setting opts into.
            # With no sketchable string column the tier is a no-op and
            # the virtual path loses nothing (same gate as
            # estimate_pair_upper_bound).
            from .approx.lsh import approx_columns

            if approx_columns(self.settings, self._ensure_encoded()):
                logger.info(
                    "device pair generation disabled: approx_blocking "
                    "needs materialised blocking (the virtual pair index "
                    "has no approximate tier)"
                )
                return None
        from .pairgen import build_virtual_plan

        table = self._ensure_encoded()
        if mode == "auto":
            # small jobs: the resident/overlap paths are already optimal
            bound = self._estimate_pair_bound(table)
            if bound <= int(self.settings["max_resident_pairs"]):
                return None
        with self._stage("pairgen_plan"):
            self._virtual = build_virtual_plan(
                self.settings, table, self._n_left
            )
        if self._virtual is not None:
            # the int64 key-code cache fed the estimator and the plan;
            # the plan keeps its own int32 copies — don't retain both
            from .blocking import clear_key_code_cache

            clear_key_code_cache(table)
            logger.info(
                "device pair generation: %d candidate positions, %d rules",
                self._virtual.n_candidates,
                len(self._virtual.rules),
            )
        return self._virtual

    def _use_pattern_pipeline(self) -> bool:
        """Whether the streamed pattern-id pipeline applies: device pair
        generation active, or a large materialised pair set with
        pattern-capable settings."""
        if self._virtual_plan() is not None:
            return True
        if not self._pattern_capable():
            return False
        pairs = self._ensure_pairs()
        if self._multihost_spill_store(pairs) is not None:
            # a per-process spill store's n_pairs is LOCAL and differs per
            # controller — a count-dependent regime choice here could put
            # controllers on different EM paths (one in a collective, one
            # not: deadlock). The manifest-fed streamed driver is the one
            # multi-controller-correct path for these stores, so the
            # decision is pinned deterministically (process_count is
            # identical in every store's meta).
            return False
        return pairs.n_pairs > int(self.settings["max_resident_pairs"])

    @staticmethod
    def _multihost_spill_store(pairs):
        """The pair index's spill store when it was written under
        MULTI-CONTROLLER emission (and therefore holds only this
        process's shard subset) — None otherwise."""
        store = getattr(pairs, "spill_store", None)
        if store is not None and (
            int(store.meta.get("process_count", 1) or 1) > 1
        ):
            return store
        return None

    def _pattern_mesh(self):
        """The mesh pattern passes shard over: the configured mesh on a
        single controller; None under multi-controller — the sharded
        passes device_put host-local full arrays onto the mesh, which is a
        single-controller layout. Each host then runs the full pattern
        pass on its own default device: duplicated device work, but no
        gamma matrix ever materialises and every host derives the same
        histogram/params (a host-sliced multi-controller pattern pass is
        future work)."""
        mesh = mesh_from_settings(self.settings)
        if mesh is None:
            return None
        import jax

        return mesh if jax.process_count() == 1 else None

    def _ensure_pattern_program(self) -> "GammaProgram":
        """The pattern-capable GammaProgram, built lazily. Scoring-only
        consumers (manual FS weights, the virtual score stream) need just
        the program — NOT the histogram pass _ensure_pattern_ids runs —
        so they must come through here to avoid a redundant device pass
        over every candidate pair."""
        if self._pattern_program is None:
            self._pattern_program = GammaProgram(
                self.settings,
                self._ensure_encoded(),
                float_dtype=self._float_dtype,
            )
        return self._pattern_program

    def _virtual_ids_policy(self) -> bool:
        """Should the virtual pattern pass ALSO materialise per-candidate
        ids? One pass (ids + histogram together) beats two (histogram-only
        EM pass, then an ids recompute inside the score stream) whenever a
        score stream is going to happen and the ids fit host RAM: the
        kernels run once instead of twice, and the downloads overlap the
        kernels either way. EM-only jobs keep the histogram-only pass —
        no per-pair bytes ever cross the link (~25x the kernel cost over
        a tunnelled device; scripts/virtual_breakdown.py)."""
        mode = self.settings.get("virtual_materialise_ids", "auto")
        if mode == "on":
            return True
        if mode == "off":
            return False
        if not self._virtual_want_ids:
            return False
        n = self._virtual.n_candidates
        from .gammas import pattern_ids_fit_uint16

        small = pattern_ids_fit_uint16(self._ensure_pattern_program().n_patterns)
        cap = _MAX_RESIDENT_IDS_U16 if small else _MAX_RESIDENT_IDS_I32
        if n > cap:
            return False
        # "fits host RAM" means the RAM actually free right now, not just
        # the hard cap: claim at most half of it, else stream chunk-wise
        try:
            avail = os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError, AttributeError):
            return True  # no probe on this platform; the cap still bounds
        return n * (2 if small else 4) <= avail // 2

    def _ensure_pattern_ids(self):
        """(pattern_ids, counts, program): ONE device pass over the pair
        index computing gammas, pattern ids and their histogram. The gamma
        matrix itself never materialises — per-pair state is a uint16/int32
        id, and every later stage (EM, scoring, output columns) derives from
        the ≤ prod(levels+1)-row pattern tables. This is also what keeps
        host<->device traffic to a single pass over the pairs."""
        if self._P is None:
            table = self._ensure_encoded()
            if self._virtual_plan() is not None:
                # device pair generation: pairs decode on device from the
                # plan's unit structure; nothing is materialised or
                # transferred per pair. Default is a histogram-ONLY pass
                # (EM needs nothing else); when a score stream is known to
                # follow, _virtual_ids_policy keeps the per-candidate ids
                # from this same pass so the stream is LUT-only.
                if self._pattern_counts is not None:
                    return None, self._pattern_counts, self._pattern_program
                from .pairgen import compute_virtual_pattern_ids

                with self._stage("gammas_patterns"):
                    self._ensure_pattern_program()
                    want_ids = self._virtual_ids_policy()
                    pids, self._pattern_counts, n_real = (
                        compute_virtual_pattern_ids(
                            self._pattern_program,
                            self._virtual,
                            int(self.settings["pair_batch_size"]),
                            mesh=self._pattern_mesh(),
                            return_ids=want_ids,
                        )
                    )
                    if want_ids:
                        self._P_virtual = pids
                logger.info(
                    "device pair generation scored %d pairs (%d candidate "
                    "positions)", n_real, self._virtual.n_candidates,
                )
                return None, self._pattern_counts, self._pattern_program
            pairs = self._ensure_pairs()
            if self._P is not None:
                # the overlap PatternStream already computed them
                return self._P, self._pattern_counts, self._pattern_program
            with self._stage("gammas_patterns"):
                self._pattern_program = GammaProgram(
                    self.settings, table, float_dtype=self._float_dtype
                )
                self._P, self._pattern_counts = (
                    self._pattern_program.compute_pattern_ids(
                        pairs.idx_l,
                        pairs.idx_r,
                        batch_size=self.settings["pair_batch_size"],
                        mesh=self._pattern_mesh(),
                    )
                )
        return self._P, self._pattern_counts, self._pattern_program

    def _tf_fold_ctx(self):
        """The offline TF u-probability fold context, memoised:
        ``(spec, tids, log_tables)`` — term_frequencies.tf_fold_spec
        entries restricted to the encoded string columns, each column's
        (n_rows,) token ids and its float64 log relative-frequency table
        (term_frequencies.tf_log_table, the SAME values the serve index
        gathers from). None when ``serve_tf_adjust`` is off or no flagged
        comparison has a token column — scored frames then carry no
        ``tf_match_probability`` column, exactly as before."""
        if self._tf_fold_cache is None:
            self._tf_fold_cache = False
            if self.settings.get("serve_tf_adjust", True):
                from .term_frequencies import tf_fold_spec, tf_log_table

                table = self._ensure_encoded()
                spec, tids, logs = [], [], []
                for ci, name, top in tf_fold_spec(self.settings):
                    sc = table.strings.get(name)
                    if sc is None or not sc.n_tokens:
                        continue
                    tid = sc.token_ids
                    counts = np.bincount(
                        tid[tid >= 0], minlength=sc.n_tokens
                    )
                    spec.append((ci, name, top))
                    tids.append(tid.astype(np.int32))
                    logs.append(tf_log_table(counts))
                if spec:
                    self._tf_fold_cache = (tuple(spec), tids, logs)
        return self._tf_fold_cache or None

    def _tf_fold_pairs(self, z, il, ir, ctx) -> np.ndarray:
        """TF-adjusted match probabilities for pairs (il, ir) from their
        match logits ``z`` — the offline half of the serve parity
        contract, evaluated by the SAME jitted fold expression the serve
        megakernel runs (term_frequencies.make_tf_fold_fn). Chunked like
        every other per-pair device pass."""
        from .term_frequencies import make_tf_fold_fn

        spec, tids, logs = ctx
        dtype = self._float_dtype
        fold = make_tf_fold_fn(spec)
        lam, m, u, _ = self.params.to_arrays(dtype=dtype)
        u_dev = jnp.asarray(u)
        logs_dev = [jnp.asarray(t.astype(dtype)) for t in logs]
        n = len(z)
        batch = min(int(self.settings["pair_batch_size"]), max(n, 1))
        out = np.empty(n, dtype)
        for s in range(0, n, batch):
            e = min(s + batch, n)
            args = [jnp.asarray(tid[il[s:e]]) for tid in tids]
            args += [jnp.asarray(tid[ir[s:e]]) for tid in tids]
            out[s:e] = np.asarray(
                fold(jnp.asarray(z[s:e]), u_dev, *args, *logs_dev)
            )
        return out

    def _pattern_score_luts(self):
        """Per-pattern lookup tables (host): match probability and, when
        intermediates are retained, per-column prob_m/prob_u — plus the
        match-logit LUT when the TF fold is active (the per-pair fold
        adds its delta to the pattern's logit; a pattern LUT of folded
        probabilities is impossible because the delta is a property of
        the PAIR's tokens, not its gamma pattern). Reuses the batched
        scoring path, which bounds HBM at any pattern count."""
        program = self._ensure_pattern_program()
        PM = program.patterns_matrix()
        dtype = self._float_dtype
        lam, m, u, _ = self.params.to_arrays(dtype=dtype)
        params_dev = FSParams(
            lam=jnp.asarray(lam), m=jnp.asarray(m), u=jnp.asarray(u)
        )
        p, pm, pu, z = self._score_batched(
            PM, params_dev, want_z=self._tf_fold_ctx() is not None
        )
        return PM, p, pm, pu, z

    def _stream_pattern_chunks(self):
        """Yield scored chunks from the pattern-id pipeline: one LUT gather
        + frame assembly per (il, ir, pattern-ids) chunk. The chunk source
        (stored virtual ids / virtual recompute / materialised pairs) is
        _iter_pattern_triples — the single definition of the pair stream."""
        PM, p_lut, pm_lut, pu_lut, z_lut = self._pattern_score_luts()
        with self._stage("score_patterns"):
            for il, ir, Pk in self._iter_pattern_triples():
                yield self._assemble_df_e(
                    PM[Pk],
                    il,
                    ir,
                    p_lut[Pk],
                    pm_lut[Pk] if pm_lut is not None else None,
                    pu_lut[Pk] if pu_lut is not None else None,
                    z=z_lut[Pk] if z_lut is not None else None,
                )

    def _iter_pattern_triples(self):
        """Yield (idx_l, idx_r, pattern_ids) per chunk across the pattern
        regimes — virtual with stored ids (host-only), virtual recompute
        (device pass), materialised pairs — with masked sentinels already
        filtered. The SINGLE definition of the pattern pair stream: the
        score stream assembles frames from it and the streaming TF
        adjustment drives it twice. (The virtual branch deliberately
        avoids _ensure_pattern_ids: scoring needs no histogram pass, e.g.
        under manual FS weights.)"""
        batch = int(self.settings["pair_batch_size"])
        if self._virtual_plan() is not None:
            from .pairgen import _virtual_pass_iter, decode_positions

            plan = self._virtual
            program = self._ensure_pattern_program()
            sentinel = program.n_patterns

            def decode(Pc, r, p0):
                keep = Pc != sentinel
                if not keep.any():
                    return None
                qs = p0 + np.flatnonzero(keep).astype(np.int64)
                il, ir, _ = decode_positions(
                    plan, r, qs, compute_masked=False
                )
                return il, ir, Pc[keep]

            P = self._P_virtual  # local: immune to concurrent release
            if P is not None:
                out_base = 0
                for r, rp in enumerate(plan.rules):
                    for p0 in range(0, rp.total, batch):
                        p1 = min(p0 + batch, rp.total)
                        t = decode(
                            P[out_base + p0 : out_base + p1].astype(
                                np.int32, copy=False
                            ),
                            r,
                            p0,
                        )
                        if t is not None:
                            yield t
                    out_base += rp.total
                return
            for r, p0, _, _n, chunk in _virtual_pass_iter(
                program, plan, batch, mesh=self._pattern_mesh()
            ):
                t = decode(chunk.astype(np.int32, copy=False), r, p0)
                if t is not None:
                    yield t
            return
        P, _, _ = self._ensure_pattern_ids()
        pairs = self._ensure_pairs()
        for s in range(0, len(P), batch):
            rows = slice(s, min(s + batch, len(P)))
            yield (
                pairs.idx_l[rows],
                pairs.idx_r[rows],
                P[rows].astype(np.int32, copy=False),
            )

    def stream_tf_adjusted_comparisons(self, compute_ll: bool = False):
        """Streaming term-frequency adjustment: the scale-free counterpart
        of ``get_scored_comparisons() -> make_term_frequency_adjustments``
        for outputs too large to materialise as one DataFrame.

        Runs EM, then TWO passes over the scored pattern stream: pass 1
        aggregates each flagged column's per-token mean match probability
        (the reference's grouped aggregate + broadcast join,
        /root/reference/splink/term_frequencies.py:49-95 — Spark gave it
        scale-out for free; here it is a chunked host aggregation over
        factorised token ids), pass 2 yields scored chunks with the
        per-column ``<col>_adj`` columns and ``tf_adjusted_match_prob``.
        Under device pair generation both passes are host-only LUT work
        when the EM pass kept its per-candidate ids
        (virtual_materialise_ids)."""
        from .term_frequencies import bayes_combine, term_frequency_columns

        tf_cols = list(term_frequency_columns(self.settings))
        if not self._use_pattern_pipeline():
            # resident regime: the one-frame path already exists
            df_e = self.get_scored_comparisons(compute_ll)
            yield self.make_term_frequency_adjustments(df_e)
            return
        if not tf_cols:
            warnings.warn(
                "No term frequency adjustment columns are specified in "
                "your settings object. Streaming unadjusted comparisons."
            )
            yield from self.stream_scored_comparisons(compute_ll)
            return
        self._virtual_want_ids = True
        # the try spans EVERYTHING from EM (which materialises the
        # potentially multi-GB per-candidate ids) onward: an exception in
        # the aggregation pass or a consumer abandoning/closing the
        # generator anywhere must not leak the ids
        try:
            self._run_em_patterns(compute_ll)
            table = self._ensure_encoded()
            cols: dict[str, tuple[np.ndarray, int]] = {}
            for name in tf_cols:
                sc = table.strings.get(name)
                if sc is not None:
                    cols[name] = (sc.token_ids, sc.n_tokens)
                    continue
                nc = table.numerics.get(name)
                if nc is not None:
                    # numeric TF column: factorise values on the fly (token =
                    # distinct value, the same grouping the one-frame host
                    # path applies to raw values); null -> -1
                    codes, uniq = pd.factorize(nc.values_f64)
                    codes = codes.astype(np.int32)
                    codes[nc.null_mask] = -1
                    cols[name] = (codes, len(uniq))
                    continue
                warnings.warn(
                    f"term-frequency column {name!r} is not an encoded "
                    "column; skipped in the streaming TF pass."
                )
            PM, p_lut, pm_lut, pu_lut, z_lut = self._pattern_score_luts()
            base_lambda = float(self.params.params["λ"])
            sums = {n: np.zeros(nt + 1) for n, (_, nt) in cols.items()}
            counts = {n: np.zeros(nt + 1) for n, (_, nt) in cols.items()}
            with self._stage("tf_aggregate_patterns"):
                for il, ir, Pk in self._iter_pattern_triples():
                    p = p_lut[Pk]
                    for name, (tid, _nt) in cols.items():
                        tl = tid[il]
                        agree = (tl == tid[ir]) & (tl >= 0)
                        np.add.at(sums[name], tl[agree], p[agree])
                        np.add.at(counts[name], tl[agree], 1.0)
            adjusted = {}
            for name in cols:
                # token lambda -> Bayes-combined with (1 - base lambda), the
                # same step as compute_token_adjustment
                lam_t = sums[name] / np.maximum(counts[name], 1.0)
                adjusted[name] = bayes_combine(
                    [lam_t, np.full(len(lam_t), 1.0 - base_lambda)]
                )
            with self._stage("score_tf_patterns"):
                for il, ir, Pk in self._iter_pattern_triples():
                    df = self._assemble_df_e(
                        PM[Pk],
                        il,
                        ir,
                        p_lut[Pk],
                        pm_lut[Pk] if pm_lut is not None else None,
                        pu_lut[Pk] if pu_lut is not None else None,
                        z=z_lut[Pk] if z_lut is not None else None,
                    )
                    adj_arrays = []
                    for name, (tid, _nt) in cols.items():
                        tl = tid[il]
                        agree = (tl == tid[ir]) & (tl >= 0)
                        adj = np.where(
                            agree, adjusted[name][np.where(agree, tl, 0)], 0.5
                        )
                        df[f"{name}_adj"] = adj
                        adj_arrays.append(adj)
                    df["tf_adjusted_match_prob"] = bayes_combine(
                        [df["match_probability"].to_numpy()] + adj_arrays
                    )
                    lead = ["tf_adjusted_match_prob", "match_probability"]
                    rest = [c for c in df.columns if c not in lead]
                    yield df[lead + rest]
        finally:
            # release on exhaustion AND on an abandoned/closed generator —
            # the ids can be multi-GB
            self._P_virtual = None
            self._obs.finish()

    def _run_em_patterns(self, compute_ll: bool) -> None:
        _, counts, program = self._ensure_pattern_ids()
        if int(counts.sum()) == 0:
            warnings.warn(
                "No candidate pairs to estimate from (blocking produced "
                "nothing); parameters are unchanged."
            )
            return
        patterns = program.patterns_matrix()
        seen = counts > 0
        logger.info(
            "pattern-compressed EM: %d pairs -> %d distinct gamma patterns",
            int(counts.sum()),
            int(seen.sum()),
        )
        self._obs.count("pairs_gamma_scored", int(counts.sum()))
        self._obs.gauge("gamma_patterns_distinct", int(seen.sum()))
        self._last_em_result = None  # same staleness guard as _run_em
        # always cheap here (the pattern matrix is small by construction);
        # feeds telemetry AND the EM diagnostics' level-support evidence
        hist = _gamma_histograms(self.settings, patterns, weights=counts)
        if self._obs.enabled:
            self._obs.record("gamma_histogram", hist)
        self._run_em_resident_weighted(patterns[seen], counts[seen], compute_ll)
        self._emit_em_diagnostics(hist)

    # ------------------------------------------------------------------
    # Public API (reference parity)
    # ------------------------------------------------------------------

    def _concat_chunks(self, chunks) -> "pd.DataFrame":
        """Concatenate streamed chunks; zero chunks (no candidates, or every
        position masked) is a valid empty result, not a pandas error."""
        chunks = list(chunks)
        if not chunks:
            return self._empty_df_e()
        return pd.concat(chunks, ignore_index=True)

    def _empty_df_e(self) -> "pd.DataFrame":
        n_cols = len(self.settings["comparison_columns"])
        zero = np.zeros(0)
        zero_cols = np.zeros((0, n_cols))
        return self._assemble_df_e(
            np.zeros((0, n_cols), np.int8),
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
            zero,
            zero_cols,
            zero_cols,
        )

    def manually_apply_fellegi_sunter_weights(self):
        """Score using the m/u values in the settings, without running EM
        (/root/reference/splink/__init__.py:111-119)."""
        if self._use_pattern_pipeline():
            df_e = self._concat_chunks(self._stream_pattern_chunks())
        else:
            G = self._ensure_gammas()
            df_e = self._build_df_e(G)
            self._G_dev = None  # release the HBM copy once scoring is done
        self._obs.count("pairs_scored_output", len(df_e))
        self._obs.finish()
        return df_e

    def estimate_parameters(
        self,
        compute_ll: bool = False,
        *,
        checkpoint_dir: str | os.PathLike | None = None,
        resume: bool = False,
    ) -> Params:
        """Train ONLY: run blocking/gammas/EM and return the fitted
        Params, producing no per-pair output. An extension beyond the
        reference (whose EM runs inside get_scored_comparisons,
        /root/reference/splink/__init__.py:121-145) for jobs where only
        the model is wanted: under device pair generation the whole run
        is the histogram-only pattern pass — zero per-pair bytes cross
        the host<->device link and nothing per-pair lands in host RAM.
        Score later (or in another process via save/load) with
        manually_apply_fellegi_sunter_weights or the streaming APIs.

        Args:
            compute_ll: archive the log likelihood per iteration.
            checkpoint_dir: snapshot EM state here every
                ``checkpoint_interval`` updates (atomic, versioned, bound
                to a settings hash — docs/resilience.md). Overrides the
                ``checkpoint_dir`` settings key.
            resume: continue from the checkpoint in ``checkpoint_dir``
                instead of training from the settings priors. A checkpoint
                written for different settings (hash mismatch) is rejected
                with CheckpointMismatchError; multi-controller runs also
                validate process-count/checkpoint agreement before
                continuing.
        """
        self._ckpt_dir_arg = os.fspath(checkpoint_dir) if checkpoint_dir else None
        self._ckpt_resume = bool(resume)
        if self._ckpt_resume and self._checkpoint_config()[0] is None:
            self._ckpt_resume = False
            raise ValueError(
                "resume=True requires a checkpoint directory: pass "
                "checkpoint_dir= or set the checkpoint_dir settings key."
            )
        try:
            if self._use_pattern_pipeline():
                self._run_em_patterns(compute_ll)
            else:
                pairs = self._ensure_pairs()
                store = getattr(pairs, "spill_store", None)
                # A store written under multi-controller emission holds
                # only THIS process's shard subset, so the manifest-fed
                # driver (whose cross-process stats reduction forms the
                # global aggregate) is the ONLY correct EM path for it —
                # and the branch must not depend on the LOCAL pair count,
                # which differs per process and would split controllers
                # across collective/non-collective regimes (deadlock) or
                # train each on its own subset without reduction.
                # process_count is identical in every per-process store's
                # meta, so this decision is globally consistent.
                if store is not None and (
                    self._multihost_spill_store(pairs) is not None
                    or pairs.n_pairs
                    > int(self.settings["max_resident_pairs"])
                ):
                    # spill-store-backed pairs past the resident cap: EM
                    # consumes the manifest directly — gammas per chunk on
                    # device, never rematerialised host-side
                    self._run_em_streamed_spill(pairs, compute_ll)
                else:
                    G = self._ensure_gammas()
                    self._run_em(G, compute_ll)
                    self._G_dev = None
        finally:
            self._ckpt_dir_arg = None
            self._ckpt_resume = False
            self._obs.finish()
        return self.params

    def get_scored_comparisons(self, compute_ll: bool = False):
        """Estimate parameters by EM and return scored comparisons
        (/root/reference/splink/__init__.py:121-145).

        When the candidate-pair count exceeds ``max_resident_pairs`` the
        pipeline switches to the pattern-id regime: one device pass encodes
        each pair's gamma vector as a mixed-radix pattern id and histograms
        them, EM runs on the weighted pattern matrix, and scoring is a host
        LUT gather — pair data crosses the host<->device link exactly once.
        """
        if self._use_pattern_pipeline():
            # scoring follows EM here, so the virtual pass may keep its
            # per-candidate ids and make the stream LUT-only (one kernel
            # pass instead of two)
            self._virtual_want_ids = True
            self._run_em_patterns(compute_ll)
            df_e = self._concat_chunks(self._stream_pattern_chunks())
            # the single-frame output is materialised — release the ids
            # (same convention as _G_dev below); a later re-stream simply
            # recomputes them chunk-wise
            self._P_virtual = None
        else:
            G = self._ensure_gammas()
            self._run_em(G, compute_ll)
            df_e = self._build_df_e(G)
            self._G_dev = None  # release the HBM copy once EM + scoring are done
        self._obs.count("pairs_scored_output", len(df_e))
        self._obs.finish()
        return df_e

    def _run_em(self, G: np.ndarray, compute_ll: bool) -> None:
        """Dispatch EM to the resident or streamed regime by pair count.

        A device OOM on the resident path (the gamma matrix plus EM
        workspace outgrew HBM) degrades to the streamed regime — same
        update math over host-batched uploads — instead of crashing the
        run (docs/resilience.md degradation ladder)."""
        from .resilience import active_plan, is_oom
        from .utils.logging_utils import warn_degraded

        self._obs.count("pairs_gamma_scored", len(G))
        # a stale result from an earlier call must not attach its
        # trajectory to this run's diagnostics (the streamed/checkpointed
        # paths replay history without going through _replay_history)
        self._last_em_result = None
        # the gamma histogram doubles as the EM diagnostics' level-support
        # evidence (obs/quality.em_diagnostics) and as the quality
        # profile's raw material; in the resident regime it is cheap
        # relative to the gamma computation that just ran, so compute it
        # there unconditionally — the huge streamed-with-telemetry-off
        # case alone skips it (diagnostics then omit support counts)
        hist = None
        if self._obs.enabled or len(G) <= int(
            self.settings["max_resident_pairs"]
        ):
            hist = _gamma_histograms(self.settings, G)
            if self._obs.enabled:
                self._obs.record("gamma_histogram", hist)
        if len(G) > int(self.settings["max_resident_pairs"]):
            self._run_em_streamed(G, compute_ll)
            self._emit_em_diagnostics(hist)
            return
        # the resident attempt may replay completed updates into
        # self.params (checkpoint boundaries / save_state_fn) before it
        # OOMs; the fallback must restart from the PRE-attempt state or
        # those updates would be applied twice
        params_snapshot = copy.deepcopy(self.params)
        try:
            active_plan(self.settings).fire("resident_em", pairs=len(G))
            self._run_em_resident(G, compute_ll)
        except Exception as e:  # noqa: BLE001 - is_oom() decides
            if not is_oom(e):
                raise
            self.params = params_snapshot
            warn_degraded(
                "resident_em", "streamed_em", f"{type(e).__name__}: {e}",
                pairs=len(G),
            )
            self._run_em_streamed(G, compute_ll)
        self._emit_em_diagnostics(hist)

    def _run_em_resident(self, G: np.ndarray, compute_ll: bool) -> None:
        """Fused on-device EM with the gamma matrix resident in HBM."""
        dtype = self._float_dtype
        mesh = mesh_from_settings(self.settings)
        weights = None
        if mesh is not None:
            G_dev, weights = shard_pairs(mesh, G)
            weights = weights.astype(dtype)
        else:
            G_dev = self._G_dev if self._G_dev is not None else jnp.asarray(G)
        self._run_em_fused(G_dev, weights, compute_ll)

    def _run_em_fused(self, G_dev, weights, compute_ll: bool) -> None:
        """Shared fused-EM driver: whole-run while_loop normally, stepped one
        update at a time when a save_state_fn checkpoint hook must run
        between iterations (the restart semantics of
        /root/reference/splink/iterate.py:54-55)."""
        dtype = self._float_dtype
        lam0, m0, u0, _ = self.params.to_arrays(dtype=dtype)
        init = FSParams(lam=jnp.asarray(lam0), m=jnp.asarray(m0), u=jnp.asarray(u0))
        max_iterations = int(self.settings["max_iterations"])
        em_kwargs = dict(
            max_levels=self.params.max_levels,
            em_convergence=self.settings["em_convergence"],
            weights=weights,
            compute_ll=compute_ll,
        )

        ckpt_dir, resume, interval = self._checkpoint_config()
        tel = self._obs if self._obs.enabled else None
        with self._stage("em"):
            # inside the stage span so em_begin captures it as the parent
            # of every em_iteration span
            if tel is not None:
                tel.em_begin("fused", lam0, m0, u0)
            if ckpt_dir is not None:
                converged = self._run_em_fused_checkpointed(
                    G_dev, init, max_iterations, em_kwargs, ckpt_dir,
                    resume, interval, compute_ll,
                )
            elif self.save_state_fn is None:
                if tel is not None:
                    # same compiled loop with the host-hook io_callback on:
                    # per-update convergence records stream out through it,
                    # the dataflow (and so the trajectory) is untouched
                    result = run_em_checkpointed(
                        G_dev, init, max_iterations=max_iterations,
                        telemetry=tel, **em_kwargs,
                    )
                else:
                    result = run_em(
                        G_dev, init, max_iterations=max_iterations, **em_kwargs
                    )
                self._replay_history(result, compute_ll)
                converged = bool(result.converged)
            else:
                converged = False
                params_dev = init
                for k in range(max_iterations):
                    result = run_em(G_dev, params_dev, max_iterations=1, **em_kwargs)
                    params_dev = result.params
                    self._replay_history(result, compute_ll)
                    if tel is not None:
                        tel.em_update(
                            k + 1,
                            float(result.lam_history[1]),
                            np.asarray(result.m_history[1]),
                            np.asarray(result.u_history[1]),
                            float(result.ll_history[0]) if compute_ll else None,
                            bool(result.converged),
                        )
                    self.save_state_fn(self.params, self.settings)
                    if bool(result.converged):
                        converged = True
                        break
        if converged:
            logger.info("EM algorithm has converged")

    def _run_em_fused_checkpointed(
        self, G_dev, init, max_iterations, em_kwargs, ckpt_dir, resume,
        interval, compute_ll,
    ) -> bool:
        """Checkpointed resident EM: em.run_em_checkpointed runs the ONE
        compiled while_loop with an in-loop host hook that writes an
        atomic checkpoint every ``interval`` updates — bit-identical
        trajectory, plus durable resume. History replays into the Params
        object incrementally at each boundary (so save_state_fn sees the
        same per-update cadence as the stepped driver, at boundary
        granularity; both run on the callback thread and must stay
        host-side) and resumed iterations replay from the checkpoint's
        histories."""
        from .resilience import active_plan

        state_hash = self._em_state_hash()
        ckpt = self._load_validated_checkpoint(ckpt_dir, state_hash, resume)
        if self.save_state_fn is not None:
            logger.warning(
                "checkpoint_dir moves save_state_fn onto the compiled "
                "loop's host-callback thread (called at checkpoint "
                "boundaries, mid-program): the hook must stay host-side "
                "work — dispatching jax computation from it can deadlock "
                "the running program."
            )
        replayed = 0

        def replay(done, hist):
            nonlocal replayed
            self._replay_em_history(
                hist["lam"], hist["m"], hist["u"], hist["ll"],
                replayed, done, compute_ll,
            )
            replayed = done

        def on_segment(done, hist, _converged):
            replay(done, hist)
            if self.save_state_fn is not None:
                self.save_state_fn(self.params, self.settings)

        result = run_em_checkpointed(
            G_dev,
            init,
            max_iterations=max_iterations,
            checkpoint_dir=ckpt_dir,
            state_hash=state_hash,
            checkpoint_every=interval,
            resume=resume,
            resume_checkpoint=ckpt,
            fault_plan=active_plan(self.settings),
            on_segment=on_segment,
            telemetry=self._obs if self._obs.enabled else None,
            **em_kwargs,
        )
        # a resume that was already complete runs zero segments; catch up
        # from the result's (checkpoint-restored) histories
        n_updates = int(result.n_updates)
        replay(
            n_updates,
            {
                "lam": result.lam_history,
                "m": result.m_history,
                "u": result.u_history,
                "ll": result.ll_history,
            },
        )
        if compute_ll and not np.isnan(result.ll_history[n_updates]):
            self.params.params["log_likelihood"] = float(
                result.ll_history[n_updates]
            )
            self.params.log_likelihood_exists = True
        return bool(result.converged)

    def _run_em_streamed(self, G: np.ndarray, compute_ll: bool) -> None:
        """Streaming EM over host-resident gamma micro-batches.

        Reached only when the pattern-id pipeline declined the job (mesh set,
        custom kernels, or a pattern space past MAX_PATTERNS) — otherwise
        large pair sets never materialise G at all (_run_em_patterns)."""
        self._run_em_streamed_stats(G, compute_ll)

    def _run_em_resident_weighted(
        self, G_pat: np.ndarray, weights: np.ndarray, compute_ll: bool
    ) -> None:
        """Fused EM on a weighted pattern matrix (counts as weights)."""
        dtype = self._float_dtype
        self._run_em_fused(
            jnp.asarray(G_pat), jnp.asarray(weights.astype(dtype)), compute_ll
        )

    def _run_em_streamed_stats(self, G: np.ndarray, compute_ll: bool) -> None:
        """Streaming EM accumulating sufficient statistics per pass — the
        fallback when the pattern space is too large for a dense histogram,
        and the mesh path (stats psum across devices).

        Under a multi-controller run (jax.process_count() > 1) each host
        streams only its global_pair_slice of the pair set and the
        per-pass sufficient statistics reduce across processes with
        all_sum_stats (one allgather per pass — the path proven
        bit-compatible with a single process by
        tests/test_multiprocess_em.py), like every host's Spark executor
        reading its own partitions."""
        import jax

        from .parallel.distributed import global_pair_slice

        if jax.process_count() > 1:
            G = G[global_pair_slice(len(G))]
        batch = int(self.settings["pair_batch_size"])

        def batches():
            for s in range(0, len(G), batch):
                yield G[s : s + batch]

        self._run_em_streamed_driver(batches, compute_ll)

    def _run_em_streamed_spill(self, pairs: PairIndex, compute_ll: bool) -> None:
        """Manifest-fed streamed EM: the spill store IS the pair stream.

        Each EM pass walks the committed pair range of the store's memmaps
        in ``pair_batch_size`` slices, computes that slice's gamma block on
        device (GammaProgram.iter_gamma_chunks — same batching, padding
        and overflow semantics as the resident paths) and feeds it to
        run_em_streamed. The gamma matrix NEVER materialises on the host:
        at billions of pairs even the int8 G is tens of GB, which is what
        capped the old write path. Multi-controller runs stream only their
        global_pair_slice of the manifest and reduce stats with
        all_sum_stats, exactly like the materialised path. Trajectory is
        bit-identical to a (hypothetical) resident streamed run over the
        same pair order — batch boundaries match by construction."""
        import jax

        from .parallel.distributed import global_pair_slice
        from .spill import iter_spill_gamma_batches

        store = pairs.spill_store
        program = GammaProgram(
            self.settings, self._ensure_encoded(),
            float_dtype=self._float_dtype,
        )
        batch = int(self.settings["pair_batch_size"])
        pair_range = None
        if (
            jax.process_count() > 1
            and int(store.meta.get("process_count", 1) or 1) == 1
        ):
            # a SHARED single-writer store consumed by many controllers
            # slices like a materialised G; a per-process store (written
            # under multi-controller emission) already holds only this
            # host's shard subset — streaming it whole IS the local slice
            pair_range = global_pair_slice(store.total_pairs)

        def batches():
            return iter_spill_gamma_batches(
                store, program, batch, pair_range=pair_range
            )

        self._obs.count("pairs_gamma_scored", int(store.total_pairs))
        self._last_em_result = None
        logger.info(
            "spill-fed streamed EM over %d pairs (%d manifest segments)",
            store.total_pairs, len(store.segments),
        )
        self._run_em_streamed_driver(batches, compute_ll)
        self._emit_em_diagnostics(None)

    def _run_em_streamed_driver(self, batches, compute_ll: bool) -> None:
        """The shared streamed-EM driver: checkpoint/resume plumbing,
        telemetry and the run_em_streamed call over any re-iterable batch
        factory — the materialised G path and the spill-manifest path
        differ ONLY in where their gamma batches come from."""
        import jax

        from .parallel.streaming import run_em_streamed
        from .resilience import RetryPolicy, active_plan
        from .resilience.checkpoint import EMCheckpointer

        dtype = self._float_dtype
        lam0, m0, u0, _ = self.params.to_arrays(dtype=dtype)
        init = FSParams(lam=jnp.asarray(lam0), m=jnp.asarray(m0), u=jnp.asarray(u0))
        mesh = mesh_from_settings(self.settings)
        stats_reduce = None
        if jax.process_count() > 1:
            from .parallel.distributed import all_sum_stats

            # host-local mesh shardings don't span controllers; the
            # explicit cross-process reduction is what makes each host's
            # partial stats a global aggregate (the caller already
            # restricted its stream to this host's global_pair_slice)
            mesh = None
            stats_reduce = all_sum_stats

        # checkpoint/resume plumbing (docs/resilience.md): the streamed
        # driver exposes progress through on_iteration, so checkpointing
        # is a hook — and resume is (restored init params, start_iteration)
        ckpt_dir, resume, interval = self._checkpoint_config()
        start_iteration = 0
        checkpointer = None
        if ckpt_dir is not None:
            state_hash = self._em_state_hash()
            ckpt = self._load_validated_checkpoint(ckpt_dir, state_hash, resume)
            if ckpt is not None:
                lam_r, m_r, u_r = ckpt.params_arrays()
                init = FSParams(
                    lam=jnp.asarray(lam_r.astype(dtype)),
                    m=jnp.asarray(m_r.astype(dtype)),
                    u=jnp.asarray(u_r.astype(dtype)),
                )
                start_iteration = min(
                    ckpt.iteration, int(self.settings["max_iterations"])
                )
                # replay the pre-interruption history into the Params
                # object so the final state is indistinguishable from an
                # uninterrupted run's
                h = ckpt.history_arrays()
                self._replay_em_history(
                    h["lam"], h["m"], h["u"], h["ll"],
                    0, start_iteration, compute_ll,
                )
            checkpointer = EMCheckpointer(
                ckpt_dir,
                state_hash,
                interval=interval,
                process_count=jax.process_count(),
                write=jax.process_index() == 0,
                dtype=np.dtype(dtype).name,
            ).start(init, from_checkpoint=ckpt)
            if ckpt is not None and ckpt.converged:
                # training already completed before the interruption —
                # resuming would append a spurious extra update
                logger.info(
                    "checkpoint at iteration %d is already converged; "
                    "nothing to resume", ckpt.iteration,
                )
                return

        tel = self._obs if self._obs.enabled else None

        def on_iteration(it, params_dev, ll, converged_now=False):
            if compute_ll and ll is not None:
                self.params.params["log_likelihood"] = float(ll)
                self.params.log_likelihood_exists = True
            self.params.update_from_arrays(
                float(params_dev.lam),
                np.asarray(params_dev.m),
                np.asarray(params_dev.u),
            )
            # checkpoint BEFORE save_state_fn and the em_iteration fault
            # site: an injected kill at iteration N must find update N
            # already durable (the kill-and-resume contract)
            if checkpointer is not None:
                checkpointer.on_iteration(
                    it, params_dev, ll, converged=converged_now
                )
            if self.save_state_fn is not None:
                self.save_state_fn(self.params, self.settings)

        with self._stage("em_streamed"):
            # inside the stage span so em_begin captures it as the parent
            # of every em_iteration span
            if tel is not None:
                tel.em_begin(
                    "streamed",
                    float(np.asarray(init.lam)),
                    np.asarray(init.m),
                    np.asarray(init.u),
                    start_iteration=start_iteration,
                )
            _, _, _, converged = run_em_streamed(
                batches,
                init,
                max_iterations=int(self.settings["max_iterations"]),
                max_levels=self.params.max_levels,
                em_convergence=self.settings["em_convergence"],
                mesh=mesh,
                compute_ll=compute_ll,
                on_iteration=on_iteration,
                stats_reduce=stats_reduce,
                start_iteration=start_iteration,
                retry_policy=RetryPolicy(),
                fault_plan=active_plan(self.settings),
                telemetry=tel,
            )
        if checkpointer is not None:
            checkpointer.finish(converged)
        if converged:
            logger.info("EM algorithm has converged")

    def stream_scored_comparisons(self, compute_ll: bool = False):
        """Streaming variant of get_scored_comparisons for outputs too large
        to materialise as one DataFrame: runs (streamed) EM, then yields
        scored-comparison DataFrame chunks of ``pair_batch_size`` pairs.

        The reference returns a lazy Spark DataFrame at any scale
        (/root/reference/splink/__init__.py:121-145); chunked emission is the
        single-host equivalent — each chunk can be appended to parquet etc.
        """
        if self._use_pattern_pipeline():
            # scoring follows EM: let the virtual pass keep its ids (the
            # auto policy still bounds them against available RAM)
            self._virtual_want_ids = True
            self._run_em_patterns(compute_ll)
            try:
                yield from self._stream_pattern_chunks()
            finally:
                # release the (potentially multi-GB) ids on exhaustion AND
                # on an abandoned/closed generator — same convention as the
                # one-frame path; a re-stream simply recomputes chunk-wise
                self._P_virtual = None
                self._obs.finish()
            return
        G = self._ensure_gammas()
        self._run_em(G, compute_ll)
        yield from self.stream_scored_comparisons_after_em()
        self._obs.finish()

    def stream_scored_comparisons_after_em(self):
        """Yield scored-comparison chunks using the current parameters
        (EM — or a loaded model — already applied); see
        stream_scored_comparisons."""
        if self._use_pattern_pipeline():
            yield from self._stream_pattern_chunks()
            return
        G = self._ensure_gammas()
        batch = int(self.settings["pair_batch_size"])
        for s in range(0, len(G), batch):
            yield self._build_df_e(G, slice(s, min(s + batch, len(G))))

    def _replay_em_history(
        self, lam_h, m_h, u_h, ll_h, from_k: int, to_k: int, compute_ll: bool
    ) -> None:
        """Apply history updates ``from_k+1 .. to_k`` into the Params
        object (per update: archive the pre-update log likelihood at
        index k-1, then update_from_arrays) — the ONE replay loop behind
        plain-result installation, checkpoint-boundary replay and resume
        (history layout: index i = params before update i+1; ll index i =
        log likelihood under params i, NaN = not computed)."""
        for k in range(from_k + 1, to_k + 1):
            if (
                compute_ll
                and ll_h is not None
                and not np.isnan(ll_h[k - 1])
            ):
                self.params.params["log_likelihood"] = float(ll_h[k - 1])
                self.params.log_likelihood_exists = True
            self.params.update_from_arrays(
                float(lam_h[k]), np.asarray(m_h[k]), np.asarray(u_h[k])
            )

    def _emit_em_diagnostics(self, gamma_hist: dict | None) -> None:
        """Offline EM diagnostics (obs/quality.em_diagnostics): final
        m/u/Bayes-factor table with identifiability warnings — levels
        with ~zero training support, levels where m~=u — logged as
        warnings and emitted as one ``em_diagnostics`` telemetry event
        (rendered by ``obs summarize``). Never raises into the run."""
        try:
            from .em import trimmed_trajectory
            from .obs.quality import em_diagnostics

            diag = em_diagnostics(self.params, gamma_hist)
            if self._last_em_result is not None:
                # the device-side trajectory carries the per-iteration
                # log likelihood the Params history cannot reconstruct
                diag["run"] = trimmed_trajectory(self._last_em_result)
            for w in diag["warnings"]:
                logger.warning("EM identifiability: %s", w)
            self._obs.emit_event("em_diagnostics", **diag)
        except Exception as e:  # noqa: BLE001 - diagnostics are best-effort
            logger.warning("EM diagnostics failed: %s", e)

    def _replay_history(self, result, compute_ll: bool) -> None:
        """Install a run_em result's device-side history into the Params
        object so history, convergence logging, charts and save/load match
        the reference's per-iteration bookkeeping."""
        self._last_em_result = result
        n_updates = int(result.n_updates)
        ll_hist = np.asarray(result.ll_history)
        self._replay_em_history(
            result.lam_history,
            result.m_history,
            result.u_history,
            ll_hist,
            0,
            n_updates,
            compute_ll,
        )
        if compute_ll and not np.isnan(ll_hist[n_updates]):
            self.params.params["log_likelihood"] = float(ll_hist[n_updates])
            self.params.log_likelihood_exists = True

    def make_term_frequency_adjustments(self, df_e):
        """Ex-post term-frequency adjustment of scored comparisons
        (/root/reference/splink/__init__.py:147-163).

        When df_e still corresponds row-for-row to this linker's pair index,
        the per-token aggregation runs on device over the encoded table's
        factorised token ids (segment_sum) instead of a host groupby."""
        from .term_frequencies import (
            make_adjustment_for_term_frequencies,
            term_frequency_columns,
        )

        pair_token_ids = None
        if self._pairs is not None and self._df_e_aligned_with_pairs(df_e):
            table = self._ensure_encoded()
            pair_token_ids = {}
            for name in term_frequency_columns(self.settings):
                if name in table.strings:
                    tid = table.strings[name].token_ids
                    pair_token_ids[name] = (
                        tid[self._pairs.idx_l],
                        tid[self._pairs.idx_r],
                        table.strings[name].n_tokens,
                    )

        return make_adjustment_for_term_frequencies(
            df_e,
            self.params,
            self.settings,
            retain_adjustment_columns=True,
            pair_token_ids=pair_token_ids,
        )

    def _df_e_aligned_with_pairs(self, df_e) -> bool:
        """Whether df_e still corresponds row-for-row to the pair index (the
        fast device-side TF path needs this; a user-sorted or filtered frame
        falls back to the host groupby path)."""
        n = self._pairs.n_pairs
        if len(df_e) != n or not df_e.index.equals(pd.RangeIndex(n)):
            return False
        uid = self.settings["unique_id_column_name"]
        cols = (f"{uid}_l", f"{uid}_r")
        if not all(c in df_e.columns for c in cols):
            return False
        table = self._ensure_encoded()
        # Full-column comparison: a sampled check could miss a small
        # permutation and silently misattribute probabilities to token ids.
        for c, idx in zip(cols, (self._pairs.idx_l, self._pairs.idx_r)):
            want = np.asarray(table.unique_id[idx])
            got = df_e[c].to_numpy()
            if not np.array_equal(got, want):
                return False
        return True

    def close_telemetry(self) -> None:
        """End this linker's telemetry record now: closes the JSONL sink
        and unregisters it from the ambient (resilience-event) publisher,
        so a long-lived caller holding many linkers doesn't fan every
        later run's events into earlier records. Happens automatically
        when the linker is garbage-collected; no-op when telemetry is
        disabled or already closed."""
        self._obs.close()

    @check_types
    def save_model_as_json(self, path: str | os.PathLike, overwrite: bool = False):
        self.params.save_params_to_json_file(path, overwrite=overwrite)

    def export_index(self, path: str | os.PathLike | None = None):
        """Freeze this linker into an online-serving artifact
        (:class:`splink_tpu.serve.LinkageIndex`): the encoded input table
        as the packed reference matrix, a per-blocking-rule hash-bucket
        index, the CURRENT parameters (train first — or load a model) and
        the term-frequency tables. With ``path`` the artifact is also
        persisted (atomic, versioned, hash-bound — docs/serving.md);
        either way the built index is returned, ready for
        ``splink_tpu.serve.QueryEngine``."""
        from .serve.index import build_index

        with self._stage("export_index"):
            index = build_index(self)
            if path is not None:
                index.save(path)
        return index

    # ------------------------------------------------------------------
    # Output assembly
    # ------------------------------------------------------------------

    def _score_batched(self, G: np.ndarray, params_dev: FSParams,
                       want_z: bool = False):
        """Score in pair_batch_size device batches (padded to one compiled
        shape), so output assembly never pushes more than a batch of the
        gamma matrix plus its (n, C) float intermediates into HBM.

        The per-column prob_m/prob_u intermediates are only computed and
        transferred when retain_intermediate_calculation_columns is set —
        the default path downloads just the (n,) probabilities; ``want_z``
        additionally downloads the match logits (the TF fold's input;
        sigmoid of the logit is the probability bit for bit). Batches are
        double-buffered: batch k+1 dispatches before batch k's download."""
        n = len(G)
        batch = min(int(self.settings["pair_batch_size"]), max(n, 1))
        n_cols = G.shape[1] if G.ndim == 2 else 0
        want_inter = bool(self.settings["retain_intermediate_calculation_columns"])
        out_dtype = self._float_dtype
        # Device copy is reusable only when scoring the exact same full matrix
        src_dev = self._G_dev if self._G_dev is not None and G is self._G else None
        p = np.empty(n, out_dtype)
        z = np.empty(n, out_dtype) if want_z else None
        if want_inter:
            prob_m = np.empty((n, n_cols), out_dtype)
            prob_u = np.empty((n, n_cols), out_dtype)
        else:
            prob_m = prob_u = None
        pending = None  # (start, stop, device results)
        for s in range(0, n, batch):
            stop = min(s + batch, n)
            Gb = src_dev[s:stop] if src_dev is not None else jnp.asarray(G[s:stop])
            if stop - s < batch:
                Gb = jnp.concatenate(
                    [Gb, jnp.zeros((batch - (stop - s), n_cols), Gb.dtype)]
                )
            if want_inter and want_z:
                res = score_pairs_with_intermediates_logits(Gb, params_dev)
            elif want_inter:
                res = score_pairs_with_intermediates(Gb, params_dev)
            elif want_z:
                res = score_pairs_with_logits(Gb, params_dev)
            else:
                res = (score_pairs(Gb, params_dev),)
            res = tuple(r[: stop - s] for r in res)
            if pending is not None:
                self._drain_score_batch(pending, p, prob_m, prob_u, z)
            pending = (s, stop, res)
        if pending is not None:
            self._drain_score_batch(pending, p, prob_m, prob_u, z)
        return p, prob_m, prob_u, z

    @staticmethod
    def _drain_score_batch(pending, p, prob_m, prob_u, z):
        s, stop, res = pending
        p[s:stop] = np.asarray(res[0])
        if prob_m is not None:
            prob_m[s:stop] = np.asarray(res[1])
            prob_u[s:stop] = np.asarray(res[2])
        if z is not None:
            # the logit rides last in every variant that computes it
            z[s:stop] = np.asarray(res[-1])

    def _build_df_e(self, G: np.ndarray, rows: slice | None = None):
        """Assemble the scored comparisons DataFrame with the reference's
        column layout (/root/reference/splink/expectation_step.py:128-165).
        ``rows`` restricts output to a slice of the pair set (streaming)."""
        pairs = self._ensure_pairs()

        il, ir = pairs.idx_l, pairs.idx_r
        if rows is not None:
            G, il, ir = G[rows], il[rows], ir[rows]

        dtype = self._float_dtype
        lam, m, u, _ = self.params.to_arrays(dtype=dtype)
        params_dev = FSParams(
            lam=jnp.asarray(lam), m=jnp.asarray(m), u=jnp.asarray(u)
        )
        with self._stage("score"):
            p, prob_m, prob_u, z = self._score_batched(
                G, params_dev, want_z=self._tf_fold_ctx() is not None
            )
        return self._assemble_df_e(G, il, ir, p, prob_m, prob_u, z=z)

    def _assemble_df_e(self, G, il, ir, p, prob_m, prob_u, z=None):
        """Column assembly shared by the device-scoring and pattern-LUT
        paths; all inputs are host arrays aligned with (il, ir). With the
        TF u-probability fold active (``_tf_fold_ctx``) and the pairs'
        match logits in ``z``, the frame carries a
        ``tf_match_probability`` column — the first-class TF-adjusted
        score, bit-identical to what the serve megakernel returns for the
        same pairs."""
        table = self._ensure_encoded()
        settings = self.settings
        uid = settings["unique_id_column_name"]
        cols: dict[str, np.ndarray] = {"match_probability": p}
        ctx = self._tf_fold_ctx()
        if ctx is not None:
            cols["tf_match_probability"] = (
                self._tf_fold_pairs(z, il, ir, ctx)
                if z is not None and len(p)
                else np.zeros(len(p), self._float_dtype)
            )

        def add_lr(name, values):
            cols.setdefault(f"{name}_l", values[il])
            cols.setdefault(f"{name}_r", values[ir])

        add_lr(uid, table.unique_id)
        for c, col in enumerate(settings["comparison_columns"]):
            name = comparison_column_name(col)
            if "col_name" in col:
                if settings["retain_matching_columns"] or col["term_frequency_adjustments"]:
                    add_lr(name, table.column_values(name))
            else:
                if (
                    settings["retain_matching_columns"]
                    or col["term_frequency_adjustments"]
                ):
                    for used in col["custom_columns_used"]:
                        add_lr(used, table.column_values(used))
            cols[f"gamma_{name}"] = G[:, c].astype(np.int64)
            if settings["retain_intermediate_calculation_columns"]:
                cols[f"prob_gamma_{name}_non_match"] = prob_u[:, c]
                cols[f"prob_gamma_{name}_match"] = prob_m[:, c]

        if settings["link_type"] == "link_and_dedupe":
            src = np.array(["left", "right"], dtype=object)[table.source_table]
            add_lr("_source_table", src)
        for extra in settings["additional_columns_to_retain"]:
            add_lr(extra, table.column_values(extra))

        return pd.DataFrame(cols)


@check_types
def load_from_json(
    path: str | os.PathLike,
    df=None,
    df_l=None,
    df_r=None,
    save_state_fn: Callable = None,
    spark=None,
):
    """Load a model saved with save_model_as_json and return a ready linker
    (/root/reference/splink/__init__.py:175-195)."""
    params = load_params_from_json(path)
    linker = Splink(
        params.settings, df=df, df_l=df_l, df_r=df_r, save_state_fn=save_state_fn
    )
    linker.params = params
    return linker
