"""EM training loop: one jit-compiled program, params resident on device.

The reference's EM driver round-trips driver <-> cluster every iteration and
re-plans a fresh SQL query with the parameters baked in as literals
(/root/reference/splink/iterate.py:20, expectation_step.py:212). Here the
whole loop is a single ``lax.while_loop`` compiled once: parameters are traced
arguments that stay in device memory, the convergence predicate evaluates on
device, and per-iteration parameter history is written into preallocated
buffers so the host reads everything back in one transfer after convergence.

Two execution modes:
  * run_em:        gamma matrix resident in HBM (optionally sharded over a
                   mesh 'data' axis) — the fast path.
  * run_em_streamed (see splink_tpu/parallel/streaming.py): gamma batches
    stream host->device and sufficient statistics accumulate across
    micro-batches before each parameter update, for datasets larger than HBM.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import io_callback

from .models.fellegi_sunter import (
    FSParams,
    log_likelihood,
    match_probability,
    sufficient_stats,
    update_params,
)


class EMResult(NamedTuple):
    params: FSParams  # final parameters
    n_updates: jnp.ndarray  # number of M-step updates performed
    converged: jnp.ndarray  # bool: stopped because delta < tol
    lam_history: jnp.ndarray  # (max_iter + 1,), entry 0 = initial
    m_history: jnp.ndarray  # (max_iter + 1, C, L)
    u_history: jnp.ndarray  # (max_iter + 1, C, L)
    ll_history: jnp.ndarray  # (max_iter + 1,) log likelihood under params i (nan if not computed)


class _LoopState(NamedTuple):
    params: FSParams
    it: jnp.ndarray
    converged: jnp.ndarray
    lam_hist: jnp.ndarray
    m_hist: jnp.ndarray
    u_hist: jnp.ndarray
    ll_hist: jnp.ndarray


class EMNumericsError(RuntimeError):
    """A non-finite value entered the EM trajectory.

    Raised by :func:`run_em_checkpointed`'s host hook the moment an
    update delivers NaN/Inf in lambda, m, u or the log likelihood —
    BEFORE the poisoned values reach the histories, telemetry or a
    checkpoint, so everything persisted stays finite. Carries the first
    poisoned iteration, which fields were non-finite, the last finite
    iteration, and (when the run checkpoints) the directory plus the
    last boundary iteration already on disk — the state a caller
    restarts from. The same facts go out as a structured
    ``em_numerics`` degradation event (obs/events.publish) before the
    raise, so the incident lands in the run record and the flight ring
    even when the caller swallows the exception.
    """

    def __init__(
        self,
        message: str,
        *,
        iteration: int,
        fields: list,
        last_good_iteration: int,
        checkpoint_dir=None,
        last_checkpoint_iteration=None,
    ):
        super().__init__(message)
        self.iteration = iteration
        self.fields = fields
        self.last_good_iteration = last_good_iteration
        self.checkpoint_dir = checkpoint_dir
        self.last_checkpoint_iteration = last_checkpoint_iteration


# The active host hook for run_em(host_hook=True): a single module-level
# trampoline keeps ONE compiled program per (shape, static args) — a
# per-call closure passed as a static argument would recompile every call.
# run_em_checkpointed sets/clears it around the run (no concurrent fused
# EM runs share a process).
_active_em_hook = None


def _em_hook_trampoline(it, lam, m, u, ll_pre, converged):
    hook = _active_em_hook
    if hook is not None:
        hook(it, lam, m, u, ll_pre, converged)


@functools.partial(
    jax.jit,
    static_argnames=("max_iterations", "max_levels", "compute_ll", "host_hook"),
)
def run_em(
    G,
    init: FSParams,
    *,
    max_iterations: int,
    max_levels: int,
    em_convergence,
    weights=None,
    compute_ll: bool = False,
    host_hook: bool = False,
) -> EMResult:
    """Run EM to convergence in one compiled program.

    Convergence matches the reference (/root/reference/splink/params.py:316-336):
    the largest absolute change across all pi probabilities (lambda excluded)
    must drop below ``em_convergence``. The history layout matches the
    reference's ``param_history``: index i holds the parameters *before*
    update i+1, so index 0 is the initial state.

    ``host_hook`` adds one ordered io_callback per update (iteration, new
    params, pre-update ll, converged flag — a few hundred bytes) through
    which run_em_checkpointed persists progress WITHOUT re-entering the
    program: restarting the while_loop per checkpoint segment re-executes
    the hoisted loop-invariant work (the one-hot gamma expansion XLA
    licms out of the body), measured at ~30% overhead at K=5 on the CPU
    tier versus <5% for the in-loop callback. The callback does not touch
    the dataflow, so the trajectory is bit-identical either way.
    """
    C, L = init.m.shape
    dtype = init.m.dtype
    n_hist = max_iterations + 1

    lam_hist = jnp.full((n_hist,), jnp.nan, dtype).at[0].set(init.lam)
    m_hist = jnp.zeros((n_hist, C, L), dtype).at[0].set(init.m)
    u_hist = jnp.zeros((n_hist, C, L), dtype).at[0].set(init.u)
    ll_hist = jnp.full((n_hist,), jnp.nan, dtype)

    def cond(state: _LoopState):
        return (state.it < max_iterations) & (~state.converged)

    def body(state: _LoopState):
        p = match_probability(G, state.params)
        stats = sufficient_stats(G, p, max_levels, weights)
        new = update_params(stats)
        delta = jnp.maximum(
            jnp.max(jnp.abs(new.m - state.params.m)),
            jnp.max(jnp.abs(new.u - state.params.u)),
        )
        it = state.it + 1
        lam_h = state.lam_hist.at[it].set(new.lam)
        m_h = state.m_hist.at[it].set(new.m)
        u_h = state.u_hist.at[it].set(new.u)
        ll_h = state.ll_hist
        ll_val = jnp.asarray(jnp.nan, dtype)
        if compute_ll:
            # Log likelihood under the *pre-update* params, stored at the
            # pre-update index — the reference computes ll in the E-step and
            # archives it with those params (expectation_step.py:52-57).
            ll_val = log_likelihood(G, state.params, weights)
            ll_h = ll_h.at[state.it].set(ll_val)
        if host_hook:
            io_callback(
                _em_hook_trampoline,
                None,
                it,
                new.lam,
                new.m,
                new.u,
                ll_val,
                delta < em_convergence,
                ordered=True,
            )
        return _LoopState(
            params=new,
            it=it,
            converged=delta < em_convergence,
            lam_hist=lam_h,
            m_hist=m_h,
            u_hist=u_h,
            ll_hist=ll_h,
        )

    init_state = _LoopState(
        params=init,
        it=jnp.zeros((), jnp.int32),
        converged=jnp.zeros((), bool),
        lam_hist=lam_hist,
        m_hist=m_hist,
        u_hist=u_hist,
        ll_hist=ll_hist,
    )
    final = lax.while_loop(cond, body, init_state)

    ll_hist = final.ll_hist
    if compute_ll:
        ll_hist = ll_hist.at[final.it].set(
            log_likelihood(G, final.params, weights)
        )

    return EMResult(
        params=final.params,
        n_updates=final.it,
        converged=final.converged,
        lam_history=final.lam_hist,
        m_history=final.m_hist,
        u_history=final.u_hist,
        ll_history=ll_hist,
    )


def run_em_checkpointed(
    G,
    init: FSParams,
    *,
    max_iterations: int,
    max_levels: int,
    em_convergence,
    weights=None,
    compute_ll: bool = False,
    checkpoint_dir=None,
    state_hash: str = "",
    checkpoint_every: int = 5,
    resume: bool = False,
    resume_checkpoint=None,
    fault_plan=None,
    on_segment=None,
    telemetry=None,
) -> EMResult:
    """Fused EM with an atomic checkpoint every ``checkpoint_every``
    updates — ONE compiled ``run_em`` execution, persisted from inside.

    The per-iteration computation IS ``run_em``'s (the host hook rides an
    io_callback that touches no dataflow), so the parameter/history
    trajectory is bit-identical to an uninterrupted run —
    tests/test_checkpoint_resume.py pins this. Per update the hook
    receives the new params; at each boundary (iteration divisible by K,
    convergence, or the final update) it writes an atomic checkpoint
    (resilience/checkpoint.py), fires the ``segment`` fault-injection
    site, and calls ``on_segment``. An interrupted run resumes
    (``resume=True``) from the last boundary instead of starting over.

    An earlier revision re-entered the compiled while_loop in
    K-iteration segments; XLA hoists the loop-invariant one-hot gamma
    expansion out of the loop body, so every re-entry re-paid it — ~30%
    wall-clock overhead at K=5 on the CPU tier, vs <5% for this in-loop
    form (BENCHMARKS.md).

    Histories are host numpy arrays in run_em's layout (index i = params
    before update i+1; ll index i = log likelihood under params i).
    ``on_segment(done, histories, converged)`` runs on the callback
    thread at each boundary — the linker uses it to replay new iterations
    into its Params object (and drive save_state_fn) incrementally; it
    must therefore stay host-side work (no jax dispatch). A hook
    exception (failed write, injected boundary fault) is re-raised after
    the program drains.

    ``telemetry`` (an ``obs.runtime.RunContext``) streams one EM
    convergence record per update through the SAME io_callback — the
    telemetry-only caller (checkpoint_dir=None) therefore runs the
    identical compiled program as the checkpointed one, and the parameter
    trajectory stays bit-identical to a telemetry-off run (the callback
    touches no dataflow). RunContext.em_update never raises, so telemetry
    failures cannot poison the deferred-exception channel.
    """
    import numpy as np

    from .resilience.checkpoint import (
        EMCheckpoint,
        load_checkpoint,
        save_checkpoint,
    )

    if resume and checkpoint_dir is None:
        raise ValueError(
            "resume=True requires checkpoint_dir — silently training from "
            "scratch is exactly the surprise a resume caller cannot afford."
        )
    m0 = np.asarray(init.m)
    C, L = m0.shape
    np_dtype = m0.dtype
    n_hist = max_iterations + 1
    lam_h = np.full((n_hist,), np.nan, np_dtype)
    m_h = np.zeros((n_hist, C, L), np_dtype)
    u_h = np.zeros((n_hist, C, L), np_dtype)
    ll_h = np.full((n_hist,), np.nan, np_dtype)
    lam_h[0] = np.asarray(init.lam)
    m_h[0] = m0
    u_h[0] = np.asarray(init.u)

    done = 0
    converged = False
    params_dev = init
    if resume and checkpoint_dir is not None:
        # a caller that already loaded (and topology-validated) the
        # checkpoint passes it in; re-reading the file here would be a
        # second full parse and a validate/restore race window
        ckpt = (
            resume_checkpoint
            if resume_checkpoint is not None
            else load_checkpoint(checkpoint_dir, expect_hash=state_hash or None)
        )
        if ckpt is not None:
            h = ckpt.history_arrays()
            done = min(ckpt.iteration, max_iterations)
            lam_h[: done + 1] = h["lam"][: done + 1].astype(np_dtype)
            m_h[: done + 1] = h["m"][: done + 1].astype(np_dtype)
            u_h[: done + 1] = h["u"][: done + 1].astype(np_dtype)
            if compute_ll and h["ll"] is not None:
                n_ll = min(len(h["ll"]), done + 1)
                ll_h[:n_ll] = h["ll"][:n_ll].astype(np_dtype)
            if ckpt.iteration > max_iterations:
                # the iteration cap was lowered below the checkpoint:
                # return the truncated trajectory's own params (history
                # index ``done``), not the checkpoint's later ones, and
                # the converged flag at the truncation point is unknown
                params_dev = FSParams(
                    lam=jnp.asarray(lam_h[done]),
                    m=jnp.asarray(m_h[done]),
                    u=jnp.asarray(u_h[done]),
                )
                converged = False
            else:
                lam, m, u = ckpt.params_arrays()
                params_dev = FSParams(
                    lam=jnp.asarray(lam.astype(np_dtype)),
                    m=jnp.asarray(m.astype(np_dtype)),
                    u=jnp.asarray(u.astype(np_dtype)),
                )
                converged = ckpt.converged

    # single-writer directory under multi-controller runs: every process
    # computes the same trajectory (the EM stats are globally reduced), so
    # only process 0 persists it
    is_writer = jax.process_count() == 1 or jax.process_index() == 0

    # the numerics guard reports the newest boundary already on disk as
    # the restart point, so _save records what it persisted
    last_saved = {"iteration": None}

    def _save(iteration, conv):
        if checkpoint_dir is None or not is_writer:
            return
        # Single-writer by design (jaxlint JL009): every process computes
        # the identical trajectory (the EM stats are globally reduced), the
        # save path contains no collective, and readers gate on
        # validate_resume_presence — so only process 0 touching the
        # directory cannot deadlock or diverge.
        save_checkpoint(  # jaxlint: disable=JL009
            checkpoint_dir,
            EMCheckpoint(
                state_hash=state_hash,
                iteration=iteration,
                lam=float(lam_h[iteration]),
                m=m_h[iteration].tolist(),
                u=u_h[iteration].tolist(),
                histories={
                    "lam": lam_h[: iteration + 1].tolist(),
                    "m": m_h[: iteration + 1].tolist(),
                    "u": u_h[: iteration + 1].tolist(),
                    # not-yet-computed entries (the boundary's own ll
                    # arrives one update later) persist as null, never a
                    # 0.0 filler a resumed run could mistake for a value
                    "ll": (
                        [
                            None if np.isnan(v) else float(v)
                            for v in ll_h[: iteration + 1]
                        ]
                        if compute_ll
                        else None
                    ),
                },
                converged=conv,
                process_count=jax.process_count(),
                dtype=np_dtype.name,
            ),
        )
        last_saved["iteration"] = int(iteration)

    checkpoint_every = max(int(checkpoint_every), 1)
    start = done
    remaining = max_iterations - done
    hook_needed = (
        checkpoint_dir is not None
        or on_segment is not None
        or (fault_plan is not None and bool(fault_plan))
        or telemetry is not None
    )
    deferred: list[BaseException] = []

    def hook(it_rel, lam, m, u, ll_pre, conv):
        # runs on the runtime's callback thread, once per completed
        # update, while the compiled loop is still executing
        if deferred:
            return
        try:
            it = start + int(it_rel)
            # numerics guard: a NaN/Inf update halts the trajectory HERE,
            # before the poisoned values can reach the histories, the
            # telemetry stream or a checkpoint. Everything written so far
            # passed this same check, so iteration it-1 is the last finite
            # state — and the newest _save boundary holds it on disk.
            bad = [
                name
                for name, v in (("lam", lam), ("m", m), ("u", u))
                if not np.isfinite(np.asarray(v)).all()
            ]
            if compute_ll and not np.isfinite(ll_pre):
                bad.append("ll")
            if bad:
                from .obs.events import publish

                info = dict(
                    iteration=it,
                    fields=bad,
                    last_good_iteration=it - 1,
                    checkpoint_dir=(
                        str(checkpoint_dir)
                        if checkpoint_dir is not None
                        else None
                    ),
                    last_checkpoint_iteration=last_saved["iteration"],
                )
                publish("em_numerics", **info)
                where = (
                    f"; last checkpoint at iteration "
                    f"{last_saved['iteration']} in {checkpoint_dir}"
                    if last_saved["iteration"] is not None
                    else ""
                )
                raise EMNumericsError(
                    f"non-finite EM update at iteration {it} "
                    f"({', '.join(bad)}); last finite iteration "
                    f"{it - 1}{where}",
                    **info,
                )
            lam_h[it] = lam
            m_h[it] = m
            u_h[it] = u
            if compute_ll and not np.isnan(ll_pre):
                ll_h[it - 1] = ll_pre
            conv = bool(conv)
            if telemetry is not None:
                telemetry.em_update(
                    it, float(lam), m, u,
                    float(ll_pre) if compute_ll else None, conv,
                )
            if conv or it == max_iterations or it % checkpoint_every == 0:
                # durability first: an injected kill at this boundary must
                # find the boundary's own update already on disk
                _save(it, conv)
                if fault_plan is not None:
                    fault_plan.fire("segment", iter=it)
                if on_segment is not None:
                    on_segment(
                        it, {"lam": lam_h, "m": m_h, "u": u_h, "ll": ll_h}, conv
                    )
        except BaseException as e:  # noqa: BLE001 - re-raised after drain
            deferred.append(e)

    if remaining > 0 and not converged:
        global _active_em_hook
        _active_em_hook = hook if hook_needed else None
        try:
            result = run_em(
                G,
                params_dev,
                max_iterations=remaining,
                max_levels=max_levels,
                em_convergence=em_convergence,
                weights=weights,
                compute_ll=compute_ll,
                host_hook=hook_needed,
            )
            # drain before releasing the hook: dispatch is async and the
            # trailing callbacks may still be in flight
            jax.block_until_ready(result.n_updates)
            jax.effects_barrier()
        finally:
            _active_em_hook = None
        if deferred:
            raise deferred[0]
        n_rel = int(result.n_updates)
        # the hook already wrote indices start+1..start+n_rel; this merge
        # re-writes them with the same values and is what the no-hook
        # (checkpoint_dir=None) path relies on
        lam_h[start + 1 : start + n_rel + 1] = np.asarray(
            result.lam_history[1 : n_rel + 1]
        )
        m_h[start + 1 : start + n_rel + 1] = np.asarray(
            result.m_history[1 : n_rel + 1]
        )
        u_h[start + 1 : start + n_rel + 1] = np.asarray(
            result.u_history[1 : n_rel + 1]
        )
        if compute_ll:
            # local indices 0..n_rel are all populated (in-loop at the
            # pre-update index, post-loop at n_rel)
            ll_h[start : start + n_rel + 1] = np.asarray(
                result.ll_history[: n_rel + 1]
            )
        params_dev = result.params
        done = start + n_rel
        converged = bool(result.converged)
        if checkpoint_dir is not None:
            # the last in-loop boundary save could not include the final
            # log likelihood (computed post-loop); re-save so the persisted
            # state is complete and a resume of a finished run reproduces
            # the uninterrupted run's Params exactly
            _save(done, converged)

    return EMResult(
        params=params_dev,
        n_updates=np.int32(done),
        converged=np.bool_(converged),
        lam_history=lam_h,
        m_history=m_h,
        u_history=u_h,
        ll_history=ll_h,
    )


def trimmed_trajectory(result: EMResult) -> dict:
    """Host-side convergence record of one EM run: the per-iteration log
    likelihood (entry 0 = the initial parameters, reference
    ``param_history`` layout; entry i = the likelihood under params i,
    None where not computed) plus update count and convergence flag —
    ONLY the series the Params history cannot reconstruct. The lambda
    path and max m/u movement live in the diagnostics event's
    ``trajectory`` payload (obs/quality._trajectory_payload); the full
    device histories stay in the result for callers that want them."""
    import numpy as np

    n = int(result.n_updates)
    ll = np.asarray(result.ll_history)[: n + 1]
    return {
        "n_updates": n,
        "converged": bool(result.converged),
        "ll": [None if np.isnan(v) else round(float(v), 4) for v in ll],
    }


@jax.jit
def score_pairs(G, params: FSParams):
    """Final E-step scoring: match probability for every pair."""
    return match_probability(G, params)


@jax.jit
def score_pairs_with_intermediates(G, params: FSParams):
    """Scoring plus the per-column m/u lookup probabilities the reference
    retains as prob_gamma_<col>_match / _non_match columns
    (/root/reference/splink/expectation_step.py:196-221)."""
    from .models.fellegi_sunter import gamma_prob_lookup

    p = match_probability(G, params)
    prob_m = gamma_prob_lookup(G, params.m)
    prob_u = gamma_prob_lookup(G, params.u)
    return p, prob_m, prob_u


@jax.jit
def score_pairs_with_logits(G, params: FSParams):
    """(p, fold_logit) — the logit is what the term-frequency fold adds
    its per-pair delta to (term_frequencies.make_tf_fold_fn). ``p`` stays
    the canonical ``match_probability`` (byte-identical to
    :func:`score_pairs`); the logit carries the FUSED serve kernel's
    left-to-right accumulation order, which is the TF parity anchor
    (fellegi_sunter.fold_logit docstring)."""
    from .models.fellegi_sunter import fold_logit

    return match_probability(G, params), fold_logit(G, params)


@jax.jit
def score_pairs_with_intermediates_logits(G, params: FSParams):
    """score_pairs_with_intermediates plus the fold logit (TF-fold jobs
    that also retain intermediate columns)."""
    from .models.fellegi_sunter import fold_logit, gamma_prob_lookup

    p = match_probability(G, params)
    prob_m = gamma_prob_lookup(G, params.m)
    prob_u = gamma_prob_lookup(G, params.u)
    return p, prob_m, prob_u, fold_logit(G, params)
