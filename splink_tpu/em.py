"""EM training loop: one jit-compiled program, params resident on device.

The reference's EM driver round-trips driver <-> cluster every iteration and
re-plans a fresh SQL query with the parameters baked in as literals
(/root/reference/splink/iterate.py:20, expectation_step.py:212). Here the
whole loop is a single ``lax.while_loop`` compiled once: parameters are traced
arguments that stay in device memory, the convergence predicate evaluates on
device, and per-iteration parameter history is written into preallocated
buffers so the host reads everything back in one transfer after convergence.

Two execution modes:
  * run_em:        gamma matrix resident in HBM (optionally sharded over a
                   mesh 'data' axis) — the fast path.
  * run_em_streamed (see splink_tpu/parallel/streaming.py): gamma batches
    stream host->device and sufficient statistics accumulate across
    micro-batches before each parameter update, for datasets larger than HBM.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .models.fellegi_sunter import (
    FSParams,
    log_likelihood,
    match_probability,
    sufficient_stats,
    update_params,
)


class EMResult(NamedTuple):
    params: FSParams  # final parameters
    n_updates: jnp.ndarray  # number of M-step updates performed
    converged: jnp.ndarray  # bool: stopped because delta < tol
    lam_history: jnp.ndarray  # (max_iter + 1,), entry 0 = initial
    m_history: jnp.ndarray  # (max_iter + 1, C, L)
    u_history: jnp.ndarray  # (max_iter + 1, C, L)
    ll_history: jnp.ndarray  # (max_iter + 1,) log likelihood under params i (nan if not computed)


class _LoopState(NamedTuple):
    params: FSParams
    it: jnp.ndarray
    converged: jnp.ndarray
    lam_hist: jnp.ndarray
    m_hist: jnp.ndarray
    u_hist: jnp.ndarray
    ll_hist: jnp.ndarray


@functools.partial(
    jax.jit, static_argnames=("max_iterations", "max_levels", "compute_ll")
)
def run_em(
    G,
    init: FSParams,
    *,
    max_iterations: int,
    max_levels: int,
    em_convergence,
    weights=None,
    compute_ll: bool = False,
) -> EMResult:
    """Run EM to convergence in one compiled program.

    Convergence matches the reference (/root/reference/splink/params.py:316-336):
    the largest absolute change across all pi probabilities (lambda excluded)
    must drop below ``em_convergence``. The history layout matches the
    reference's ``param_history``: index i holds the parameters *before*
    update i+1, so index 0 is the initial state.
    """
    C, L = init.m.shape
    dtype = init.m.dtype
    n_hist = max_iterations + 1

    lam_hist = jnp.full((n_hist,), jnp.nan, dtype).at[0].set(init.lam)
    m_hist = jnp.zeros((n_hist, C, L), dtype).at[0].set(init.m)
    u_hist = jnp.zeros((n_hist, C, L), dtype).at[0].set(init.u)
    ll_hist = jnp.full((n_hist,), jnp.nan, dtype)

    def cond(state: _LoopState):
        return (state.it < max_iterations) & (~state.converged)

    def body(state: _LoopState):
        p = match_probability(G, state.params)
        stats = sufficient_stats(G, p, max_levels, weights)
        new = update_params(stats)
        delta = jnp.maximum(
            jnp.max(jnp.abs(new.m - state.params.m)),
            jnp.max(jnp.abs(new.u - state.params.u)),
        )
        it = state.it + 1
        lam_h = state.lam_hist.at[it].set(new.lam)
        m_h = state.m_hist.at[it].set(new.m)
        u_h = state.u_hist.at[it].set(new.u)
        ll_h = state.ll_hist
        if compute_ll:
            # Log likelihood under the *pre-update* params, stored at the
            # pre-update index — the reference computes ll in the E-step and
            # archives it with those params (expectation_step.py:52-57).
            ll_h = ll_h.at[state.it].set(log_likelihood(G, state.params, weights))
        return _LoopState(
            params=new,
            it=it,
            converged=delta < em_convergence,
            lam_hist=lam_h,
            m_hist=m_h,
            u_hist=u_h,
            ll_hist=ll_h,
        )

    init_state = _LoopState(
        params=init,
        it=jnp.zeros((), jnp.int32),
        converged=jnp.zeros((), bool),
        lam_hist=lam_hist,
        m_hist=m_hist,
        u_hist=u_hist,
        ll_hist=ll_hist,
    )
    final = lax.while_loop(cond, body, init_state)

    ll_hist = final.ll_hist
    if compute_ll:
        ll_hist = ll_hist.at[final.it].set(
            log_likelihood(G, final.params, weights)
        )

    return EMResult(
        params=final.params,
        n_updates=final.it,
        converged=final.converged,
        lam_history=final.lam_hist,
        m_history=final.m_hist,
        u_history=final.u_hist,
        ll_history=ll_hist,
    )


@jax.jit
def score_pairs(G, params: FSParams):
    """Final E-step scoring: match probability for every pair."""
    return match_probability(G, params)


@jax.jit
def score_pairs_with_intermediates(G, params: FSParams):
    """Scoring plus the per-column m/u lookup probabilities the reference
    retains as prob_gamma_<col>_match / _non_match columns
    (/root/reference/splink/expectation_step.py:196-221)."""
    from .models.fellegi_sunter import gamma_prob_lookup

    p = match_probability(G, params)
    prob_m = gamma_prob_lookup(G, params.m)
    prob_u = gamma_prob_lookup(G, params.u)
    return p, prob_m, prob_u
