// Host-side native kernels for splink_tpu.
//
// The TPU does the per-pair math; these cover the irregular host work that
// Python loops handle too slowly at the 10M-100M row scale the framework
// targets (SURVEY.md section 6): fixed-width string encoding and blocked
// pair emission. They fill the architectural slot of the reference's native
// components (the Spark/JVM runtime and the scala-udf-similarity jar,
// /root/reference/jars/) on the host side of the pipeline.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 dependency).
// Build: make -C splink_tpu/native   (produces libsplink_host.so)

#include <cstdint>
#include <cstring>
#include <algorithm>

extern "C" {

// ---------------------------------------------------------------------------
// encode_fixed_width: pack UTF-8 rows into a zero-padded (n, width) uint8
// matrix plus int32 lengths. Rows are given as one contiguous byte buffer
// with (n+1) int64 offsets (Arrow-style). Truncates at `width` bytes.
// Intended for ASCII columns (the common case); non-ASCII columns go through
// the Python codepoint path.
void encode_fixed_width(const uint8_t* data, const int64_t* offsets,
                        int64_t n_rows, int64_t width,
                        uint8_t* out_bytes, int32_t* out_lens) {
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t start = offsets[i];
    const int64_t len = std::min(offsets[i + 1] - start, width);
    uint8_t* dst = out_bytes + i * width;
    std::memcpy(dst, data + start, static_cast<size_t>(len));
    if (len < width) std::memset(dst + len, 0, static_cast<size_t>(width - len));
    out_lens[i] = static_cast<int32_t>(len);
  }
}

// ---------------------------------------------------------------------------
// Self-join pair emission over key groups.
//
// Input: rows sorted by key code; group_starts/group_sizes describe runs of
// equal codes (as produced by the Python grouping). Emits every unordered
// within-group position pair (p, q), p < q, as indices into `rows`.
//
// count_self_pairs returns the total so the caller can allocate exactly once;
// emit_self_pairs fills the preallocated buffers.
int64_t count_self_pairs(const int64_t* group_sizes, int64_t n_groups) {
  int64_t total = 0;
  for (int64_t g = 0; g < n_groups; ++g) {
    const int64_t s = group_sizes[g];
    total += s * (s - 1) / 2;
  }
  return total;
}

void emit_self_pairs(const int64_t* rows, const int64_t* group_starts,
                     const int64_t* group_sizes, int64_t n_groups,
                     int64_t* out_i, int64_t* out_j) {
  int64_t k = 0;
  for (int64_t g = 0; g < n_groups; ++g) {
    const int64_t start = group_starts[g];
    const int64_t s = group_sizes[g];
    for (int64_t p = 0; p < s; ++p) {
      const int64_t rp = rows[start + p];
      for (int64_t q = p + 1; q < s; ++q) {
        out_i[k] = rp;
        out_j[k] = rows[start + q];
        ++k;
      }
    }
  }
}

// int32 variant: at billions of candidate pairs the pair-index buffers are
// the dominant host allocation, and int32 row indices cover 2^31 rows.
void emit_self_pairs_i32(const int32_t* rows, const int64_t* group_starts,
                         const int64_t* group_sizes, int64_t n_groups,
                         int32_t* out_i, int32_t* out_j) {
  int64_t k = 0;
  for (int64_t g = 0; g < n_groups; ++g) {
    const int64_t start = group_starts[g];
    const int64_t s = group_sizes[g];
    for (int64_t p = 0; p < s; ++p) {
      const int32_t rp = rows[start + p];
      for (int64_t q = p + 1; q < s; ++q) {
        out_i[k] = rp;
        out_j[k] = rows[start + q];
        ++k;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Cross-join pair emission (link_only): for each key present on both sides,
// emit the full left-group x right-group product.
int64_t count_cross_pairs(const int64_t* l_sizes, const int64_t* r_sizes,
                          int64_t n_groups) {
  int64_t total = 0;
  for (int64_t g = 0; g < n_groups; ++g) total += l_sizes[g] * r_sizes[g];
  return total;
}

void emit_cross_pairs(const int64_t* l_rows, const int64_t* l_starts,
                      const int64_t* l_sizes, const int64_t* r_rows,
                      const int64_t* r_starts, const int64_t* r_sizes,
                      int64_t n_groups, int64_t* out_i, int64_t* out_j) {
  int64_t k = 0;
  for (int64_t g = 0; g < n_groups; ++g) {
    const int64_t ls = l_starts[g], le = ls + l_sizes[g];
    const int64_t rs = r_starts[g], re = rs + r_sizes[g];
    for (int64_t a = ls; a < le; ++a) {
      const int64_t ra = l_rows[a];
      for (int64_t b = rs; b < re; ++b) {
        out_i[k] = ra;
        out_j[k] = r_rows[b];
        ++k;
      }
    }
  }
}

void emit_cross_pairs_i32(const int32_t* l_rows, const int64_t* l_starts,
                          const int64_t* l_sizes, const int32_t* r_rows,
                          const int64_t* r_starts, const int64_t* r_sizes,
                          int64_t n_groups, int32_t* out_i, int32_t* out_j) {
  int64_t k = 0;
  for (int64_t g = 0; g < n_groups; ++g) {
    const int64_t ls = l_starts[g], le = ls + l_sizes[g];
    const int64_t rs = r_starts[g], re = rs + r_sizes[g];
    for (int64_t a = ls; a < le; ++a) {
      const int32_t ra = l_rows[a];
      for (int64_t b = rs; b < re; ++b) {
        out_i[k] = ra;
        out_j[k] = r_rows[b];
        ++k;
      }
    }
  }
}

}  // extern "C"
