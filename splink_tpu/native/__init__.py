"""ctypes loader for the native host kernels, with pure-numpy fallback.

The library is built on first use (``make`` + g++, a one-second compile) and
cached next to the sources. Every entry point has a Python fallback so the
package works on machines without a toolchain — ``available()`` reports which
path is active.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("splink_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libsplink_host.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_i64p = ctypes.POINTER(ctypes.c_int64)
_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception as e:  # pragma: no cover - depends on toolchain
        logger.debug("native build failed (%s); using numpy fallbacks", e)
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = _bind(ctypes.CDLL(_LIB_PATH))
        except AttributeError:
            # Stale cached .so from an older source revision (missing a newer
            # symbol): rebuild once, then retry; numpy fallback if that fails.
            logger.debug("native lib stale; rebuilding")
            try:
                os.remove(_LIB_PATH)
            except OSError:
                pass
            if _build():
                try:
                    _lib = _bind(ctypes.CDLL(_LIB_PATH))
                except (OSError, AttributeError) as e:  # pragma: no cover
                    logger.debug("native rebuild failed (%s); numpy fallbacks", e)
                    _lib = None
            else:
                _lib = None
        except OSError as e:  # pragma: no cover
            logger.debug("native load failed (%s); using numpy fallbacks", e)
            _lib = None
        else:
            _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare signatures; raises AttributeError if the .so is stale."""
    lib.encode_fixed_width.argtypes = [
        _u8p, _i64p, ctypes.c_int64, ctypes.c_int64, _u8p, _i32p,
    ]
    lib.count_self_pairs.restype = ctypes.c_int64
    lib.count_self_pairs.argtypes = [_i64p, ctypes.c_int64]
    lib.emit_self_pairs.argtypes = [_i64p] * 3 + [ctypes.c_int64, _i64p, _i64p]
    lib.emit_self_pairs_i32.argtypes = [
        _i32p, _i64p, _i64p, ctypes.c_int64, _i32p, _i32p,
    ]
    lib.count_cross_pairs.restype = ctypes.c_int64
    lib.count_cross_pairs.argtypes = [_i64p, _i64p, ctypes.c_int64]
    lib.emit_cross_pairs.argtypes = [_i64p] * 6 + [ctypes.c_int64, _i64p, _i64p]
    lib.emit_cross_pairs_i32.argtypes = [
        _i32p, _i64p, _i64p, _i32p, _i64p, _i64p,
        ctypes.c_int64, _i32p, _i32p,
    ]
    return lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctype)


def encode_fixed_width(data: np.ndarray, offsets: np.ndarray, width: int):
    """(flat uint8 buffer, int64 offsets) -> ((n, width) uint8, (n,) int32)."""
    n = len(offsets) - 1
    out_bytes = np.zeros((n, width), np.uint8)
    out_lens = np.zeros(n, np.int32)
    lib = _load()
    if lib is not None and data.flags.c_contiguous:
        lib.encode_fixed_width(
            _ptr(data, _u8p), _ptr(offsets, _i64p), n, width,
            _ptr(out_bytes, _u8p), _ptr(out_lens, _i32p),
        )
        return out_bytes, out_lens
    for i in range(n):  # numpy fallback
        row = data[offsets[i] : offsets[i + 1]][:width]
        out_bytes[i, : len(row)] = row
        out_lens[i] = len(row)
    return out_bytes, out_lens


def self_join_pairs(rows_sorted: np.ndarray, starts: np.ndarray, sizes: np.ndarray):
    """Emit all unordered within-group pairs; None -> caller uses numpy path.

    Output dtype follows the rows dtype: int32 rows emit int32 pairs (the
    preferred path — at billions of pairs the index buffers dominate host
    memory), anything else goes through the int64 kernel.
    """
    lib = _load()
    if lib is None:
        return None
    starts = np.ascontiguousarray(starts, np.int64)
    sizes = np.ascontiguousarray(sizes, np.int64)
    total = lib.count_self_pairs(_ptr(sizes, _i64p), len(sizes))
    if rows_sorted.dtype == np.int32:
        rows32 = np.ascontiguousarray(rows_sorted, np.int32)
        out_i = np.empty(total, np.int32)
        out_j = np.empty(total, np.int32)
        lib.emit_self_pairs_i32(
            _ptr(rows32, _i32p), _ptr(starts, _i64p), _ptr(sizes, _i64p),
            len(sizes), _ptr(out_i, _i32p), _ptr(out_j, _i32p),
        )
        return out_i, out_j
    rows64 = np.ascontiguousarray(rows_sorted, np.int64)
    out_i = np.empty(total, np.int64)
    out_j = np.empty(total, np.int64)
    lib.emit_self_pairs(
        _ptr(rows64, _i64p), _ptr(starts, _i64p), _ptr(sizes, _i64p),
        len(sizes), _ptr(out_i, _i64p), _ptr(out_j, _i64p),
    )
    return out_i, out_j


def cross_join_pairs(l_rows, l_starts, l_sizes, r_rows, r_starts, r_sizes):
    """Emit all cross-table pairs for matched key groups; None -> numpy path.

    Like self_join_pairs, int32 row arrays use the int32 kernel."""
    lib = _load()
    if lib is None:
        return None
    l_starts = np.ascontiguousarray(l_starts, np.int64)
    l_sizes = np.ascontiguousarray(l_sizes, np.int64)
    r_starts = np.ascontiguousarray(r_starts, np.int64)
    r_sizes = np.ascontiguousarray(r_sizes, np.int64)
    total = lib.count_cross_pairs(
        _ptr(l_sizes, _i64p), _ptr(r_sizes, _i64p), len(l_sizes)
    )
    if l_rows.dtype == np.int32 and r_rows.dtype == np.int32:
        lr = np.ascontiguousarray(l_rows, np.int32)
        rr = np.ascontiguousarray(r_rows, np.int32)
        out_i = np.empty(total, np.int32)
        out_j = np.empty(total, np.int32)
        lib.emit_cross_pairs_i32(
            _ptr(lr, _i32p), _ptr(l_starts, _i64p), _ptr(l_sizes, _i64p),
            _ptr(rr, _i32p), _ptr(r_starts, _i64p), _ptr(r_sizes, _i64p),
            len(l_sizes), _ptr(out_i, _i32p), _ptr(out_j, _i32p),
        )
        return out_i, out_j
    lr = np.ascontiguousarray(l_rows, np.int64)
    rr = np.ascontiguousarray(r_rows, np.int64)
    out_i = np.empty(total, np.int64)
    out_j = np.empty(total, np.int64)
    lib.emit_cross_pairs(
        _ptr(lr, _i64p), _ptr(l_starts, _i64p), _ptr(l_sizes, _i64p),
        _ptr(rr, _i64p), _ptr(r_starts, _i64p), _ptr(r_sizes, _i64p),
        len(l_sizes), _ptr(out_i, _i64p), _ptr(out_j, _i64p),
    )
    return out_i, out_j
