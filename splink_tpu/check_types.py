"""Runtime type enforcement for public API functions.

The reference applies an equivalent decorator to all public entry points
(/root/reference/splink/check_types.py:20); we keep the behaviour (clear
TypeError naming the argument, Union-aware) for API parity.
"""

from __future__ import annotations

import inspect
import types
import typing
from functools import wraps


def _possible_types(hint):
    origin = typing.get_origin(hint)
    if origin is typing.Union or origin is types.UnionType:  # X | Y (PEP 604) too
        return tuple(t for t in typing.get_args(hint) if t is not type(None)) + (
            type(None),
        )
    if origin is not None:
        # Parameterised generics (dict[str, x], list[x], ...) -> check the origin only
        return (origin,)
    return (hint,)


def check_types(func):
    """Decorator that validates annotated arguments at call time."""
    sig = inspect.signature(func)
    hints = typing.get_type_hints(func)

    @wraps(func)
    def wrapper(*args, **kwargs):
        bound = sig.bind_partial(*args, **kwargs)
        for name, value in bound.arguments.items():
            if name not in hints or value is None:
                continue
            types = _possible_types(hints[name])
            try:
                ok = isinstance(value, types)
            except TypeError:
                continue  # unresolvable hint (e.g. Callable with params)
            if not ok:
                expected = " or ".join(str(t) for t in types)
                raise TypeError(
                    f"Wrong type for argument '{name}' of {func.__name__}: "
                    f"got {value!r} of type {type(value)}; expected {expected}."
                )
        return func(*args, **kwargs)

    return wrapper
