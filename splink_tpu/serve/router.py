"""Replica routing: health-aware dispatch, failover, hedged requests.

One :class:`~.service.LinkageService` is one replica. Production traffic
wants N of them — separate worker threads today, separate hosts once the
front-end speaks a wire protocol — and a front-end that (1) routes each
request to the healthiest replica, (2) fails over when a replica sheds or
breaks, and (3) optionally HEDGES: re-dispatches a slow request to a
second replica after a delay, first result wins. Hedging is the classic
tail-latency cut (Dean & Barroso, "The Tail at Scale"): a p95-derived
delay means ~5% of requests cost a duplicate dispatch and the p99 stops
being hostage to one stalled replica.

Routing order ranks replicas by their health state (healthy < degraded <
broken — :mod:`.health`) and round-robins within a rank, so load spreads
across healthy replicas and a broken replica is only ever tried as the
last resort. Failover is result-driven: any shed result (closed, breaker
open, queue full, worker restart...) forwards the request to the next
replica in the order; the requester sees ONE future that resolves with
the first non-shed result, or — only when every replica shed — the last
shed result. Exceptions never propagate through the returned future (the
same contract the service makes).

The router is duck-typed over its replicas: anything with ``submit(record,
deadline_ms=) -> Future[QueryResult]``, ``health_state`` and
``latency_summary()`` routes — the unit tests drive it with fakes, and a
future multi-host front-end can wrap RPC stubs in the same shape.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Protocol, runtime_checkable

from ..analysis import lockwatch

from .health import health_rank

logger = logging.getLogger("splink_tpu")

_DEFAULT_HEDGE_FLOOR_MS = 20.0


@runtime_checkable
class Replica(Protocol):
    """The replica duck-type, pinned.

    Three implementations ride this shape and must not drift apart:
    :class:`~.service.LinkageService` (in-process),
    :class:`~.remote.RemoteReplica` (another host over the wire tier),
    and the test fakes the router's unit suite drives failover with —
    ``tests/test_serve_resilience.py`` asserts conformance for all three.

    The contract behind the signatures:

    * ``submit`` NEVER raises and the returned future ALWAYS resolves —
      with a :class:`~.service.QueryResult`, shed results carrying a
      machine-readable ``reason``. (The router treats a raising replica
      as a shed, but that is a mercy, not a licence.)
    * ``health_state`` is a cheap property (``healthy`` / ``degraded`` /
      ``broken``) read on every routing decision — no locks held long,
      no I/O.
    * ``latency_summary()`` reports at least ``p95_ms`` once it has
      samples (the hedger's trigger delay keys on it).

    Two optional members extend the shape without breaking it: a
    truthy class attribute ``accepts_trace`` admits the router-minted
    ``trace=`` keyword on submit, and ``close()`` lets
    :meth:`ReplicaRouter.close` tear the replica down.
    """

    def submit(self, record: dict, deadline_ms: float | None = None):
        """-> Future[QueryResult]; never raises, always resolves."""
        ...  # pragma: no cover - Protocol signature

    @property
    def health_state(self) -> str:
        ...  # pragma: no cover - Protocol signature

    def latency_summary(self) -> dict:
        ...  # pragma: no cover - Protocol signature


class ReplicaRouter:
    """Health-aware front-end over N replica services (module docstring).

    ``hedge_ms`` — ``None``: read ``serve_hedge_ms`` from the first
    replica's settings (0 disables); a number: fixed hedge delay in ms;
    ``"p95"``: derive per request from the primary replica's measured p95
    (floor ``_DEFAULT_HEDGE_FLOOR_MS`` while the reservoir is cold).
    """

    def __init__(self, replicas, *, hedge_ms=None, telemetry=None,
                 trace_sample_rate=None, incident_reporter=None):
        from ..obs.reqtrace import ServeTracer

        self._replicas = list(replicas)
        if not self._replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        first = self._replicas[0]
        settings = getattr(
            getattr(getattr(first, "engine", None), "index", None),
            "settings",
            {},
        ) or {}
        if hedge_ms is None:
            hedge_ms = settings.get("serve_hedge_ms", 0) or 0
        self.hedge_ms = hedge_ms
        # Request tracing (obs v2): the router MINTS the trace context —
        # one trace_id per logical request, one attempt per replica
        # dispatch (primary / failover / hedge) — and each replica closes
        # the attempts it resolves through its own tracer, so phase
        # attribution lands on the replica that did the work. The shared
        # TraceRoot guarantees exactly one `delivered` span tree per
        # request even when a hedge race serves it twice.
        if trace_sample_rate is None:
            trace_sample_rate = settings.get("serve_trace_sample_rate", 0.0)
        self._tracer = ServeTracer(trace_sample_rate or 0.0, service="router")
        self._obs = telemetry
        # optional FleetIncidentReporter (obs/fleet.py): the router feeds
        # it hedge dispatches so a hedge STORM — every primary slow at
        # once — triggers a correlated incident bundle
        self._incident = incident_reporter
        self._lock = lockwatch.new_lock("ReplicaRouter._lock")
        self._rr = 0
        self.dispatched = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.failovers = 0

    def _bump(self, counter: str) -> None:
        """Increment a router counter under the lock: counters are hit
        from timer threads and replica done-callback threads, and ``+=``
        is not atomic."""
        with self._lock:
            setattr(self, counter, getattr(self, counter) + 1)

    # -- routing order --------------------------------------------------

    def _ordered(self) -> list:
        """Replicas ranked healthy < degraded < broken, round-robin within
        a rank (the rotation point advances per request)."""
        with self._lock:
            start = self._rr
            self._rr += 1
        n = len(self._replicas)
        rotated = [self._replicas[(start + i) % n] for i in range(n)]
        return sorted(
            rotated,
            key=lambda svc: health_rank(getattr(svc, "health_state", "broken")),
        )

    def _hedge_delay_ms(self, primary) -> float | None:
        if not self.hedge_ms or len(self._replicas) < 2:
            return None
        if self.hedge_ms == "p95":
            try:
                p95 = primary.latency_summary().get("p95_ms")
            except Exception:  # noqa: BLE001 - a fake replica may not report
                p95 = None
            return max(float(p95 or 0.0), _DEFAULT_HEDGE_FLOOR_MS)
        return float(self.hedge_ms)

    # -- request path ---------------------------------------------------

    def submit(self, record: dict, deadline_ms: float | None = None):
        """Dispatch one record; returns a Future[QueryResult] that never
        raises: first non-shed replica result wins, shed results fail
        over, the hedge timer (when enabled) races a second replica."""
        order = self._ordered()
        call = _HedgedCall(
            self, order, record, deadline_ms, self._hedge_delay_ms(order[0]),
            trace=self._tracer.maybe_start(),
        )
        call.start()
        return call.out

    def query(
        self,
        record: dict,
        timeout: float | None = None,
        deadline_ms: float | None = None,
    ):
        """Submit and wait. On timeout the caller gets a shed result; the
        per-replica timeout bookkeeping lives in each service."""
        from .service import QueryResult

        fut = self.submit(record, deadline_ms=deadline_ms)
        try:
            return fut.result(timeout=timeout)
        except Exception:  # noqa: BLE001 - the router future never raises by contract
            return QueryResult(shed=True, reason="timeout")

    # -- introspection / lifecycle --------------------------------------

    def health(self) -> dict:
        """Per-replica health snapshots plus the router's own counters."""
        replicas = []
        for svc in self._replicas:
            try:
                replicas.append(svc.health())
            except Exception as e:  # noqa: BLE001 - a dead replica still reports
                replicas.append({"state": "broken", "error": str(e)})
        return {
            "replicas": replicas,
            "dispatched": self.dispatched,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "failovers": self.failovers,
        }

    def close(self) -> None:
        """Close every replica that exposes ``close()`` (convenience for
        single-process deployments that own their replicas)."""
        for svc in self._replicas:
            close = getattr(svc, "close", None)
            if close is not None:
                close()


class _HedgedCall:
    """One routed request: sequential failover over the ranked replicas,
    plus at most one time-triggered hedge dispatch. Thread-safe; the
    ``out`` future resolves exactly once."""

    def __init__(self, router, order, record, deadline_ms, hedge_delay_ms,
                 trace=None):
        from concurrent.futures import Future

        self.router = router
        self.order = order
        self.record = record
        self.deadline_ms = deadline_ms
        self.hedge_delay_ms = hedge_delay_ms
        self.trace = trace  # shared-root context; one child per attempt
        self.out: Future = Future()
        self._lock = lockwatch.new_lock("_HedgedCall._lock")
        self._next = 0
        self._inflight = 0
        self._hedge_idx = None  # the exact attempt index the hedge dispatched
        self._last_shed = None
        # the resolution claim: flipped exactly once, under _lock, by the
        # attempt that wins the right to resolve ``out`` — set_result
        # itself then runs OUTSIDE the lock (done-callbacks are foreign
        # code and must not execute under it)
        self._resolved = False
        self._timer: threading.Timer | None = None
        self._t0 = time.monotonic()

    def start(self) -> None:
        self._dispatch_next()
        timer = None
        with self._lock:
            # arm under the lock: the first attempt may resolve on another
            # thread before we get here, and ITS cancel must see the timer
            if (
                self.hedge_delay_ms is not None
                and not self._resolved
                and self._next < len(self.order)
            ):
                timer = threading.Timer(
                    self.hedge_delay_ms / 1000.0, self._hedge
                )
                timer.daemon = True
                self._timer = timer
        if timer is not None:
            timer.start()

    def _dispatch_next(self, hedge: bool = False) -> int | None:
        """Dispatch to the next replica in the order; returns its attempt
        index, or None when exhausted / already resolved. ``hedge`` tags
        the attempt as THE hedge dispatch before its callback can run, so
        the win accounting cannot race a synchronously resolving
        replica."""
        with self._lock:
            if self._resolved or self._next >= len(self.order):
                return None
            idx = self._next
            self._next += 1
            self._inflight += 1
            if hedge:
                self._hedge_idx = idx
            svc = self.order[idx]
        self.router._bump("dispatched")
        # trace propagation is duck-typed like the replicas themselves:
        # only a replica that declares `accepts_trace` (LinkageService, or
        # a future RPC stub that forwards the context) receives the
        # attempt; fakes and plain replicas keep the PR 6 signature
        att = None
        if self.trace is not None and getattr(svc, "accepts_trace", False):
            att = self.trace.child(attempt=idx, hedge=hedge)
        try:
            if att is not None:
                fut = svc.submit(
                    self.record, deadline_ms=self.deadline_ms, trace=att
                )
            else:
                fut = svc.submit(self.record, deadline_ms=self.deadline_ms)
        except Exception as e:  # noqa: BLE001 - a throwing replica is a shed
            logger.warning("replica submit failed, failing over: %s", e)
            self.router._tracer.close(att, "shed", reason="submit_error")
            self._finish_attempt(idx, None)
            return idx
        fut.add_done_callback(lambda f, i=idx: self._on_done(i, f))
        return idx

    def _hedge(self) -> None:
        if self.out.done():
            return
        if self._dispatch_next(hedge=True) is not None:
            self.router._bump("hedges")
            reporter = self.router._incident
            if reporter is not None:
                # outside every lock: note_hedge may trigger a bundle
                # thread and must not serialize the hedge timer
                reporter.note_hedge()

    def _on_done(self, idx: int, fut) -> None:
        try:
            res = fut.result()
        except Exception as e:  # noqa: BLE001 - replica futures should not raise
            logger.warning("replica future raised (treated as shed): %s", e)
            res = None
        self._finish_attempt(idx, res)

    def _finish_attempt(self, idx: int, res) -> None:
        # Decide under the lock, act after releasing it: the winner claims
        # `_resolved` inside the critical section, then resolves `out`
        # (whose done-callbacks may grab the router's counter lock or run
        # user code) and cancels the timer with no lock held.
        win = hedge_won = False
        timer = None
        with self._lock:
            self._inflight -= 1
            if self._resolved:
                return
            if res is not None and not res.shed:
                self._resolved = True
                win = True
                hedge_won = idx == self._hedge_idx  # the hedge itself won
                timer = self._timer
            else:
                if res is not None:
                    self._last_shed = res
                exhausted = self._next >= len(self.order)
                settle = exhausted and self._inflight == 0
        if win:
            self.out.set_result(res)
            if timer is not None:
                timer.cancel()
            if hedge_won:
                self.router._bump("hedge_wins")
            return
        if not exhausted:
            self.router._bump("failovers")
            if self._dispatch_next() is None:
                with self._lock:
                    settle = self._inflight == 0 and not self._resolved
        if not settle:
            return
        from .service import QueryResult

        with self._lock:
            if self._resolved:  # lost the settle race to a late winner
                return
            self._resolved = True
            last = self._last_shed or QueryResult(shed=True, reason="no_replica")
            timer = self._timer
        self.out.set_result(last)
        if timer is not None:
            timer.cancel()
