"""LinkageIndex: the frozen, versioned serving artifact.

Everything built so far is batch/offline — train a model, score every pair,
exit. This module is the bridge to ONLINE linkage: a trained ``Splink``
linker freezes into a :class:`LinkageIndex`, a self-contained artifact that
a query service loads once and serves from for its whole lifetime. It holds

  * the encoded reference table as the packed uint32 row matrix the gamma
    kernels gather from (``gammas.pack_table`` layout — resident on device
    for the life of the engine, so a query batch costs exactly two row
    gathers like the offline path),
  * a per-blocking-rule hash-bucket index over the same packed key codes
    blocking.py joins on (``_key_codes``): rows grouped by combined key
    code in CSR form (``rows_sorted``/``starts``/``sizes``) plus a
    per-row bucket id for device-side sequential-rule dedup, plus the
    host-side key -> bucket dictionary a query record resolves through,
  * the trained Fellegi-Sunter parameters,
  * the term-frequency tables (per-token counts) of every TF-flagged
    column, and the per-column vocabularies that bind query-side encoding
    to the reference factorisation.

Durability mirrors the EM checkpoints (resilience/checkpoint.py, whose
atomic-write machinery this reuses): the artifact is versioned, the meta
JSON is the atomic commit point, the settings are hash-bound (an index
built for different settings or a different reference extract is rejected,
never silently served), and the array payload carries a content fingerprint
verified at load.

Serving restriction: blocking rules must be pure equality conjunctions
(``l.a = r.a AND substr(l.b,1,3) = substr(r.b,1,3)`` — symmetric keys,
derived-key expressions included). Residual predicates and cross-column
equalities have no bucket structure to index; :func:`build_index` rejects
them with a clear error rather than serving wrong candidates.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
from dataclasses import dataclass, field

import numpy as np

from ..blocking import _key_codes, _sort_groups, clear_key_code_cache
from ..compat_sql import parse_blocking_rule
from ..data import (
    EncodedStringColumn,
    EncodedTable,
    encode_table,
)
from ..gammas import (
    charset_specs_for,
    comparison_columns_used,
    pack_table,
    qgram_specs_for,
)
from ..resilience.checkpoint import (
    atomic_write_bytes,
    atomic_write_json,
    fsync_dir,
    settings_state_hash,
)

logger = logging.getLogger("splink_tpu")

INDEX_VERSION = 1
META_NAME = "linkage_index.json"
ARRAYS_STEM = "linkage_index"  # arrays live at <stem>-<sha16>.npz

BUILD_STATE_NAME = "build_state.json"
BUILD_STATE_VERSION = 1

# row chunk for hashing / streaming large arrays: big enough that per-chunk
# python overhead vanishes, small enough that the transient contiguous copy
# stays tens of MB
_HASH_CHUNK_ROWS = 1 << 18


def _hash_update_array(h, arr: np.ndarray, chunk_rows: int = _HASH_CHUNK_ROWS):
    """h.update() over an array's bytes in row chunks. Byte-identical to
    ``h.update(np.ascontiguousarray(arr).tobytes())`` — row-chunk bytes of
    a row-major array concatenate to the whole-array bytes — WITHOUT the
    full-size contiguous copy that call materialises: the out-of-core
    build hands content_fingerprint a disk-backed packed matrix, and the
    fingerprint walk must not be the step that re-materialises it in
    host RAM."""
    if arr.ndim == 0 or len(arr) == 0:
        h.update(np.ascontiguousarray(arr).tobytes())
        return
    for s in range(0, len(arr), chunk_rows):
        h.update(np.ascontiguousarray(arr[s : s + chunk_rows]).tobytes())
    # drop the pages a memmapped source just faulted in: the hash walk is
    # one sequential pass and must not leave the whole file resident
    mm = getattr(arr, "_mmap", None)
    if mm is not None:
        try:
            import mmap as _mmap

            mm.madvise(_mmap.MADV_DONTNEED)
        except (AttributeError, ValueError, OSError):
            pass

# canonical-key-token type tags (see _canon_token)
_KEY_SEP = "\x1f"


class ServeIndexError(RuntimeError):
    """Unreadable / corrupt / mismatched serving index."""


class IndexMismatchError(ServeIndexError):
    """Index belongs to a different job (settings hash, format version or
    array fingerprint disagree) — refusing to serve from it."""


def _canon_token(v) -> str | None:
    """Canonical string token for one blocking-key value, equality-isomorphic
    to the factorisation blocking.py keys on: strings compare by their
    ``str()`` form (token-id semantics), numbers by exact float value.
    None means null — a null key never joins (SQL equality)."""
    if v is None:
        return None
    if isinstance(v, (bool, np.bool_)):
        return f"b:{bool(v)}"
    if isinstance(v, (int, np.integer)):
        f = float(v)
        return f"n:{f!r}" if int(f) == int(v) else f"i:{int(v)}"
    if isinstance(v, (float, np.floating)):
        return f"n:{float(v)!r}"
    return f"s:{v}"


def _canonical_key_values(table: EncodedTable, col: str) -> np.ndarray:
    """(n_rows,) object array of canonical key values for one blocking-key
    column/expression; None where null. The single definition used at index
    build (reference side) and at query encode (query side), so the two
    sides cannot drift. Tokens materialise only for NON-null rows and the
    common families skip the _canon_token dispatch per value (a build over
    the full reference walks this once per rule key column)."""
    import pandas as pd

    n = table.n_rows
    out = np.empty(n, dtype=object)
    out[:] = None
    if col in table.strings:
        sc = table.strings[col]
        nz = np.flatnonzero(~sc.null_mask)
        out[nz] = [f"s:{sc.values[i]}" for i in nz]
        return out
    if col in table.numerics:
        nc = table.numerics[col]
        nz = np.flatnonzero(~nc.null_mask)
        # .tolist() yields PYTHON floats: numpy 2 reprs scalars as
        # "np.float64(x)", which would silently split every bucket key
        vals = nc.values_f64.tolist()
        out[nz] = [f"n:{vals[i]!r}" for i in nz]
        return out
    if col in table.raw:
        vals = table.raw[col]
        null = pd.isna(pd.Series(vals)).to_numpy()
        nz = np.flatnonzero(~null)
        out[nz] = [_canon_token(vals[i]) for i in nz]
        return out
    from ..derived_keys import is_plain_column, key_values_object

    if is_plain_column(col):
        raise KeyError(f"blocking key column {col!r} is not in the table")
    vals, null = key_values_object(table, col)
    nz = np.flatnonzero(~np.asarray(null))
    out[nz] = [_canon_token(vals[i]) for i in nz]
    return out


def _encode_value_chars(
    bytes_: np.ndarray, lengths: np.ndarray, row: int, value: str,
    width: int, kind: str,
) -> None:
    """Write one query value's chars into ``row`` of (bytes_, lengths)
    with the reference byte semantics — values truncate at the reference
    width; a non-ASCII char in an ascii column becomes 0xFF, which
    definitionally matches no reference byte. The ONE definition behind
    ``LinkageIndex._pin_string_column`` and ``_encode_query_bytes``: the
    serve-fallback parity contract needs query-side gram sets bit-equal
    to the reference encoding, so the rule must not fork."""
    chars = value[:width]
    lengths[row] = len(chars)
    for j, ch in enumerate(chars):
        cp = ord(ch)
        if kind == "ascii":
            bytes_[row, j] = cp if cp < 128 else 0xFF
        else:
            bytes_[row, j] = cp


def _encode_query_bytes(
    sc: EncodedStringColumn, width: int, kind: str, rows: np.ndarray
):
    """(bytes, lengths) for the given ``rows`` of a query string column,
    pinned to the REFERENCE width and ascii/wide kind (the byte semantics
    of ``_encode_value_chars``), without the vocabulary work the minhash
    kernel doesn't need. Null rows keep length 0 (no grams). Encoding
    only the requested rows keeps the serve fallback's cost proportional
    to the MISSED queries, not the whole batch."""
    n = len(rows)
    dt = np.uint8 if kind == "ascii" else np.uint32
    bytes_ = np.zeros((n, width), dt)
    lengths = np.zeros(n, np.int32)
    for k, i in enumerate(rows):
        if sc.null_mask[i]:
            continue
        _encode_value_chars(bytes_, lengths, k, str(sc.values[i]), width, kind)
    return bytes_, lengths


def _rule_key_cols(rule: str) -> list[str]:
    """The symmetric equality key columns of one blocking rule, or raise
    for shapes serving cannot index (residuals, cross-column keys, keyless
    rules)."""
    from ..blocking import _split_join_keys

    eq_pairs, residual = parse_blocking_rule(rule)
    sym, asym, residual = _split_join_keys(eq_pairs, residual)
    if residual is not None:
        raise ValueError(
            f"blocking rule {rule!r} has a non-equality residual predicate; "
            "online serving indexes pure equality conjunctions only — move "
            "the filter into the comparison columns or drop it for serving"
        )
    if asym:
        raise ValueError(
            f"blocking rule {rule!r} joins across different columns/"
            "expressions (l.a = r.b); online serving indexes symmetric "
            "keys only"
        )
    if not sym:
        raise ValueError(
            f"blocking rule {rule!r} has no equality condition (cartesian); "
            "online serving requires at least one equality key"
        )
    return sym


@dataclass
class ServeRule:
    """One blocking rule's frozen hash-bucket index."""

    rule: str
    key_cols: list[str]
    rows_sorted: np.ndarray  # (n_valid,) int32: rows grouped by bucket
    starts: np.ndarray  # (n_buckets,) int32 CSR starts into rows_sorted
    sizes: np.ndarray  # (n_buckets,) int32 bucket sizes
    row_bucket: np.ndarray  # (n_rows,) int32 bucket of each row; -1 null key
    bucket_of: dict = field(default_factory=dict)  # canonical key -> bucket

    @property
    def n_buckets(self) -> int:
        return len(self.starts)

    def query_bucket(self, key_tokens: list) -> int:
        """Bucket index for one query's canonical key tokens; -1 when any
        key is null or the combination is absent from the reference."""
        if any(t is None for t in key_tokens):
            return -1
        return self.bucket_of.get(_KEY_SEP.join(key_tokens), -1)


@dataclass
class ApproxBand:
    """One LSH band's frozen bucket index — the same CSR quartet as a
    :class:`ServeRule`, so the engine's candidate-gather kernel consumes a
    band exactly like a blocking rule (the cross-band dedup IS the
    sequential-rule dedup mask)."""

    rows_sorted: np.ndarray  # (n_valid,) int32
    starts: np.ndarray  # (n_buckets,) int32
    sizes: np.ndarray  # (n_buckets,) int32
    row_bucket: np.ndarray  # (n_rows,) int32; -1 = no signature
    bucket_of: dict = field(default_factory=dict)  # int band key -> bucket


@dataclass
class ApproxServe:
    """The serve fallback bucket path (docs/blocking.md#approximate-tier):
    minhash-LSH band buckets over the approx columns. A query whose EXACT
    keys hit no bucket resolves its band keys through ``bucket_of`` and is
    scored against the union of its band buckets instead of returning
    empty; results are tagged ``approx=True``."""

    cols: list[str]
    col_meta: dict  # name -> {"width": int, "kind": "ascii"|"wide"}
    q: int
    bands: int
    rows_per_band: int
    band_index: list[ApproxBand] = field(default_factory=list)
    # TF-weighting IDF table (approx_tf_weighting; minhash.idf_weights):
    # query-side fallback signatures MUST draw from the same weights the
    # index build drew from, so the table rides in the artifact. None =
    # unweighted tier.
    idf: np.ndarray | None = None


@dataclass
class QueryBatch:
    """Host-side encoded query batch, ready for the engine.

    ``qbuckets`` covers the engine's FULL gather menu: one row per exact
    blocking rule followed by one row per approx LSH band (all -1 when the
    index carries no approx tier or the query resolved exactly).
    ``approx_used`` marks queries served through the fallback bucket
    path."""

    packed: np.ndarray  # (n, n_lanes) uint32, same layout as the index
    qbuckets: np.ndarray  # (n_gather, n) int32; -1 = no candidates
    n: int
    unique_id: np.ndarray  # (n,) query ids (positional when absent)
    approx_used: np.ndarray | None = None  # (n,) bool, None = no approx tier
    # (n_tf_fold, n) int32 query token ids for the TF fold columns (the
    # reference-vocabulary ids _pin_string_column resolved — an unseen
    # query value takes a fresh id past the vocabulary, which can never
    # agree with a reference row); None when the index has no fold data
    tf_tids: np.ndarray | None = None


class LinkageIndex:
    """Frozen serving artifact for one trained linker (module docstring)."""

    def __init__(
        self,
        *,
        settings: dict,
        dtype: str,
        lam: float,
        m: np.ndarray,
        u: np.ndarray,
        packed: np.ndarray,
        layout: dict,
        string_cols: list[str],
        numeric_cols: list[str],
        string_meta: dict,
        rules: list[ServeRule],
        unique_id: np.ndarray,
        tf_tables: dict,
        state_hash: str,
        approx: ApproxServe | None = None,
        profile=None,
        tf_tids: dict | None = None,
    ):
        self.settings = settings
        self.dtype = dtype  # "float32" | "float64"
        self.lam = float(lam)
        self.m = np.asarray(m)
        self.u = np.asarray(u)
        self.packed = packed
        self.layout = layout
        self.string_cols = string_cols
        self.numeric_cols = numeric_cols
        self.string_meta = string_meta  # name -> {width, kind, vocab}
        self.rules = rules
        self.unique_id = unique_id
        self.tf_tables = tf_tables  # name -> (n_tokens,) int64 counts
        # name -> (n_rows,) int32 reference token ids for the TF fold
        # (term_frequencies.tf_fold_spec columns). Empty on artifacts
        # built before the fold existed — such indexes serve UNADJUSTED
        # exactly as they always did (engine warns once).
        self.tf_tids = dict(tf_tids or {})
        self.state_hash = state_hash
        self.approx = approx  # LSH fallback bucket path (None = exact only)
        # training-reference quality profile (obs/quality.py) — None on
        # profile-less artifacts (quality_profile off, or a legacy index):
        # drift reporting goes dark with a reason, serving is unchanged.
        # Deliberately NOT part of content_fingerprint(): the profile is
        # observability data, no compiled executable reads it, so adding
        # one must not invalidate an AOT sidecar.
        self.profile = profile
        self._device = None  # memoised device-resident arrays
        self._tf_device = None  # memoised TF-fold device arrays
        self._vocab_maps: dict | None = None
        self._content_fp: str | None = None

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    @property
    def n_rows(self) -> int:
        return len(self.unique_id)

    @property
    def n_lanes(self) -> int:
        return self.packed.shape[1]

    @property
    def float_dtype(self):
        return np.float64 if self.dtype == "float64" else np.float32

    @property
    def gather_units(self) -> list:
        """The engine's full candidate-gather menu: the exact blocking
        rules followed by the approx LSH bands (each entry carries the
        same rows_sorted/starts/sizes/row_bucket CSR quartet, so the
        gather kernel is agnostic to which tier an entry came from)."""
        units = list(self.rules)
        if self.approx is not None:
            units.extend(self.approx.band_index)
        return units

    def tf_fold_columns(self) -> list:
        """The TF u-probability fold menu this index can serve:
        ``term_frequencies.tf_fold_spec`` entries whose column has BOTH a
        count table and per-row reference token ids in the artifact.
        Empty for TF-less models and for legacy artifacts that predate
        the fold data (those serve unadjusted, as before)."""
        from ..term_frequencies import tf_fold_spec

        return [
            (ci, name, top)
            for ci, name, top in tf_fold_spec(self.settings)
            if name in self.tf_tables and name in self.tf_tids
        ]

    def tf_device_state(self):
        """Memoised TF-fold device arrays for :meth:`tf_fold_columns`, in
        spec order: ``tid`` (per column (n_rows,) int32 reference token
        ids) and ``log`` (the :func:`~..term_frequencies.tf_log_table`
        values cast to the index's compute dtype). Uploaded once, shared
        by every query batch — only built when an engine actually folds."""
        if self._tf_device is None:
            import jax.numpy as jnp

            from ..term_frequencies import tf_log_table

            dt = self.float_dtype
            cols = self.tf_fold_columns()
            self._tf_device = {
                "tid": tuple(
                    jnp.asarray(self.tf_tids[name]) for _, name, _t in cols
                ),
                "log": tuple(
                    jnp.asarray(
                        tf_log_table(self.tf_tables[name]).astype(dt)
                    )
                    for _, name, _t in cols
                ),
            }
        return self._tf_device

    def content_fingerprint(self) -> str:
        """sha256 over every array a serve executable's answers depend on
        (packed matrix, per-rule CSR, trained parameters, dtype, settings
        hash) — the identity the AOT executable sidecar binds to. Two
        indexes with the same fingerprint produce bit-identical kernel
        results; anything else invalidates the sidecar. Memoised (one hash
        walk over ~the artifact size)."""
        if self._content_fp is None:
            h = hashlib.sha256()
            h.update(self.state_hash.encode())
            h.update(self.dtype.encode())
            # row-chunked: the packed matrix may be a disk-backed memmap
            # (out-of-core build) whose whole-array tobytes() would
            # re-materialise exactly the footprint the build avoided;
            # digest is byte-identical to the one-shot form
            _hash_update_array(h, self.packed)
            for r in self.rules:
                for a in (r.rows_sorted, r.starts, r.sizes, r.row_bucket):
                    _hash_update_array(h, a)
            if self.approx is not None:
                # approx config + band CSRs change the compiled gather
                # menu, so they are part of the executable-binding
                # identity; an exact-only index hashes exactly as before
                ap = self.approx
                h.update(
                    f"approx:{ap.q}:{ap.bands}:{ap.rows_per_band}:"
                    f"{','.join(ap.cols)}".encode()
                )
                if ap.idf is not None:
                    # the IDF table shapes query-side fallback band keys
                    h.update(np.ascontiguousarray(ap.idf).tobytes())
                for band in ap.band_index:
                    for a in (band.rows_sorted, band.starts, band.sizes,
                              band.row_bucket):
                        h.update(np.ascontiguousarray(a).tobytes())
            if self.tf_tids:
                # the fold data changes what a TF-serving executable
                # answers, so it joins the executable-binding identity; a
                # fold-less index (TF-less OR legacy) hashes exactly as
                # before
                for name in sorted(self.tf_tids):
                    h.update(f"tf:{name}".encode())
                    h.update(
                        np.ascontiguousarray(self.tf_tids[name]).tobytes()
                    )
                    h.update(
                        np.ascontiguousarray(
                            self.tf_tables[name]
                        ).tobytes()
                    )
            h.update(np.float64(self.lam).tobytes())
            h.update(np.ascontiguousarray(self.m, np.float64).tobytes())
            h.update(np.ascontiguousarray(self.u, np.float64).tobytes())
            self._content_fp = h.hexdigest()
        return self._content_fp

    def candidate_counts(self, qbuckets: np.ndarray) -> np.ndarray:
        """(n,) int64 upper-bound candidate count per query (duplicates
        across rules/bands included — the capacity the engine pads to)."""
        total = np.zeros(qbuckets.shape[1], np.int64)
        for r, unit in enumerate(self.gather_units):
            qb = qbuckets[r]
            has = qb >= 0
            total[has] += unit.sizes[qb[has]]
        return total

    # ------------------------------------------------------------------
    # Device residency
    # ------------------------------------------------------------------

    def device_state(self):
        """Memoised device-resident arrays: the packed reference matrix,
        the per-rule bucket CSR arrays and the trained FSParams — uploaded
        once, shared by every query batch for the index's lifetime."""
        if self._device is None:
            import jax.numpy as jnp

            from ..models.fellegi_sunter import FSParams

            dt = self.float_dtype
            units = self.gather_units
            self._device = {
                "packed": jnp.asarray(self.packed),
                "starts": tuple(jnp.asarray(r.starts) for r in units),
                "sizes": tuple(jnp.asarray(r.sizes) for r in units),
                "rows": tuple(jnp.asarray(r.rows_sorted) for r in units),
                "row_bucket": tuple(
                    jnp.asarray(r.row_bucket) for r in units
                ),
                "params": FSParams(
                    lam=jnp.asarray(np.asarray(self.lam, dt)),
                    m=jnp.asarray(self.m.astype(dt)),
                    u=jnp.asarray(self.u.astype(dt)),
                ),
            }
        return self._device

    # ------------------------------------------------------------------
    # Query-side encoding
    # ------------------------------------------------------------------

    def encode_queries(self, df) -> QueryBatch:
        """Encode a query DataFrame into the index's packed layout.

        Query records encode against the REFERENCE vocabulary: a query
        string seen in the reference takes its reference token id (so exact
        and token-equality comparisons behave identically to the offline
        pipeline); unseen values take fresh ids past the reference
        vocabulary. Char/length/numeric encoding is pinned to the reference
        layout (width, ascii/wide kind, f32/f64 lanes), so the packed query
        matrix is gather-compatible with the resident reference matrix and
        gammas are bit-identical to the offline program on shared records.
        """
        import pandas as pd

        settings = self.settings
        uid_col = settings["unique_id_column_name"]
        if uid_col not in df.columns:
            df = df.copy()
            df[uid_col] = np.arange(len(df))
        qtable = encode_table(df, settings)
        # pin every packed string column to the reference encoding
        for name in self.string_cols:
            if name not in qtable.strings:
                raise ValueError(
                    f"query data is missing encoded column {name!r}"
                )
            qtable.strings[name] = self._pin_string_column(
                qtable.strings[name], self.string_meta[name]
            )
        # pack_table iterates insertion order; rebuild the dicts in the
        # exact order recorded at build so lanes line up byte for byte
        qtable.strings = {
            **{n: qtable.strings[n] for n in self.string_cols},
            **{
                n: c
                for n, c in qtable.strings.items()
                if n not in self.string_cols
            },
        }
        for name in self.numeric_cols:
            if name not in qtable.numerics:
                raise ValueError(
                    f"query data is missing numeric column {name!r}"
                )
        qtable.numerics = {
            **{n: qtable.numerics[n] for n in self.numeric_cols},
            **{
                n: c
                for n, c in qtable.numerics.items()
                if n not in self.numeric_cols
            },
        }
        import jax.numpy as jnp

        float_dtype = (
            jnp.float64 if self.dtype == "float64" else jnp.float32
        )
        packed_q, _ = pack_table(
            qtable,
            float_dtype,
            include=comparison_columns_used(settings),
            qgram_specs=qgram_specs_for(settings),
            charset_specs=charset_specs_for(settings),
            jw_specs=(),
        )
        if packed_q.shape[1] != self.n_lanes:
            raise ServeIndexError(
                f"query packing produced {packed_q.shape[1]} lanes but the "
                f"index holds {self.n_lanes} — the settings or encoding "
                "drifted from the artifact"
            )
        n_rules = len(self.rules)
        n_gather = len(self.gather_units)
        qbuckets = np.full((n_gather, len(df)), -1, np.int32)
        for r, rule in enumerate(self.rules):
            tokens = [
                _canonical_key_values(qtable, col) for col in rule.key_cols
            ]
            for q in range(len(df)):
                qbuckets[r, q] = rule.query_bucket(
                    [t[q] for t in tokens]
                )
        approx_used = None
        if self.approx is not None:
            # fallback bucket path: queries whose EXACT keys all missed
            # resolve their LSH band keys instead of returning empty.
            # Signatures are computed for the MISSED rows only — a batch
            # with one garbled query must not pay the per-character
            # re-encode + minhash kernel for every clean row in it.
            missed = ~(qbuckets[:n_rules] >= 0).any(axis=0)
            approx_used = np.zeros(len(df), bool)
            if missed.any():
                rows = np.flatnonzero(missed)
                keys, has_sig = self._query_band_keys(qtable, rows)
                for b, band in enumerate(self.approx.band_index):
                    row = qbuckets[n_rules + b]
                    for k, q in enumerate(rows):
                        if has_sig[k]:
                            row[q] = band.bucket_of.get(
                                int(keys[k, b]), -1
                            )
                approx_used = missed & (qbuckets[n_rules:] >= 0).any(axis=0)
        tf_tids = None
        fold_cols = self.tf_fold_columns()
        if fold_cols:
            # fold-column token ids from the PINNED columns: a query value
            # present in the reference vocabulary carries its reference id
            # (agreement is id equality on device), an unseen value a
            # fresh id past it (never agrees), null -1
            tf_tids = np.stack(
                [qtable.strings[name].token_ids for _, name, _t in fold_cols]
            ).astype(np.int32)
        return QueryBatch(
            packed=packed_q,
            qbuckets=qbuckets,
            n=len(df),
            unique_id=np.asarray(pd.Series(df[uid_col]).to_numpy()),
            approx_used=approx_used,
            tf_tids=tf_tids,
        )

    def _query_band_keys(self, qtable: EncodedTable, rows: np.ndarray):
        """(keys (len(rows), bands) uint32, has_sig (len(rows),) bool) for
        the given query rows: every approx column re-encoded at the
        REFERENCE width/kind (the jitted minhash kernel is
        shape-specialised per column layout, so pinning keeps query-side
        signatures on the same compiled kernel as the index build — and
        gram sets identical for shared values)."""
        from ..approx.minhash import band_key_arrays

        ap = self.approx
        columns = []
        for name in ap.cols:
            sc = qtable.strings.get(name)
            if sc is None:
                raise ValueError(
                    f"query data is missing approx column {name!r}"
                )
            meta = ap.col_meta[name]
            columns.append(
                _encode_query_bytes(
                    sc, int(meta["width"]), meta["kind"], rows
                )
            )
        return band_key_arrays(
            columns, ap.q, ap.bands, ap.rows_per_band, idf=ap.idf
        )

    def _pin_string_column(
        self, sc: EncodedStringColumn, meta: dict
    ) -> EncodedStringColumn:
        """Re-encode a query string column against the reference layout:
        reference width, reference ascii/wide kind, reference vocabulary
        token ids (unseen values get fresh ids past the vocabulary)."""
        width = int(meta["width"])
        kind = meta["kind"]
        vocab = self._vocab_map_for(meta)
        n = len(sc.token_ids)
        n_ref = len(meta["vocab"])
        token_ids = np.full(n, -1, np.int32)
        fresh: dict[str, int] = {}
        if kind == "ascii":
            bytes_ = np.zeros((n, width), np.uint8)
        else:
            bytes_ = np.zeros((n, width), np.uint32)
        lengths = np.zeros(n, np.int32)
        for i in range(n):
            if sc.null_mask[i]:
                continue
            v = str(sc.values[i])
            tid = vocab.get(v)
            if tid is None:
                tid = fresh.get(v)
                if tid is None:
                    tid = fresh[v] = n_ref + len(fresh)
            token_ids[i] = tid
            _encode_value_chars(bytes_, lengths, i, v, width, kind)
        return EncodedStringColumn(
            bytes_=bytes_,
            lengths=lengths,
            token_ids=token_ids,
            null_mask=sc.null_mask,
            values=sc.values,
            width=width,
        )

    def _vocab_map_for(self, meta: dict) -> dict:
        key = id(meta)
        if self._vocab_maps is None:
            self._vocab_maps = {}
        vm = self._vocab_maps.get(key)
        if vm is None:
            vm = self._vocab_maps[key] = {
                v: i for i, v in enumerate(meta["vocab"])
            }
        return vm

    # ------------------------------------------------------------------
    # Persistence
    # ------------------------------------------------------------------

    def save(self, directory: str | os.PathLike) -> str:
        """Persist the artifact: arrays first (under a fingerprint-derived
        file name), then the meta JSON as the atomic commit point. Saving
        OVER an existing artifact is crash-safe: the new arrays land in a
        fresh file, so a crash before the meta commit leaves the previous
        meta still pointing at the previous (intact) arrays; superseded
        arrays files are swept only after the commit. Returns the meta
        path."""
        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
        arrays = {"packed": self.packed}
        for r, rule in enumerate(self.rules):
            arrays[f"rule{r}_rows"] = rule.rows_sorted
            arrays[f"rule{r}_starts"] = rule.starts
            arrays[f"rule{r}_sizes"] = rule.sizes
            arrays[f"rule{r}_row_bucket"] = rule.row_bucket
        if self.approx is not None:
            for b, band in enumerate(self.approx.band_index):
                arrays[f"approx{b}_rows"] = band.rows_sorted
                arrays[f"approx{b}_starts"] = band.starts
                arrays[f"approx{b}_sizes"] = band.sizes
                arrays[f"approx{b}_row_bucket"] = band.row_bucket
            if self.approx.idf is not None:
                arrays["approx_idf"] = self.approx.idf
        for name, counts in self.tf_tables.items():
            arrays[f"tf_{name}"] = counts
        for name, tids in self.tf_tids.items():
            arrays[f"tftid_{name}"] = tids
        if self.profile is not None:
            # inside the npz payload, so arrays_sha256 — the fingerprint
            # load_index verifies — covers the profile arrays too
            arrays["profile_gamma_hist"] = self.profile.gamma_hist
            arrays["profile_score_hist"] = self.profile.score_hist
            arrays["profile_gamma_hist_matched"] = (
                self.profile.gamma_hist_matched
            )
            arrays["profile_score_hist_matched"] = (
                self.profile.score_hist_matched
            )
        if self.unique_id.dtype != object:
            arrays["unique_id"] = self.unique_id
        if any(isinstance(a, np.memmap) for a in arrays.values()):
            # out-of-core artifact: the npz streams straight to a temp
            # file in the target directory (numpy writes each array
            # through the zip stream — never the whole payload in RAM),
            # the fingerprint comes from a chunked re-read, and os.replace
            # commits under the fingerprint-derived name exactly like the
            # resident path
            arrays_file, fingerprint = self._save_arrays_streaming(
                directory, arrays
            )
        else:
            buf = io.BytesIO()
            np.savez_compressed(buf, **arrays)
            payload = buf.getvalue()
            fingerprint = hashlib.sha256(payload).hexdigest()
            arrays_file = f"{ARRAYS_STEM}-{fingerprint[:16]}.npz"
            atomic_write_bytes(os.path.join(directory, arrays_file), payload)
        from ..params import _jsonable_settings

        meta = {
            "version": INDEX_VERSION,
            "state_hash": self.state_hash,
            "arrays_file": arrays_file,
            "arrays_sha256": fingerprint,
            "dtype": self.dtype,
            "settings": _jsonable_settings(self.settings),
            "lam": self.lam,
            "m": self.m.tolist(),
            "u": self.u.tolist(),
            "string_cols": self.string_cols,
            "numeric_cols": self.numeric_cols,
            "string_meta": self.string_meta,
            "rules": [
                {
                    "rule": r.rule,
                    "key_cols": r.key_cols,
                    "bucket_of": r.bucket_of,
                }
                for r in self.rules
            ],
            "tf_columns": sorted(self.tf_tables),
            "tf_tid_columns": sorted(self.tf_tids),
            "approx": (
                None
                if self.approx is None
                else {
                    "cols": list(self.approx.cols),
                    "col_meta": self.approx.col_meta,
                    "q": self.approx.q,
                    "bands": self.approx.bands,
                    "rows_per_band": self.approx.rows_per_band,
                    # JSON keys must be strings; band keys are uint32 ints
                    "bucket_of": [
                        {str(k): v for k, v in band.bucket_of.items()}
                        for band in self.approx.band_index
                    ],
                }
            ),
            "profile": (
                None if self.profile is None else self.profile.to_meta()
            ),
            "n_rows": self.n_rows,
            "unique_id_json": (
                self.unique_id.tolist()
                if self.unique_id.dtype == object
                else None
            ),
        }
        path = atomic_write_json(os.path.join(directory, META_NAME), meta)
        # post-commit sweep of superseded arrays files (best-effort: a
        # leftover costs disk, never correctness — meta names its file)
        try:
            for name in os.listdir(directory):
                if (
                    name.startswith(ARRAYS_STEM)
                    and name.endswith(".npz")
                    and name != arrays_file
                ):
                    os.unlink(os.path.join(directory, name))
        except OSError:  # pragma: no cover - sweep is best-effort
            pass
        logger.info(
            "linkage index saved: %s (%d rows, %d rules, %d lanes)",
            directory, self.n_rows, len(self.rules), self.n_lanes,
        )
        return path

    @staticmethod
    def _save_arrays_streaming(directory: str, arrays: dict):
        """Write the arrays npz without ever holding the payload in RAM:
        temp file in the target directory, fsync, chunked sha256 of the
        file bytes, then os.replace under the fingerprint-derived name
        (the same crash-safety shape as atomic_write_bytes). Returns
        (arrays_file, fingerprint)."""
        import tempfile

        fd, tmp = tempfile.mkstemp(
            prefix=ARRAYS_STEM + ".", suffix=".tmp", dir=directory
        )
        try:
            with os.fdopen(fd, "wb") as fh:
                np.savez_compressed(fh, **arrays)
                fh.flush()
                os.fsync(fh.fileno())
            h = hashlib.sha256()
            with open(tmp, "rb") as fh:
                while True:
                    block = fh.read(1 << 22)
                    if not block:
                        break
                    h.update(block)
            fingerprint = h.hexdigest()
            arrays_file = f"{ARRAYS_STEM}-{fingerprint[:16]}.npz"
            os.replace(tmp, os.path.join(directory, arrays_file))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        fsync_dir(directory)
        return arrays_file, fingerprint


def load_index(directory: str | os.PathLike) -> LinkageIndex:
    """Load a saved index, verifying format version, settings-hash binding
    and the array-payload fingerprint (a torn or tampered artifact is
    rejected, never served)."""
    directory = os.fspath(directory)
    meta_path = os.path.join(directory, META_NAME)
    try:
        with open(meta_path, encoding="utf-8") as fh:
            meta = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        raise ServeIndexError(f"unreadable index meta at {meta_path}: {e}") from e
    if meta.get("version") != INDEX_VERSION:
        raise IndexMismatchError(
            f"index at {directory} has format version "
            f"{meta.get('version')!r}; this build reads {INDEX_VERSION}. "
            "Rebuild the index with build_index()."
        )
    arrays_name = meta.get("arrays_file")
    if not arrays_name or os.path.sep in arrays_name:
        raise ServeIndexError(
            f"index meta at {meta_path} names no valid arrays file"
        )
    arrays_path = os.path.join(directory, arrays_name)
    try:
        with open(arrays_path, "rb") as fh:
            payload = fh.read()
    except OSError as e:
        raise ServeIndexError(f"unreadable index arrays at {arrays_path}: {e}") from e
    fingerprint = hashlib.sha256(payload).hexdigest()
    if fingerprint != meta.get("arrays_sha256"):
        raise IndexMismatchError(
            f"index arrays at {arrays_path} do not match the meta "
            "fingerprint (torn write or tampering); rebuild the index"
        )
    settings = meta["settings"]
    expect = settings_state_hash(
        settings, extra={"artifact": "linkage_index", "n_rows": meta["n_rows"]}
    )
    if expect != meta.get("state_hash"):
        raise IndexMismatchError(
            f"index at {directory} was written for a different job "
            f"(settings hash {meta.get('state_hash')!r}, recomputed "
            f"{expect!r}); rebuild the index"
        )
    npz = np.load(io.BytesIO(payload), allow_pickle=False)
    rules = []
    for r, rm in enumerate(meta["rules"]):
        rules.append(
            ServeRule(
                rule=rm["rule"],
                key_cols=list(rm["key_cols"]),
                rows_sorted=npz[f"rule{r}_rows"],
                starts=npz[f"rule{r}_starts"],
                sizes=npz[f"rule{r}_sizes"],
                row_bucket=npz[f"rule{r}_row_bucket"],
                bucket_of=dict(rm["bucket_of"]),
            )
        )
    if meta.get("unique_id_json") is not None:
        unique_id = np.asarray(meta["unique_id_json"], dtype=object)
    else:
        unique_id = npz["unique_id"]
    tf_tables = {name: npz[f"tf_{name}"] for name in meta.get("tf_columns", [])}
    # legacy artifacts carry no per-row token ids ("tf_tid_columns"
    # absent): tf_tids stays empty and the index serves unadjusted
    tf_tids = {
        name: npz[f"tftid_{name}"]
        for name in meta.get("tf_tid_columns", [])
    }
    approx = None
    am = meta.get("approx")
    if am is not None:
        approx = ApproxServe(
            cols=list(am["cols"]),
            col_meta=dict(am["col_meta"]),
            q=int(am["q"]),
            bands=int(am["bands"]),
            rows_per_band=int(am["rows_per_band"]),
            idf=npz["approx_idf"] if "approx_idf" in npz.files else None,
            band_index=[
                ApproxBand(
                    rows_sorted=npz[f"approx{b}_rows"],
                    starts=npz[f"approx{b}_starts"],
                    sizes=npz[f"approx{b}_sizes"],
                    row_bucket=npz[f"approx{b}_row_bucket"],
                    bucket_of={int(k): v for k, v in bo.items()},
                )
                for b, bo in enumerate(am["bucket_of"])
            ],
        )
    profile = None
    pm = meta.get("profile")
    if pm is not None:
        from ..obs.quality import QualityProfile

        files = set(npz.files)
        profile = QualityProfile.from_meta(
            pm,
            npz["profile_gamma_hist"],
            npz["profile_score_hist"],
            (
                npz["profile_gamma_hist_matched"]
                if "profile_gamma_hist_matched" in files
                else None
            ),
            (
                npz["profile_score_hist_matched"]
                if "profile_score_hist_matched" in files
                else None
            ),
        )
    return LinkageIndex(
        settings=settings,
        dtype=meta["dtype"],
        lam=meta["lam"],
        m=np.asarray(meta["m"]),
        u=np.asarray(meta["u"]),
        packed=npz["packed"],
        layout=None,  # rebuilt below
        string_cols=list(meta["string_cols"]),
        numeric_cols=list(meta["numeric_cols"]),
        string_meta=meta["string_meta"],
        rules=rules,
        unique_id=unique_id,
        tf_tables=tf_tables,
        state_hash=meta["state_hash"],
        approx=approx,
        profile=profile,
        tf_tids=tf_tids,
    )._rebuild_layout()


def _string_vocab(sc: EncodedStringColumn) -> list[str]:
    """token id -> stringified value, the factorisation the reference
    encoding committed to (token ids factorise the str() forms)."""
    tids = sc.token_ids
    n_tokens = sc.n_tokens
    vocab: list[str | None] = [None] * n_tokens
    uniq, first = np.unique(tids, return_index=True)
    for tid, idx in zip(uniq, first):
        if tid >= 0:
            vocab[int(tid)] = str(sc.values[int(idx)])
    return [v if v is not None else "" for v in vocab]


def _pack_table_out_of_core(
    table: EncodedTable,
    float_dtype,
    include,
    qgram_specs,
    charset_specs,
    build_dir: str,
    chunk_rows: int,
    state_hash: str,
    fault_plan=None,
):
    """Row-chunked, resumable pack_table: (packed memmap, layout).

    The packed reference matrix is the dominant resident term of an index
    build (n_rows x n_lanes x 4 bytes — at 100M rows of a 64-lane table,
    ~26 GB). pack_table's lane LAYOUT depends only on column metadata, so
    packing ``chunk_rows``-row windows (EncodedTable.slice_rows) produces
    exactly the corresponding rows of the full matrix; each chunk streams
    to ``<build_dir>/index_build/packed.bin`` with plain buffered writes
    (no mapping — the written pages live in the kernel's evictable page
    cache, not this process's anonymous RSS) and commits through an atomic
    ``build_state.json`` watermark. A killed build resumes at the last
    committed chunk; a state file from a different job/shape starts fresh.
    Returns a read-only memmap over the finished file — bit-identical,
    row for row, to what pack_table would have returned resident.
    """
    from ..resilience import faults as _faults
    from ..resilience.checkpoint import atomic_write_json

    if fault_plan is None:
        fault_plan = _faults.active_plan()
    out_dir = os.path.join(os.fspath(build_dir), "index_build")
    os.makedirs(out_dir, exist_ok=True)
    n = table.n_rows
    chunk_rows = max(int(chunk_rows), 1)
    # layout + lane count from a zero-row window — the same determinism
    # _layout_rebuild_table already relies on for load-time rebuilds
    probe, layout = pack_table(
        table.slice_rows(0, 0),
        float_dtype,
        include=include,
        qgram_specs=qgram_specs,
        charset_specs=charset_specs,
        jw_specs=(),
    )
    n_lanes = probe.shape[1]
    data_path = os.path.join(out_dir, "packed.bin")
    state_path = os.path.join(out_dir, BUILD_STATE_NAME)
    want_state = {
        "version": BUILD_STATE_VERSION,
        "state_hash": state_hash,
        "n_rows": int(n),
        "n_lanes": int(n_lanes),
        "chunk_rows": int(chunk_rows),
        "dtype": "float64" if float_dtype == np.float64 else "float32",
    }
    chunks_done = 0
    if os.path.exists(state_path) and os.path.exists(data_path):
        try:
            with open(state_path, encoding="utf-8") as fh:
                st = json.load(fh)
            if all(st.get(k) == v for k, v in want_state.items()):
                chunks_done = int(st.get("chunks_done", 0))
        except (OSError, json.JSONDecodeError, ValueError):
            chunks_done = 0
    n_chunks = -(-n // chunk_rows) if n else 0
    chunks_done = min(chunks_done, n_chunks)
    row_bytes = n_lanes * 4
    watermark = min(chunks_done * chunk_rows, n) * row_bytes
    if chunks_done:
        try:
            have = os.path.getsize(data_path)
        except OSError:
            have = -1
        if have < watermark:
            # data shorter than the committed watermark (partial copy of
            # the build dir, bin replaced while the state file survived):
            # truncate() below would silently ZERO-EXTEND the missing
            # prefix into all-zero packed rows — start fresh instead (the
            # spill store raises for the same condition; here a rebuild
            # is cheap and always correct)
            logger.warning(
                "out-of-core build state at %s commits %d bytes but "
                "packed.bin holds %d; discarding the stale watermark and "
                "rebuilding from chunk 0", out_dir, watermark, have,
            )
            chunks_done = 0
            watermark = 0
    if chunks_done:
        logger.info(
            "out-of-core index build resumed at %s: %d/%d packed chunks "
            "committed", out_dir, chunks_done, n_chunks,
        )
    with open(data_path, "r+b" if os.path.exists(data_path) else "w+b") as fh:
        fh.truncate(watermark)  # drop any torn uncommitted tail
        fh.seek(watermark)
        for k in range(chunks_done, n_chunks):
            s, e = k * chunk_rows, min((k + 1) * chunk_rows, n)
            arr, _ = pack_table(
                table.slice_rows(s, e),
                float_dtype,
                include=include,
                qgram_specs=qgram_specs,
                charset_specs=charset_specs,
                jw_specs=(),
            )
            if arr.shape[1] != n_lanes:  # pragma: no cover - layout is static
                raise ServeIndexError(
                    f"chunk {k} packed {arr.shape[1]} lanes, layout probe "
                    f"said {n_lanes}"
                )
            np.ascontiguousarray(arr).tofile(fh)
            fh.flush()
            os.fsync(fh.fileno())
            # the injection point sits between the byte append and the
            # watermark commit — the widest window a kill can tear
            fault_plan.fire("build_chunk", chunk=k)
            atomic_write_json(state_path, {**want_state, "chunks_done": k + 1})
    if n == 0:
        return np.zeros((0, n_lanes), np.uint32), layout
    packed = np.memmap(data_path, dtype=np.uint32, mode="r", shape=(n, n_lanes))
    return packed, layout


def build_index(linker, *, clear_caches: bool = True) -> LinkageIndex:
    """Freeze a trained linker into a :class:`LinkageIndex`.

    Uses the linker's current parameters (post ``estimate_parameters`` /
    loaded model) and its encoded input table as the reference corpus.
    ``clear_caches`` releases the per-table blocking key-code caches on
    completion: the bucket build runs through the same ``_key_codes`` cache
    blocking uses, and an index build holds its encoded table long-lived —
    without the release every cached key tuple (8 bytes/row each) would
    pin host RAM for the artifact's lifetime.
    """
    import jax.numpy as jnp

    settings = linker.settings
    table = linker._ensure_encoded()
    if table.n_rows == 0:
        raise ValueError("cannot build a serving index over an empty table")
    rules_text = settings.get("blocking_rules") or []
    if not rules_text:
        raise ValueError(
            "online serving requires at least one blocking rule (a keyless "
            "cartesian scan per query does not serve at low latency)"
        )
    try:
        dtype_np = linker._float_dtype
        float_dtype = jnp.float64 if dtype_np == np.float64 else jnp.float32
        lam, m, u, _ = linker.params.to_arrays(dtype=dtype_np)

        include = comparison_columns_used(settings)
        build_dir = settings.get("build_spill_dir") or None
        if build_dir:
            # out-of-core: the packed matrix streams to disk chunk by
            # chunk (bounded working set, resumable) and rides in the
            # index as a read-only memmap — every downstream consumer
            # (device_state upload, fingerprint, save) reads it the same.
            # Per-process root under multi-controller (the pairs path's
            # discipline): P processes must not race truncate/append on
            # one packed.bin — each writes its own deterministic,
            # fingerprint-identical copy instead.
            from ..parallel.distributed import spill_shard_dir

            packed, layout = _pack_table_out_of_core(
                table,
                float_dtype,
                include=include,
                qgram_specs=qgram_specs_for(settings),
                charset_specs=charset_specs_for(settings),
                build_dir=spill_shard_dir(build_dir),
                chunk_rows=int(
                    settings.get("build_spill_chunk_rows") or 1048576
                ),
                state_hash=settings_state_hash(
                    settings,
                    extra={
                        "artifact": "index_build",
                        "n_rows": int(table.n_rows),
                    },
                ),
            )
        else:
            packed, layout = pack_table(
                table,
                float_dtype,
                include=include,
                qgram_specs=qgram_specs_for(settings),
                charset_specs=charset_specs_for(settings),
                jw_specs=(),
            )
        string_cols = [
            n for n in table.strings if include is None or n in include
        ]
        numeric_cols = [
            n for n in table.numerics if include is None or n in include
        ]
        string_meta = {}
        for name in string_cols:
            sc = table.strings[name]
            string_meta[name] = {
                "width": int(sc.width),
                "kind": "ascii" if sc.bytes_.dtype == np.uint8 else "wide",
                "vocab": _string_vocab(sc),
            }

        # same backend policy as device_block_rules: 'auto' keeps the host
        # argsort on the CPU backend (the XLA-CPU sort measured slower —
        # BENCHMARKS.md round 8); 'on' forces the device CSR anywhere
        import jax

        blk_mode = settings.get("device_blocking", "auto")
        device_csr = blk_mode == "on" or (
            blk_mode != "off" and jax.default_backend() != "cpu"
        )
        rules = [
            _build_serve_rule(table, rule, device=device_csr)
            for rule in rules_text
        ]

        approx = None
        if settings.get("approx_blocking"):
            approx = _build_approx_serve(table, settings)

        # training-reference quality profile (obs/quality.py): the drift
        # observatory's baseline, captured from whichever training gammas
        # the linker still holds and published as a quality_profile event
        profile = None
        if settings.get("quality_profile"):
            from ..obs.events import publish
            from ..obs.quality import capture_profile

            profile = capture_profile(linker, table)
            if profile is None:
                import warnings

                warnings.warn(
                    "quality_profile is on but the linker holds no "
                    "training gammas (train with estimate_parameters / "
                    "get_scored_comparisons in this process before "
                    "export_index); the index ships WITHOUT a reference "
                    "profile and serve-time drift reporting will be dark."
                )
            else:
                publish("quality_profile", **profile.summary())
                if getattr(linker, "_obs", None) is not None:
                    linker._obs.record("quality_profile", profile.summary())

        from ..term_frequencies import term_frequency_columns, tf_fold_spec

        tf_tables = {}
        for name in term_frequency_columns(settings):
            sc = table.strings.get(name)
            if sc is not None and sc.n_tokens:
                tids = sc.token_ids
                tf_tables[name] = np.bincount(
                    tids[tids >= 0], minlength=sc.n_tokens
                ).astype(np.int64)
        # per-row reference token ids for the serve-time u-probability
        # fold (one per tf_fold_spec column with a count table): with
        # these in the artifact, serving scores ARE TF-adjusted — the old
        # "unadjusted at serve" warning is gone because the gap it warned
        # about is gone
        tf_tids = {
            name: table.strings[name].token_ids.astype(np.int32)
            for _ci, name, _top in tf_fold_spec(settings)
            if name in tf_tables
        }

        state_hash = settings_state_hash(
            settings,
            extra={"artifact": "linkage_index", "n_rows": int(table.n_rows)},
        )
        return LinkageIndex(
            settings=settings,
            dtype=np.dtype(dtype_np).name,
            lam=float(lam),
            m=np.asarray(m, np.float64),
            u=np.asarray(u, np.float64),
            packed=packed,
            layout=layout,
            string_cols=string_cols,
            numeric_cols=numeric_cols,
            string_meta=string_meta,
            rules=rules,
            unique_id=np.asarray(table.unique_id),
            tf_tables=tf_tables,
            state_hash=state_hash,
            approx=approx,
            profile=profile,
            tf_tids=tf_tids,
        )
    finally:
        if clear_caches:
            # the bucket build warmed the per-table key-code caches (one
            # int64 array per key tuple); the index keeps its own compact
            # CSR copies, so the caches must not outlive the build
            clear_key_code_cache(table)


def _build_serve_rule(
    table: EncodedTable, rule: str, device: bool = True
) -> ServeRule:
    """One rule's frozen bucket index from the same key codes blocking
    joins on. The device-resident part of the build — the bucket CSR
    (rows_sorted/starts/sizes/row_bucket) — runs through the device
    segmented-sort kernel (blocking_device.build_bucket_csr, bit-equal to
    the host construction); the host keeps only the O(buckets)
    representative-token dict loop. ``device=False`` (or an unsupported
    code range) takes the host argsort path."""
    key_cols = _rule_key_cols(rule)
    codes = _key_codes(table, key_cols)
    n = table.n_rows
    csr = None
    if device and n:
        from ..blocking_device import build_bucket_csr

        csr = build_bucket_csr(codes)
    if csr is not None:
        rows_sorted, starts, sizes, row_bucket_dev = csr
        n_buckets = len(starts)
    else:
        row_bucket_dev = None
        rows = np.flatnonzero(codes >= 0).astype(np.int32)
        rows_sorted, uniq_codes, starts, sizes = _sort_groups(codes, rows)
        n_buckets = len(uniq_codes)
    if n_buckets == 0:
        # every key null: empty dict, 1-element dummy CSR so device
        # gathers stay in bounds (qbucket is always -1)
        return ServeRule(
            rule=rule,
            key_cols=key_cols,
            rows_sorted=np.zeros(1, np.int32),
            starts=np.zeros(1, np.int32),
            sizes=np.zeros(1, np.int32),
            row_bucket=np.full(n, -1, np.int32),
        )
    if row_bucket_dev is not None:
        row_bucket = row_bucket_dev
    else:
        row_bucket = np.full(n, -1, np.int32)
        row_bucket[rows_sorted] = np.repeat(
            np.arange(n_buckets, dtype=np.int32), sizes
        )
    # host-side key -> bucket dictionary from one representative row per
    # bucket, via the same canonicalisation queries resolve through
    reps = rows_sorted[starts]
    col_tokens = [_canonical_key_values(table, c) for c in key_cols]
    bucket_of: dict[str, int] = {}
    for b, rep in enumerate(reps):
        tokens = [t[rep] for t in col_tokens]
        if any(tok is None for tok in tokens):  # pragma: no cover - codes>=0
            continue
        key = _KEY_SEP.join(tokens)
        if key in bucket_of:
            raise ValueError(
                f"blocking rule {rule!r}: two key groups canonicalise to "
                f"the same serving key {key!r}; this key type cannot be "
                "indexed for online serving"
            )
        bucket_of[key] = b
    return ServeRule(
        rule=rule,
        key_cols=key_cols,
        rows_sorted=rows_sorted.astype(np.int32),
        starts=starts.astype(np.int32),
        sizes=sizes.astype(np.int32),
        row_bucket=row_bucket,
        bucket_of=bucket_of,
    )


def _build_approx_serve(table: EncodedTable, settings: dict):
    """The index's LSH fallback tier: band-key bucket CSRs over the approx
    columns (splink_tpu/approx/minhash.py band keys — the SAME fixed-seed
    kernel the query side runs, so reference and query signatures agree for
    shared values). Returns None when no approx column exists."""
    # MAX_BUCKET_ROWS is the ONE degenerate-bucket contract, shared with
    # the offline tier: a band bucket wider than it is a near-constant
    # signature, so it stays in the CSR (cross-band dedup needs
    # row_bucket) but is never resolvable from the query side — serving
    # it would truncate at the candidate-bucket menu anyway while blowing
    # the padded capacity for every fallback batch.
    from ..approx.lsh import MAX_BUCKET_ROWS, ApproxConfig, compute_band_codes

    cfg = ApproxConfig.from_settings(settings, table)
    if cfg is None:
        return None
    band_codes, uniq_keys, idf = compute_band_codes(table, cfg)
    col_meta = {}
    for name in cfg.cols:
        sc = table.strings[name]
        col_meta[name] = {
            "width": int(sc.width),
            "kind": "ascii" if sc.bytes_.dtype == np.uint8 else "wide",
        }
    n = table.n_rows
    bands = []
    for b in range(cfg.bands):
        codes = band_codes[b]
        rows = np.flatnonzero(codes >= 0).astype(np.int32)
        rows_sorted, uniq_codes, starts, sizes = _sort_groups(
            codes.astype(np.int64), rows
        )
        if len(uniq_codes) == 0:
            bands.append(
                ApproxBand(
                    rows_sorted=np.zeros(1, np.int32),
                    starts=np.zeros(1, np.int32),
                    sizes=np.zeros(1, np.int32),
                    row_bucket=np.full(n, -1, np.int32),
                )
            )
            continue
        row_bucket = np.full(n, -1, np.int32)
        row_bucket[rows_sorted] = np.repeat(
            np.arange(len(uniq_codes), dtype=np.int32), sizes
        )
        # code order == ascending band-key order (factorise_band_codes), so
        # bucket k's key is uniq_keys[b][uniq_codes[k]]
        keys_of_bucket = uniq_keys[b][uniq_codes.astype(np.int64)]
        bucket_of = {
            int(keys_of_bucket[k]): int(k)
            for k in range(len(uniq_codes))
            if sizes[k] <= MAX_BUCKET_ROWS
        }
        bands.append(
            ApproxBand(
                rows_sorted=rows_sorted.astype(np.int32),
                starts=starts.astype(np.int32),
                sizes=sizes.astype(np.int32),
                row_bucket=row_bucket,
                bucket_of=bucket_of,
            )
        )
    return ApproxServe(
        cols=list(cfg.cols),
        col_meta=col_meta,
        q=cfg.q,
        bands=cfg.bands,
        rows_per_band=cfg.rows_per_band,
        band_index=bands,
        idf=idf,
    )


def _layout_rebuild_table(index: LinkageIndex) -> EncodedTable:
    """A zero-row EncodedTable with the index's column structure, enough
    for pack_table to reproduce the lane layout deterministically."""
    table = EncodedTable(n_rows=0, unique_id=np.zeros(0, np.int64))
    for name in index.string_cols:
        meta = index.string_meta[name]
        w = int(meta["width"])
        dt = np.uint8 if meta["kind"] == "ascii" else np.uint32
        table.strings[name] = EncodedStringColumn(
            bytes_=np.zeros((0, w), dt),
            lengths=np.zeros(0, np.int32),
            token_ids=np.zeros(0, np.int32),
            null_mask=np.zeros(0, bool),
            values=np.zeros(0, object),
            width=w,
        )
    from ..data import EncodedNumericColumn

    for name in index.numeric_cols:
        table.numerics[name] = EncodedNumericColumn(
            values_f64=np.zeros(0, np.float64),
            null_mask=np.zeros(0, bool),
            values=np.zeros(0, object),
        )
    return table


def _attach_rebuilt_layout(index: LinkageIndex) -> LinkageIndex:
    import jax.numpy as jnp

    settings = index.settings
    float_dtype = jnp.float64 if index.dtype == "float64" else jnp.float32
    probe, layout = pack_table(
        _layout_rebuild_table(index),
        float_dtype,
        include=comparison_columns_used(settings),
        qgram_specs=qgram_specs_for(settings),
        charset_specs=charset_specs_for(settings),
        jw_specs=(),
    )
    if probe.shape[1] != index.n_lanes:
        raise IndexMismatchError(
            f"rebuilt layout has {probe.shape[1]} lanes but the stored "
            f"packed matrix has {index.n_lanes}; the artifact does not "
            "match this build's packing"
        )
    index.layout = layout
    return index


# bound as a method so load_index can chain it
LinkageIndex._rebuild_layout = _attach_rebuilt_layout
