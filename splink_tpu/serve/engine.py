"""Shape-bucketed jitted query engine over a :class:`LinkageIndex`.

The hot path is ONE fused jitted program per (query-bucket, candidate-
bucket) shape combination, composed from three kernels (each registered in
the analysis layers — ``serve_encode_query`` / ``serve_candidate_gather`` /
``serve_score_topk`` in :mod:`..analysis.trace_audit`, the scoring kernel
also sharded in :mod:`..analysis.shard_audit`):

  encode_query       padding hygiene on the uploaded (donated) query
                     buffers: rows past the batch's real length are zeroed
                     and their rule buckets forced to -1 on device, so the
                     host can reuse pinned upload buffers without a memset
                     and stale bytes can never alias a candidate.
  candidate_gather   device hash-bucket lookup: each query's per-rule
                     bucket id dereferences the index's CSR
                     (starts/sizes/rows_sorted) into a padded (Q, C)
                     candidate matrix; sequential-rule dedup is an
                     elementwise mask over the per-row bucket ids (a pair
                     produced by an earlier rule is invalid here, the
                     device twin of blocking.py's ``AND NOT
                     ifnull(previous_rule, false)``).
  score_topk         two packed-row reads (query side: a static broadcast;
                     reference side: one gather), the comparison kernels
                     via the shared :func:`gammas._spec_gamma` dispatch
                     (exact bodies — bit-identical to the offline
                     program), log-space Fellegi-Sunter scoring, and a
                     partition-safe row-wise top-k per query
                     (``lax.top_k`` all-gathers under a sharded query
                     axis; see :func:`_top_k_rowwise`).

Inside the fused program no scalar ever syncs to the host (JL011-clean):
the driver dispatches the batch and fetches the packed results once.
Shapes come from :mod:`.bucketing`; after the policy's warmup pass the jit
cache holds every (Q, C) combination and steady-state serving performs
zero recompiles (proven by the ``jax.monitoring`` compile counter in
``obs.metrics``).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time

from ..analysis import lockwatch

import numpy as np

from ..utils.logging_utils import warn_degraded

logger = logging.getLogger("splink_tpu")


class IndexSwapError(RuntimeError):
    """A hot-swap candidate index failed to load or validate; the swap was
    rolled back and the previous index is still serving."""


# ---------------------------------------------------------------------------
# Kernel factories (pure jax; traced under jit by the engine and under the
# analysis registries)
# ---------------------------------------------------------------------------


def make_encode_query_fn():
    """(packed_q, qbuckets, valid) -> (packed_q, qbuckets) with padding rows
    zeroed / bucket -1 on device (see module docstring)."""
    import jax.numpy as jnp

    def encode_query(packed_q, qbuckets, valid):
        rows = jnp.arange(packed_q.shape[0], dtype=jnp.int32)
        packed_q = jnp.where(
            (rows < valid)[:, None], packed_q, jnp.uint32(0)
        )
        cols = jnp.arange(qbuckets.shape[1], dtype=jnp.int32)
        qbuckets = jnp.where(
            (cols < valid)[None, :], qbuckets, jnp.int32(-1)
        )
        return packed_q, qbuckets

    return encode_query


def make_candidate_gather_fn(n_rules: int, capacity: int):
    """Device hash-bucket candidate decode for ``n_rules`` rules into a
    padded (Q, ``capacity``) candidate matrix.

    Per query, rule r's bucket contributes its rows at slots
    [offset_r, offset_r + size_r) where offset_r is the running sum of the
    earlier rules' bucket sizes — the same emission order as offline
    blocking. A candidate whose row falls in an EARLIER rule's bucket for
    this query is masked invalid (sequential-rule dedup)."""
    import jax.numpy as jnp

    def candidate_gather(qbuckets, starts, sizes, rows, row_bucket):
        q_n = qbuckets.shape[1]
        slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]  # (1, C)
        cand = jnp.zeros((q_n, capacity), jnp.int32)
        valid = jnp.zeros((q_n, capacity), bool)
        offset = jnp.zeros((q_n, 1), jnp.int32)
        for r in range(n_rules):
            qb = qbuckets[r][:, None]  # (Q, 1)
            has = qb >= 0
            qb0 = jnp.where(has, qb, 0)
            cnt = jnp.where(has, sizes[r][qb0], 0)  # (Q, 1)
            local = slot - offset  # (Q, C)
            in_r = (local >= 0) & (local < cnt)
            limit = jnp.int32(rows[r].shape[0] - 1)
            pos = jnp.clip(starts[r][qb0] + local, 0, jnp.maximum(limit, 0))
            cand_r = rows[r][pos]
            dup = jnp.zeros(in_r.shape, bool)
            for j in range(r):
                qbj = qbuckets[j][:, None]
                dup = dup | ((qbj >= 0) & (row_bucket[j][cand_r] == qbj))
            cand = jnp.where(in_r, cand_r, cand)
            valid = valid | (in_r & ~dup)
            offset = offset + cnt
        n_cand = jnp.sum(valid, axis=1, dtype=jnp.int32)
        return cand, valid, n_cand

    return candidate_gather


def _top_k_rowwise(scores, k: int):
    """(Q, C) -> ((Q, k) values, (Q, k) int32 indices), ``lax.top_k``
    semantics (descending, ties by ascending index) built from k max/mask
    passes. ``lax.top_k`` itself is unpartitionable under GSPMD — it
    all-gathers a query-sharded score matrix onto every device (the
    shard_audit SA-COLL gate caught exactly that) — while per-row max
    reductions along the replicated candidate axis partition trivially.
    k is small (the serving top-k), so k passes beat a gathered sort."""
    import jax.numpy as jnp

    c = scores.shape[1]
    col = jnp.arange(c, dtype=jnp.int32)[None, :]
    masked = scores
    vals, idxs = [], []
    for _ in range(k):
        m = jnp.max(masked, axis=1, keepdims=True)  # (Q, 1)
        # first index attaining the max (top_k's tie order); int32
        # throughout — jnp.argmax would emit int64 under x64
        i = jnp.min(
            jnp.where(masked == m, col, jnp.int32(c)), axis=1
        )
        i = jnp.minimum(i, jnp.int32(c - 1))
        vals.append(m[:, 0])
        idxs.append(i)
        masked = jnp.where(col == i[:, None], jnp.asarray(-2.0, scores.dtype), masked)
    return jnp.stack(vals, axis=1), jnp.stack(idxs, axis=1)


def _finish_topk(p, cand, valid, k: int):
    """Shared tail of both scoring paths: mask invalid slots to an
    impossible -1, run the partition-safe row-wise top-k, and map the
    winning slots back to reference rows. Invalid slots can never displace
    a real candidate; ``top_valid`` reports which of the k slots are
    real."""
    import jax.numpy as jnp

    q_n, capacity = cand.shape
    scores = jnp.where(
        valid.reshape(-1), p, jnp.asarray(-1.0, p.dtype)
    ).reshape(q_n, capacity)
    top_p, top_i = _top_k_rowwise(scores, k)
    top_rows = jnp.take_along_axis(cand, top_i, axis=1)
    top_valid = jnp.take_along_axis(valid, top_i, axis=1)
    # a row with fewer than k valid candidates re-picks slot 0 with the
    # -2 mask sentinel once real entries are exhausted; the score guard
    # keeps such duplicates from reading slot 0's valid flag (real
    # probabilities are >= 0, invalid slots -1, re-picks -2)
    top_valid = top_valid & (top_p > -0.5)
    return top_p, top_rows, top_valid


def make_score_topk_fn(layout: dict, comparison_columns, k: int,
                       tf_spec: tuple = ()):
    """(packed_q, packed_ref, cand, valid, params[, tf_q, tf_tid, tf_log])
    -> (top_p, top_rows, top_valid): gammas via the shared comparison
    dispatch (exact bodies), Fellegi-Sunter match probabilities, masked
    top-k per query. The UNFUSED scoring path — it materialises the full
    (Q*C, n_comparisons) gamma matrix and hands it to
    ``match_probability`` wholesale. Retained as the parity oracle for
    :func:`make_score_fused_fn`, which is the default serving path.

    ``tf_spec`` (term_frequencies.tf_fold_spec entries restricted to the
    index's fold columns) arms the term-frequency u-probability fold:
    per TF column one (Q,) query-token-id vector (``tf_q``), the
    (n_rows,) reference token ids (``tf_tid``) and the log relative-
    frequency table (``tf_log``, term_frequencies.tf_log_table values in
    the compute dtype) — pairs that agree on a token swap the top
    level's average u for the token's own collision probability."""
    import jax
    import jax.numpy as jnp

    from ..gammas import PairContext, _spec_gamma
    from ..models.fellegi_sunter import fold_logit, match_probability
    from ..term_frequencies import tf_fold_delta

    cols = tuple(comparison_columns)
    tf_spec = tuple(tf_spec)

    def score_topk(packed_q, packed_ref, cand, valid, params,
                   tf_q=(), tf_tid=(), tf_log=()):
        q_n, capacity = cand.shape
        # query side: static repeat (broadcast + reshape), NOT an index
        # gather — same row order as packed_q[repeat(arange(Q), C)] but
        # partitions trivially when the query axis is sharded (a computed-
        # index gather of a sharded operand would all-gather it; the
        # shard_audit SA-COLL budget pins this kernel collective-free)
        rows_l = jnp.repeat(packed_q, capacity, axis=0)
        rflat = cand.reshape(-1)
        rows_r = packed_ref[rflat]
        ctx = PairContext(layout, rows_l, rows_r, None)
        G = jnp.stack([_spec_gamma(c, ctx) for c in cols], axis=1)
        if not tf_spec:
            p = match_probability(G, params)
        else:
            # the TF fold: same delta expression, accumulation order and
            # association as the fused kernel and the offline fold —
            # fold_logit IS the fused kernel's left-to-right log-BF
            # accumulation, the anchor that keeps TF-adjusted parity
            # exact at any column count (its docstring has the ulp story)
            from ..models.fellegi_sunter import _safe_log

            z = fold_logit(G, params)
            log_u = _safe_log(params.u)
            tf_sum = jnp.zeros(z.shape, z.dtype)
            for t, (ci, _name, top) in enumerate(tf_spec):
                tql = jnp.repeat(tf_q[t], capacity)
                trf = tf_tid[t][rflat]
                tf_sum = tf_sum + tf_fold_delta(
                    tql, trf, tf_log[t], log_u[ci, top], z.dtype
                )
            p = jax.nn.sigmoid(z + tf_sum)
        return _finish_topk(p, cand, valid, k)

    return score_topk


def make_score_fused_fn(layout: dict, comparison_columns, k: int,
                        tf_spec: tuple = ()):
    """The fused gamma→score→top-k megakernel: same signature and
    BIT-identical results as :func:`make_score_topk_fn`, without ever
    materialising the (Q*C, n_comparisons) gamma matrix.

    The unfused path stacks every comparison's gamma levels into G, then
    ``match_probability`` walks that matrix twice more (``_select_levels``
    over the m and u tables) — three full (Q*C, C)-shaped intermediates
    round-tripping through HBM per batch. Here each comparison's gamma
    levels fold into a running per-pair log-Bayes-factor the moment they
    are computed: one (Q*C,) accumulator crosses the comparisons, and the
    per-comparison gamma vector dies inside the fusion. Per comparison,
    every arithmetic step mirrors the unfused expression tree exactly —
    the same ``_safe_log`` probability tables, the same per-level
    compare-and-mask lookup in the same level order, the same null
    (gamma = -1) masking. ACROSS comparisons the accumulation order is
    the pinned left-to-right fold of
    :func:`~..models.fellegi_sunter.fold_logit` (the NA-ORD audit
    invariant, docs/static_analysis.md#layer-6); ``match_probability``'s
    ``jnp.sum`` reduction tree is not contractually that order past ~2
    comparison columns, so fused-vs-unfused parity is bit-identical
    UNDER the fold order and ulp-budgeted otherwise — the parity tests
    and the ``make warmup-smoke`` oracle comparison gate bit-identity on
    the tiers where the lowered reduction coincides, and the layer-6
    numerics audit pins the fold order itself.

    With ``tf_spec`` the term-frequency u-probability fold rides the same
    fusion: per TF column ONE extra device gather (the reference token ids
    at the candidate rows; the query side is a static repeat like the
    packed rows) plus a log-table lookup, and the per-pair delta
    accumulates into a separate running sum added to the log-Bayes-factor
    before the sigmoid — the identical expression the unfused oracle and
    the offline fold kernel evaluate (term_frequencies module docstring),
    so TF-adjusted parity stays exact."""
    import jax
    import jax.numpy as jnp

    from ..gammas import PairContext, _spec_gamma
    from ..models.fellegi_sunter import _safe_log
    from ..term_frequencies import tf_fold_delta

    cols = tuple(comparison_columns)
    tf_spec = tuple(tf_spec)

    def score_fused(packed_q, packed_ref, cand, valid, params,
                    tf_q=(), tf_tid=(), tf_log=()):
        # identical row materialisation to the unfused path (static
        # broadcast on the query side, one reference gather) — the fusion
        # target is the scoring chain, not the row reads
        capacity = cand.shape[1]
        rows_l = jnp.repeat(packed_q, capacity, axis=0)
        rflat = cand.reshape(-1)
        rows_r = packed_ref[rflat]
        ctx = PairContext(layout, rows_l, rows_r, None)
        log_m = _safe_log(params.m)  # (C, L)
        log_u = _safe_log(params.u)
        n_levels = log_m.shape[1]
        log_bf = jnp.zeros(rows_l.shape[0], log_m.dtype)
        for ci, c in enumerate(cols):
            g = _spec_gamma(c, ctx)  # (Q*C,) int8; dies inside the fusion
            # per-column twin of models.fellegi_sunter._select_levels:
            # compare-and-mask accumulation over the static level axis in
            # the same level order, scalar table entries broadcast
            lp_m = jnp.zeros(g.shape, log_m.dtype)
            lp_u = jnp.zeros(g.shape, log_u.dtype)
            for lv in range(n_levels):
                hit = g == lv
                zero = jnp.zeros((), log_m.dtype)
                lp_m = lp_m + jnp.where(hit, log_m[ci, lv], zero)
                lp_u = lp_u + jnp.where(hit, log_u[ci, lv], zero)
            null = g >= 0
            zero = jnp.zeros((), log_m.dtype)
            log_bf = log_bf + (
                jnp.where(null, lp_m, zero) - jnp.where(null, lp_u, zero)
            )
        lam = params.lam
        prior_logit = _safe_log(lam) - _safe_log(1.0 - lam)
        if not tf_spec:
            p = jax.nn.sigmoid(prior_logit + log_bf)
        else:
            # TF u-probability fold: a separate running delta sum added
            # AFTER the comparison accumulation — `(prior + log_bf) +
            # tf_sum` is the association the offline fold kernel's
            # `z + tf_sum` reproduces (z = prior + log_bf), keeping the
            # adjusted scores bit-identical across every path
            tf_sum = jnp.zeros(log_bf.shape, log_bf.dtype)
            for t, (ci, _name, top) in enumerate(tf_spec):
                tql = jnp.repeat(tf_q[t], capacity)
                trf = tf_tid[t][rflat]
                tf_sum = tf_sum + tf_fold_delta(
                    tql, trf, tf_log[t], log_u[ci, top], log_bf.dtype
                )
            p = jax.nn.sigmoid(prior_logit + log_bf + tf_sum)
        return _finish_topk(p, cand, valid, k)

    return score_fused


def _exec_name(kind: str, q_pad: int, capacity: int) -> str:
    """Canonical sidecar name of one compiled shape combination."""
    return f"{kind}-q{q_pad}-c{capacity}"


@contextlib.contextmanager
def _persistent_cache_disabled():
    """Force a REAL backend compile (no persistent-cache read) — the only
    kind of executable that serializes into a loadable sidecar blob."""
    import jax

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)


def _params_structs(mu_shape, dt):
    """ShapeDtypeStruct pytree of the device-resident FSParams."""
    import jax

    from ..models.fellegi_sunter import FSParams

    S = jax.ShapeDtypeStruct
    return FSParams(lam=S((), dt), m=S(mu_shape, dt), u=S(mu_shape, dt))


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class QueryEngine:
    """Low-latency query interface over a resident :class:`LinkageIndex`.

    One engine owns one index's device residency and one jit cache. Use
    :meth:`warmup` (via a :class:`~.bucketing.BucketPolicy`) before taking
    traffic so steady-state batches never compile.
    """

    def __init__(self, index, *, top_k: int | None = None, policy=None,
                 telemetry=None, brownout_top_k: int | None = None,
                 fused: bool | None = None, aot_dir=None,
                 sketch: bool | None = None, tf_adjust: bool | None = None):
        from .bucketing import BucketPolicy, bucket_for

        self.index = index
        settings = index.settings
        # Fused scoring (make_score_fused_fn) is the default hot path; the
        # unfused program is the retained parity oracle (serve_fused=False
        # or fused=False selects it).
        self.fused = bool(
            settings.get("serve_fused", True) if fused is None else fused
        )
        # Term-frequency u-probability fold (term_frequencies module
        # docstring): default on whenever the index carries fold data
        # (serve_tf_adjust settings gate). ``tf_adjust=`` overrides the
        # gate like ``fused=`` so one index can serve TF-on and TF-off
        # engines side by side (the bench's interleaved tier); it never
        # conjures a fold for an index without the data.
        self._tf_override = tf_adjust  # forwarded across swap_index
        want_tf = bool(
            settings.get("serve_tf_adjust", True)
            if tf_adjust is None
            else tf_adjust
        )
        self.tf_spec = tuple(index.tf_fold_columns()) if want_tf else ()
        if want_tf and not self.tf_spec and index.tf_tables:
            # a TF-flagged model whose artifact predates the fold data
            # (counts only, no per-row token ids): serve exactly as
            # before this build — unadjusted — and say so once
            logger.warning(
                "index carries TF count tables but no per-row token ids "
                "(artifact built before the TF fold); serving UNADJUSTED "
                "scores — re-export the index to enable serve-time TF "
                "adjustment"
            )
        # AOT executable sidecar (serve/aot.py): when set, warmup restores
        # every valid serialized executable instead of compiling, and
        # save_aot() persists the compiled menu for the next process.
        self._aot_dir = os.fspath(aot_dir) if aot_dir else None
        self._aot_store = None  # memoised validated AotStore (or False)
        self.top_k = int(
            top_k
            if top_k is not None
            else settings.get("serve_top_k", 5) or 5
        )
        self.policy = policy or BucketPolicy.from_settings(settings)
        if self.top_k > self.policy.candidate_buckets[-1]:
            raise ValueError(
                f"serve_top_k={self.top_k} exceeds the largest candidate "
                f"bucket ({self.policy.candidate_buckets[-1]}); widen "
                "serve_candidate_buckets — top-k cannot exceed the padded "
                "candidate capacity"
            )
        # Brown-out tier: a second, budgeted program — smaller top-k AND
        # the smallest candidate bucket that covers it, so a degraded
        # dispatch runs the CHEAPEST compiled shape combination instead of
        # shedding outright (admission.py). 0 disables the tier.
        self.brownout_top_k = int(
            brownout_top_k
            if brownout_top_k is not None
            else settings.get("serve_brownout_top_k", 0) or 0
        )
        if self.brownout_top_k < 0 or self.brownout_top_k > self.top_k:
            raise ValueError(
                f"serve_brownout_top_k={self.brownout_top_k} must be in "
                f"[0, serve_top_k={self.top_k}] — the brown-out tier serves "
                "a REDUCED budget"
            )
        self.brownout_capacity = (
            bucket_for(self.brownout_top_k, self.policy.candidate_buckets)
            if self.brownout_top_k
            else None
        )
        self._obs = telemetry
        # Serve-time drift sketch (obs/drift.py): device-side gamma/score
        # histogram accumulation folded onto the fused-kernel outputs of
        # every full-service batch. Requires BOTH the quality_profile
        # setting and a profiled index — a legacy (profile-less) artifact
        # serves unchanged and drift reporting states why it is dark.
        # ``sketch=`` overrides the settings gate (like ``fused=``) so one
        # profiled index can serve sketch-on and sketch-off engines
        # side by side (the bench's interleaved overhead tier); it never
        # conjures a sketch for a profile-less index.
        self.sketch = None
        self._sketch_override = sketch  # forwarded across swap_index
        want_sketch = (
            bool(settings.get("quality_profile"))
            if sketch is None
            else bool(sketch)
        )
        if want_sketch and index.profile is not None:
            from ..obs.drift import ServeSketch

            self.sketch = ServeSketch(index, index.profile)
        # kind ("full" | "brownout") -> jitted fused program (stable
        # identity; only used through .lower() for AOT-style compilation)
        self._jits: dict = {}
        # (kind, q_pad, capacity) -> jax.stages.Compiled: THE dispatch
        # table. Each entry is an ahead-of-time compiled (or AOT-sidecar
        # restored) executable for one exact shape combination — dispatch
        # never goes through jit's tracing machinery, so a fresh process
        # that restores the menu performs zero backend compiles.
        self._execs: dict = {}
        # key -> "compiled" | "aot": where each executable came from (an
        # AOT-restored menu executes ONE dispatch probe during warmup
        # instead of one per shape — see _warm_one)
        self._exec_source: dict = {}
        self._aot_exec_probed = False
        self._donate = None
        self._warmed: set[tuple[int, int]] = set()
        self._warmed_brownout: set[tuple[int, int]] = set()
        # serializes batch dispatch against index hot-swap: a dispatch in
        # flight finishes on the index it started on (graceful drain), and
        # the swap flip is atomic with respect to the next dispatch
        self._swap_lock = lockwatch.new_rlock("QueryEngine._swap_lock")
        # serializes swap_index against ITSELF (the dispatch lock must stay
        # free during a swap's long validation, so it cannot do this job):
        # without it two concurrent swaps both "commit", one silently lost
        self._swap_mutex = lockwatch.new_lock("QueryEngine._swap_mutex")
        self._probes = None  # (query df, recorded answer arrays)
        self._generation = 0
        # float64 serving needs process-wide x64, same semantics as the
        # linker's float64 setting (jax silently downcasts otherwise)
        if index.dtype == "float64":
            import jax

            if jax.default_backend() == "tpu":  # pragma: no cover - no TPU CI
                raise ValueError(
                    "index was built for float64 but the TPU backend has no "
                    "float64 support; rebuild with float64 off"
                )
            if not jax.config.jax_enable_x64:
                jax.config.update("jax_enable_x64", True)
                logger.info(
                    "float64 serving index: enabled jax x64 mode "
                    "(process-wide)"
                )

    # -- kernel ---------------------------------------------------------

    # threadlint: holds=_swap_lock (query/warmup/save_aot enter locked)
    def _build_kernel(self, k: int):
        """One jitted fused program for one top-k. ``capacity`` is a
        static argument; the engine compiles each (capacity, shapes)
        combination explicitly through ``.lower().compile()`` (the AOT
        path — a compiled executable can be serialized into the sidecar
        and restored by a fresh process without the backend compiler)."""
        import functools

        import jax

        index = self.index
        # the gather menu covers the exact rules AND the approx LSH bands
        # (the fallback bucket path rides the same compiled programs, so a
        # fallback batch is recompile-free and brown-out compatible)
        n_rules = len(index.gather_units)
        encode = make_encode_query_fn()
        layout = index.layout
        cols = tuple(index.settings["comparison_columns"])
        make_score = (
            make_score_fused_fn if self.fused else make_score_topk_fn
        )
        score = make_score(layout, cols, k, tf_spec=self.tf_spec)

        def fused(
            capacity, packed_q, qbuckets, valid,
            starts, sizes, rows, row_bucket, packed_ref, params,
            tf_q=(), tf_tid=(), tf_log=(),
        ):
            gather = make_candidate_gather_fn(n_rules, capacity)
            packed_q, qbuckets = encode(packed_q, qbuckets, valid)
            cand, cvalid, n_cand = gather(
                qbuckets, starts, sizes, rows, row_bucket
            )
            top_p, top_rows, top_valid = score(
                packed_q, packed_ref, cand, cvalid, params,
                tf_q, tf_tid, tf_log,
            )
            return top_p, top_rows, top_valid, n_cand

        # donate the per-request buffers (query rows + buckets); the
        # CPU backend ignores donation with a warning, so gate it — and
        # the drift sketch re-reads the query upload AFTER the scoring
        # kernel consumed it, so sketching keeps the buffers live
        donate = ()
        if jax.default_backend() not in ("cpu",) and self.sketch is None:
            donate = (1, 2)
        self._donate = donate
        return functools.partial(
            jax.jit, static_argnums=(0,), donate_argnums=donate
        )(fused)

    # threadlint: holds=_swap_lock (query/warmup/save_aot enter locked)
    def _jit_kernel(self, kind: str):
        """The jitted program for one tier (stable identity; lowered per
        shape by :meth:`_ensure_exec`, never called directly)."""
        if kind == "brownout" and not self.brownout_top_k:
            raise RuntimeError(
                "brown-out tier is disabled (serve_brownout_top_k=0)"
            )
        jfn = self._jits.get(kind)
        if jfn is None:
            k = self.top_k if kind == "full" else self.brownout_top_k
            jfn = self._jits[kind] = self._build_kernel(k)
        return jfn

    # threadlint: holds=_swap_lock (query/warmup/save_aot enter locked)
    def _arg_structs(self, q_pad: int):
        """ShapeDtypeStruct pytree of one dispatch's dynamic arguments at
        query bucket ``q_pad`` — what ``.lower()`` needs instead of real
        (allocated) example batches."""
        import jax

        index = self.index
        S = jax.ShapeDtypeStruct
        dt = index.float_dtype
        i32, u32 = np.int32, np.uint32
        units = index.gather_units
        structs = (
            S((q_pad, index.n_lanes), u32),
            S((len(units), q_pad), i32),
            S((), i32),
            tuple(S(r.starts.shape, i32) for r in units),
            tuple(S(r.sizes.shape, i32) for r in units),
            tuple(S(r.rows_sorted.shape, i32) for r in units),
            tuple(S(r.row_bucket.shape, i32) for r in units),
            S(index.packed.shape, u32),
            _params_structs(index.m.shape, dt),
        )
        if not self.tf_spec:
            # legacy / TF-off: the exact argument tree of today's
            # executables (byte-identical serving, unchanged sidecars
            # modulo the binding's tf flag)
            return structs
        tf_dev = index.tf_device_state()
        return structs + (
            tuple(S((q_pad,), i32) for _ in self.tf_spec),
            tuple(S(a.shape, i32) for a in tf_dev["tid"]),
            tuple(S(a.shape, dt) for a in tf_dev["log"]),
        )

    # threadlint: holds=_swap_lock (query/warmup/save_aot enter locked)
    def _ensure_exec(self, kind: str, q_pad: int, capacity: int):
        """The compiled executable for one exact shape combination:
        dispatch-table hit, else AOT-sidecar restore (zero backend
        compiles), else a fresh ``.lower().compile()``."""
        key = (kind, q_pad, capacity)
        ex = self._execs.get(key)
        if ex is not None:
            return ex
        store = self._aot_ready_store()
        if store is not None:
            ex = store.restore(_exec_name(kind, q_pad, capacity))
            if ex is not None:
                from ..obs.metrics import note_aot_restore

                note_aot_restore()
                self._execs[key] = ex
                self._exec_source[key] = "aot"
                return ex
        from ..obs.metrics import compile_stats, install_compile_monitor

        install_compile_monitor()
        h0 = compile_stats()["cache_hits"]
        lowered = self._jit_kernel(kind).lower(
            capacity, *self._arg_structs(q_pad)
        )
        ex = self._execs[key] = lowered.compile()
        # an executable the PERSISTENT cache served was itself
        # deserialized — like an AOT restore, re-serializing it yields a
        # blob that cannot be loaded ("Symbols not found"); save_aot must
        # know to re-compile it cache-bypassed for the sidecar
        self._exec_source[key] = (
            "cache" if compile_stats()["cache_hits"] > h0 else "compiled"
        )
        return ex

    # -- AOT executable sidecar -----------------------------------------

    # threadlint: holds=_swap_lock (query/warmup/save_aot enter locked)
    def _aot_binding(self) -> dict:
        """The strict-invalidation identity every sidecar executable is
        bound to (serve/aot.py adds the environment half: jax/jaxlib
        version, backend, target-feature fingerprint)."""
        index = self.index
        return {
            "index_state_hash": index.state_hash,
            "index_fingerprint": index.content_fingerprint(),
            "dtype": index.dtype,
            "n_rules": len(index.rules),
            "n_approx_bands": (
                0 if index.approx is None else index.approx.bands
            ),
            "top_k": self.top_k,
            "brownout_top_k": self.brownout_top_k,
            "query_buckets": list(self.policy.query_buckets),
            "candidate_buckets": list(self.policy.candidate_buckets),
            "fused": self.fused,
            # sketching flips buffer donation off, which changes the
            # compiled executable — a sidecar saved either way must not
            # serve the other configuration
            "sketch": self.sketch is not None,
            # the TF fold changes the compiled scoring tail (extra gather
            # + delta accumulation), so a sidecar saved either way must
            # not serve the other configuration
            "tf": bool(self.tf_spec),
        }

    # threadlint: holds=_swap_lock (query/warmup/save_aot enter locked)
    def _aot_ready_store(self):
        """The validated sidecar store, memoised; None when no sidecar is
        configured, present, or valid (every invalidation reason emits one
        ``serve_aot`` degradation event and serving falls back to fresh
        compiles — never a wrong or foreign executable)."""
        if self._aot_store is None:
            if self._aot_dir is None:
                self._aot_store = False
            else:
                from .aot import AotStore

                store = AotStore(self._aot_dir)
                self._aot_store = (
                    store if store.validate(self._aot_binding()) else False
                )
        return self._aot_store or None

    def save_aot(self, directory=None) -> str:
        """Serialize every compiled executable currently in the dispatch
        table into the AOT sidecar at ``directory`` (default: the engine's
        ``aot_dir``), bound to the index fingerprint, settings hash, shape
        menu and environment. Call after :meth:`warmup` so the sidecar
        holds the full bucket menu. Returns the sidecar meta path."""
        # the whole save runs under the swap lock (reentrant): a swap
        # committing mid-iteration would mix two menus into one sidecar
        with self._swap_lock:
            return self._save_aot_locked(directory or self._aot_dir)

    # threadlint: holds=_swap_lock
    def _save_aot_locked(self, directory) -> str:
        from .aot import AotStore

        if not directory:
            raise ValueError(
                "no sidecar directory: pass save_aot(directory) or "
                "construct the engine with aot_dir="
            )
        if not self._execs:
            raise RuntimeError(
                "nothing to save: run warmup() first so the dispatch table "
                "holds the compiled bucket menu"
            )
        executables = {}
        recompiled = 0
        for (kind, q_pad, capacity), ex in self._execs.items():
            if self._exec_source.get((kind, q_pad, capacity)) != "compiled":
                # only an executable ACTUALLY backend-compiled in this
                # process serializes into a loadable blob; one restored
                # from the sidecar OR served by the persistent compile
                # cache was itself deserialized, and re-serializing it
                # succeeds silently but fails deserialize_and_load with
                # "Symbols not found" — writing it would overwrite a
                # valid sidecar with a poisoned one. Re-compile a fresh
                # twin with the persistent cache bypassed; the existing
                # executable keeps serving.
                with _persistent_cache_disabled():
                    ex = self._jit_kernel(kind).lower(
                        capacity, *self._arg_structs(q_pad)
                    ).compile()
                recompiled += 1
            executables[_exec_name(kind, q_pad, capacity)] = ex
        path = AotStore.write(directory, self._aot_binding(), executables)
        logger.info(
            "AOT executable sidecar saved: %s (%d executables, %d "
            "re-lowered from restored entries)",
            directory, len(executables), recompiled,
        )
        return path

    # -- query paths ----------------------------------------------------

    def encode(self, df):
        """Host-side query encode (see LinkageIndex.encode_queries)."""
        with self._swap_lock:  # reentrant: the batch path enters locked
            return self.index.encode_queries(df)

    def query_arrays(self, df, *, degraded: bool = False, profile=None,
                     approx_out: list | None = None):
        """Score a query DataFrame; returns
        ``(top_p, top_rows, top_valid, n_candidates)`` numpy arrays of
        shape (n, k) / (n,). ``top_rows`` are reference ROW indices; map
        through ``index.unique_id`` for ids (``query`` does).

        ``approx_out``, when a list, receives one (n,) bool array marking
        the queries served through the approx LSH FALLBACK bucket path
        (their exact keys hit no bucket; candidates come from minhash band
        buckets and results should surface as ``approx=True``). The scores
        themselves are bit-identical to offline scoring of the same
        (query, candidate) pairs — the fallback changes WHICH candidates
        are gathered, never how a pair is scored.

        ``degraded=True`` runs the brown-out program: top-k
        ``brownout_top_k`` over candidates truncated to the cheapest
        bucket (``brownout_capacity``) — the budgeted answer the service
        serves under pressure instead of shedding.

        ``profile`` (an :class:`~..obs.reqtrace.PhaseProfile`) accumulates
        the batch's compile/execute/transfer split for request tracing.
        Profiling splits the EXISTING single result rendezvous into a
        compute wait plus the D2H fetch — it adds no new host sync and
        leaves the compiled programs untouched."""
        with self._swap_lock:
            k = self.brownout_top_k if degraded else self.top_k
            if degraded and not k:
                raise RuntimeError(
                    "brown-out tier is disabled (serve_brownout_top_k=0)"
                )
            batch = self.encode(df)
            if self.sketch is not None:
                # host-side sketch counters from the already-encoded
                # batch (OOV / null-key / approx-fallback rates) — no
                # device work; brown-out batches only count as degraded
                # (their reduced top-k would skew the histograms)
                if degraded:
                    self.sketch.note_degraded(batch.n)
                else:
                    self.sketch.note_batch(df, batch, len(self.index.rules))
            if approx_out is not None:
                approx_out.append(
                    batch.approx_used
                    if batch.approx_used is not None
                    else np.zeros(batch.n, bool)
                )
            out_p = np.full((batch.n, k), -1.0, self.index.float_dtype)
            out_rows = np.zeros((batch.n, k), np.int32)
            out_valid = np.zeros((batch.n, k), bool)
            out_ncand = np.zeros(batch.n, np.int64)
            pos = 0
            for q_pad, start, stop in self.policy.iter_query_chunks(batch.n):
                p, r, v, nc = self._run_chunk(
                    batch, start, stop, q_pad, degraded=degraded,
                    profile=profile,
                )
                out_p[start:stop] = p[: stop - start]
                out_rows[start:stop] = r[: stop - start]
                out_valid[start:stop] = v[: stop - start]
                out_ncand[start:stop] = nc[: stop - start]
                pos = stop
            assert pos == batch.n
            return out_p, out_rows, out_valid, out_ncand

    # threadlint: holds=_swap_lock (only query_arrays calls this, locked)
    def _run_chunk(self, batch, start: int, stop: int, q_pad: int, *,
                   degraded: bool = False, profile=None):
        """One bucketed device dispatch: pad the chunk to ``q_pad`` queries
        and its candidate axis to a policy bucket, run the fused kernel,
        fetch once."""
        import jax.numpy as jnp

        index = self.index
        n = stop - start
        qb = batch.qbuckets[:, start:stop]
        if degraded:
            # brown-out: the candidate budget IS the truncation — always
            # the cheapest compiled shape, no per-batch warning spam (the
            # service tags every result degraded and emits the episode
            # events)
            capacity = self.brownout_capacity
            kind = "brownout"
        else:
            counts = index.candidate_counts(qb)
            need = max(int(counts.max(initial=0)), self.top_k, 1)
            capacity = self.policy.candidate_bucket(need)
            if capacity is None:
                capacity = self.policy.candidate_buckets[-1]
                warn_degraded(
                    "serve_candidates",
                    "truncated",
                    f"largest candidate block needs {need} slots but the "
                    f"largest candidate bucket is {capacity}; blocks are "
                    "truncated to the bucket (top-k over the truncated set)",
                    queries=n,
                )
            kind = "full"
        if profile is not None:
            from ..obs.metrics import compile_totals

            # snapshot BEFORE the dispatch-table lookup: a cold shape
            # compiles inside _ensure_exec, not inside the call
            c0 = compile_totals()[1]
        kernel = self._ensure_exec(kind, q_pad, capacity)
        # pinned upload buffers are reused without a host memset: the
        # encode_query kernel zeroes padding rows on device
        packed_pad = np.empty((q_pad, index.n_lanes), np.uint32)
        packed_pad[:n] = batch.packed[start:stop]
        qb_pad = np.empty((len(index.gather_units), q_pad), np.int32)
        qb_pad[:, :n] = qb
        dev = index.device_state()
        packed_dev = jnp.asarray(packed_pad)
        tf_args = ()
        if self.tf_spec:
            # padding rows carry token id -1 (never agrees), so the fold
            # is inert on them like the encode kernel's zeroed rows
            tf_q = []
            for t in range(len(self.tf_spec)):
                buf = np.full(q_pad, -1, np.int32)
                buf[:n] = batch.tf_tids[t, start:stop]
                tf_q.append(jnp.asarray(buf))
            tf_dev = index.tf_device_state()
            tf_args = (tuple(tf_q), tf_dev["tid"], tf_dev["log"])
        top_p, top_rows, top_valid, n_cand = kernel(
            packed_dev,
            jnp.asarray(qb_pad),
            np.int32(n),
            dev["starts"],
            dev["sizes"],
            dev["rows"],
            dev["row_bucket"],
            dev["packed"],
            dev["params"],
            *tf_args,
        )
        if self.sketch is not None and not degraded:
            # fold the batch into the device drift accumulator: an async
            # dispatch over the already-device-resident outputs — nothing
            # is fetched, the hot path gains no host sync (padding rows
            # carry top_valid=False and drop inside the scatter)
            self.sketch.update(
                packed_dev, dev["packed"], top_rows, top_valid, top_p
            )
        (self._warmed_brownout if degraded else self._warmed).add(
            (q_pad, capacity)
        )
        if profile is None:
            # the single host fetch for this batch
            return (
                np.asarray(top_p),
                np.asarray(top_rows),
                np.asarray(top_valid),
                np.asarray(n_cand),
            )
        # traced batch: split the SAME single rendezvous into its parts —
        # compile (monitor delta; zero in steady state), device compute
        # (block_until_ready on the already-dispatched outputs) and the
        # D2H fetch. No additional sync point: the untraced path blocks at
        # exactly this line inside np.asarray instead.
        import jax

        profile.compile_s += max(compile_totals()[1] - c0, 0.0)
        t0 = time.perf_counter()
        jax.block_until_ready((top_p, top_rows, top_valid, n_cand))
        t1 = time.perf_counter()
        profile.execute_s += t1 - t0
        out = (
            np.asarray(top_p),
            np.asarray(top_rows),
            np.asarray(top_valid),
            np.asarray(n_cand),
        )
        profile.transfer_s += time.perf_counter() - t1
        return out

    def query(self, df):
        """Score a query DataFrame; returns a tidy DataFrame with one row
        per (query, match): query id, matched reference id, rank, match
        probability, the query's candidate count and — when the index
        carries the approx tier — an ``approx`` flag marking matches found
        through the LSH fallback bucket path (the query's exact keys hit
        no bucket)."""
        import pandas as pd

        approx_out: list = []
        # one lock span across scoring AND the uid mapping: a hot-swap
        # committing between them would map row indices scored on the old
        # index through the new index's unique_id column
        with self._swap_lock:
            top_p, top_rows, top_valid, n_cand = self.query_arrays(
                df, approx_out=approx_out
            )
            approx_used = approx_out[0]
            ref_uid = self.index.unique_id
            q_idx, rank = np.nonzero(top_valid)
            uid_col = self.index.settings["unique_id_column_name"]
            query_uid = self._query_uids(df)
            out = {
                f"{uid_col}_q": query_uid[q_idx],
                f"{uid_col}_m": ref_uid[top_rows[q_idx, rank]],
                "rank": rank.astype(np.int64),
                "match_probability": top_p[q_idx, rank],
                "n_candidates": n_cand[q_idx],
            }
            if self.index.approx is not None:
                out["approx"] = approx_used[q_idx]
        return pd.DataFrame(out)

    # threadlint: holds=_swap_lock (only query() calls this, locked)
    def _query_uids(self, df) -> np.ndarray:
        uid_col = self.index.settings["unique_id_column_name"]
        if uid_col in df.columns:
            return df[uid_col].to_numpy()
        return np.arange(len(df))

    # -- warmup / compile accounting ------------------------------------

    def warmup(self) -> dict:
        """Ready every (query-bucket, candidate-bucket) combination so
        steady-state serving never compiles — the brown-out tier's
        (query-bucket, ``brownout_capacity``) shapes included when enabled,
        so a brown-out EPISODE is also recompile-free. Each combination is
        AOT-restored from the sidecar when one is configured and valid
        (zero backend compiles), else compiled fresh. Freshly compiled
        programs each execute one dummy batch; a restored menu executes
        only the FIRST and the LARGEST full-service shape
        (deserialization already validated the artifacts, the first probe
        proves dispatch on this machine, the largest proves the biggest
        buffer allocation — per-shape dummy batches made restored warmup
        scale with menu compute for nothing).

        Returns the jax.monitoring-measured accounting split:
        ``combinations``, ``compiles`` (REAL backend compiles),
        ``cache_hits`` (persistent-compilation-cache restores) and
        ``aot_restored`` (sidecar-deserialized executables) — a cold
        replica shows combinations == compiles, a persistent-cache-warm
        one combinations == cache_hits, an AOT-restored one
        combinations == aot_restored with compiles == 0."""
        from ..obs.metrics import compile_stats, install_compile_monitor

        install_compile_monitor()
        s0 = compile_stats()
        combos = self.policy.warmup_combinations()
        for q_pad, capacity in combos:
            self._warm_one(
                q_pad, capacity,
                force_execute=(q_pad, capacity) == combos[-1],
            )
        brownout_combos = []
        if self.brownout_top_k:
            brownout_combos = [
                (qb, self.brownout_capacity)
                for qb in self.policy.query_buckets
            ]
            for q_pad, capacity in brownout_combos:
                self._warm_one(q_pad, capacity, degraded=True)
        # pre-compile the drift-sketch program for every query bucket
        # (one dummy all-invalid dispatch per shape), so sketching
        # adds zero steady-state recompiles. These compiles are ON
        # TOP of the scoring combinations — sketch-on replicas show
        # compiles > combinations here, never in steady state.
        with self._swap_lock:
            if self.sketch is not None:
                for q_pad in self.policy.query_buckets:
                    self.sketch.warm(q_pad, self.top_k)
        s1 = compile_stats()
        stats = {
            "combinations": len(combos) + len(brownout_combos),
            "compiles": s1["compiles"] - s0["compiles"],
            "cache_hits": s1["cache_hits"] - s0["cache_hits"],
            "aot_restored": s1["aot_restores"] - s0["aot_restores"],
        }
        if self._obs is not None:
            self._obs.count("serve_warmup_compiles", stats["compiles"])
            self._obs.count("serve_warmup_cache_hits", stats["cache_hits"])
            self._obs.count(
                "serve_warmup_aot_restores", stats["aot_restored"]
            )
        return stats

    def _warm_one(self, q_pad: int, capacity: int,
                  degraded: bool = False, force_execute: bool = False) -> None:
        import jax.numpy as jnp

        with self._swap_lock:
            index = self.index
            dev = index.device_state()
            kind = "brownout" if degraded else "full"
            kernel = self._ensure_exec(kind, q_pad, capacity)
            if not force_execute and (
                self._exec_source.get((kind, q_pad, capacity)) == "aot"
            ):
                # a restored executable was already validated by its
                # deserialization; executing a dummy batch per shape is
                # what made CPU-tier warmup scale with the menu (the big
                # combos score millions of padded pairs for nothing). ONE
                # dispatch probe per restored menu proves execution on
                # this machine; the rest skip straight to ready.
                if self._aot_exec_probed:
                    (self._warmed_brownout if degraded else self._warmed).add(
                        (q_pad, capacity)
                    )
                    return
                self._aot_exec_probed = True
            packed = np.zeros((q_pad, index.n_lanes), np.uint32)
            qb = np.full((len(index.gather_units), q_pad), -1, np.int32)
            tf_args = ()
            if self.tf_spec:
                tf_dev = index.tf_device_state()
                tf_args = (
                    tuple(
                        jnp.asarray(np.full(q_pad, -1, np.int32))
                        for _ in self.tf_spec
                    ),
                    tf_dev["tid"],
                    tf_dev["log"],
                )
            out = kernel(
                jnp.asarray(packed),
                jnp.asarray(qb),
                np.int32(0),
                dev["starts"],
                dev["sizes"],
                dev["rows"],
                dev["row_bucket"],
                dev["packed"],
                dev["params"],
                *tf_args,
            )
            np.asarray(out[0])  # execute fully
            (self._warmed_brownout if degraded else self._warmed).add(
                (q_pad, capacity)
            )

    @property
    def warmed_shapes(self) -> set:
        """The (query_bucket, candidate_bucket) combinations compiled so
        far (full-service program; the brown-out program's shapes are in
        ``warmed_brownout_shapes``)."""
        with self._swap_lock:
            return set(self._warmed)

    @property
    def warmed_brownout_shapes(self) -> set:
        with self._swap_lock:
            return set(self._warmed_brownout)

    def probe(self) -> None:
        """Execute the smallest warmed shape end to end (kernel + device +
        result fetch, no compile after warmup). The watchdog's circuit-
        breaker recovery probe: success proves the engine can dispatch."""
        self._warm_one(
            self.policy.query_buckets[0], self.policy.candidate_buckets[0],
            force_execute=True,
        )

    @property
    def generation(self) -> int:
        """How many hot-swaps this engine has committed."""
        with self._swap_lock:
            return self._generation

    @property
    def tf_active(self) -> bool:
        """Whether this engine folds the term-frequency u-probability
        adjustment into its served scores (settings gate on AND the index
        carries the fold data)."""
        with self._swap_lock:
            return bool(self.tf_spec)

    # -- drift sketch drain ---------------------------------------------

    def drift_drain_due(self, cadence_s: float) -> bool:
        """Whether the drift accumulator is due a drain (no lock, no
        device work — a cheap poll for the service worker/watchdog).

        Deliberately lock-free: the swap lock is held for entire batch
        dispatches, and the watchdog must never stall its tick budget on
        a serving batch. ``sketch`` only flips on a hot-swap; racing one
        at worst answers the poll for the outgoing sketch (off-by-one
        tick, self-correcting next poll)."""
        # threadlint: disable=TL001 (atomic reference read, see docstring)
        return self.sketch is not None and self.sketch.drain_due(cadence_s)

    def drain_drift(self):
        """Fetch + reset the drift accumulator into one window sketch
        (:class:`~..obs.drift.WindowSketch`), or None when sketching is
        off. The sketch's ONLY device fetch — called between batches by
        the service worker or from the watchdog when idle, never inside a
        dispatch."""
        with self._swap_lock:
            if self.sketch is None:
                return None
            return self.sketch.drain()

    # -- parity probes & index hot-swap ---------------------------------

    def capture_probes(self, df) -> int:
        """Record ``df`` and this engine's CURRENT answers for it as the
        parity probe set: :meth:`swap_index` replays these queries on a
        candidate index and requires bit-identical answers before
        committing. Returns the number of probes stored."""
        df = df.reset_index(drop=True).copy()
        # one lock span across compute AND store: a swap committing in
        # between would attach answers recorded on the OLD index to the
        # NEW one, failing the next (valid) swap's parity replay
        with self._swap_lock:
            answers = self.query_arrays(df)
            self._probes = (df, answers)
        return len(df)

    @property
    def probe_count(self) -> int:
        """Stat-only accessor, deliberately lock-free: ``_probes`` is an
        atomically-assigned tuple reference and the swap lock can be held
        for a whole batch dispatch — a health poll must not stall on it.
        A read racing capture/swap returns the count of either the old or
        the new probe set, both truthful answers."""
        probes = self._probes  # threadlint: disable=TL001 (see docstring)
        return 0 if probes is None else len(probes[0])

    def swap_index(self, source, *, refresh_probes: bool = False) -> dict:
        """Hot-swap to a new :class:`LinkageIndex` with validation and
        rollback (ISSUE tentpole 4):

        1. load the candidate (a directory path or an in-memory index) —
           ``load_index`` verifies format version, settings-hash binding
           and the array fingerprint;
        2. build + pre-warm a pending engine over it (every bucket
           combination compiles BEFORE the flip, so post-swap steady
           state stays recompile-free);
        3. replay the stored parity probes against the recorded answers —
           any drift (``refresh_probes=False``) fails the swap;
        4. atomically flip index/kernels/warm-state under the swap lock —
           an in-flight dispatch finishes on the old index first
           (graceful drain), the next one runs on the new.

        ANY failure before the flip emits a ``serve_index_swap``
        degradation event and raises :class:`IndexSwapError` with the old
        index untouched and still serving. ``refresh_probes=True`` skips
        the parity comparison and re-records the probe answers on the new
        index (an intentional content change). Concurrent ``swap_index``
        calls serialize on the swap mutex — without it both would
        "commit" and one new index would be silently lost."""
        with self._swap_mutex:
            return self._swap_index_serialized(source, refresh_probes)

    def _swap_index_serialized(self, source, refresh_probes: bool) -> dict:
        from ..obs.events import publish
        from ..resilience.faults import active_plan
        from .index import LinkageIndex, load_index

        t0 = time.perf_counter()
        with self._swap_lock:
            plan = active_plan(self.index.settings)
            generation = self._generation + 1
        try:
            plan.fire("swap_load", generation=generation)
            if isinstance(source, LinkageIndex):
                new_index = source
            else:
                new_index = load_index(source)
        except Exception as e:  # noqa: BLE001 - every load failure rolls back
            warn_degraded(
                "serve_index_swap",
                "rolled_back",
                f"candidate index failed to load: {e}",
                generation=generation,
            )
            raise IndexSwapError(
                f"index swap rolled back (old index still serving): "
                f"candidate failed to load: {e}"
            ) from e
        probes_checked = 0
        new_probes = None
        with self._swap_lock:
            probes = self._probes  # snapshot: validation runs on THIS set
        try:
            # a candidate loaded from disk may ship its own AOT sidecar
            # (<dir>/aot) — the pending engine's pre-warm restores from it
            # when its binding matches, cutting the swap's compile window;
            # a stale/foreign sidecar degrades to fresh compiles as usual
            pending_aot = None
            if not isinstance(source, LinkageIndex):
                cand_aot = os.path.join(os.fspath(source), "aot")
                if os.path.isdir(cand_aot):
                    pending_aot = cand_aot
            pending = QueryEngine(
                new_index,
                top_k=self.top_k,
                policy=self.policy,
                telemetry=self._obs,
                brownout_top_k=self.brownout_top_k,
                fused=self.fused,
                sketch=self._sketch_override,
                tf_adjust=self._tf_override,
                aot_dir=pending_aot,
            )
            warm = pending.warmup()
            plan.fire("swap_validate", generation=generation)
            if probes is not None:
                probe_df, expected = probes
                got = pending.query_arrays(probe_df)
                if refresh_probes:
                    new_probes = (probe_df, got)
                else:
                    _check_probe_parity(expected, got)
                    probes_checked = len(probe_df)
                    new_probes = (probe_df, got)
        except Exception as e:  # noqa: BLE001 - every validation failure rolls back
            warn_degraded(
                "serve_index_swap",
                "rolled_back",
                f"candidate index failed validation: {e}",
                generation=generation,
            )
            raise IndexSwapError(
                f"index swap rolled back (old index still serving): {e}"
            ) from e
        with self._swap_lock:
            self.index = pending.index
            self.tf_spec = pending.tf_spec
            self._jits = pending._jits
            self._execs = pending._execs
            self._exec_source = pending._exec_source
            self._aot_exec_probed = pending._aot_exec_probed
            self._donate = pending._donate
            self._aot_dir = pending._aot_dir
            self._aot_store = pending._aot_store
            self._warmed = pending._warmed
            self._warmed_brownout = pending._warmed_brownout
            # the drift sketch binds to the index's profile and device
            # residency; the pending engine built (and warmed) its own
            self.sketch = pending.sketch
            if new_probes is not None:
                self._probes = new_probes
            elif self._probes is not probes:
                # a concurrent capture landed DURING validation: its
                # answers describe the outgoing index and must not gate
                # the next swap — drop them so the service re-seeds its
                # probe set from post-swap traffic
                self._probes = None
            self._generation = generation
            n_rows = self.index.n_rows
        stats = {
            "generation": generation,
            "n_rows": n_rows,
            "warmup_combinations": warm["combinations"],
            "warmup_compiles": warm["compiles"],
            "probes_checked": probes_checked,
            "elapsed_s": round(time.perf_counter() - t0, 3),
        }
        publish("index_swap", **stats)
        logger.info(
            "serving index hot-swapped: generation %d, %d rows, "
            "%d probe(s) parity-checked, %.3fs",
            generation, n_rows, probes_checked, stats["elapsed_s"],
        )
        return stats


def _check_probe_parity(expected, got) -> None:
    """Raise with a precise diff summary unless the candidate engine's
    probe answers are BIT-identical to the recorded ones (same dtypes,
    same shapes, same values — the serve<->offline parity contract carried
    across the swap)."""
    names = ("top_p", "top_rows", "top_valid", "n_candidates")
    for name, e, g in zip(names, expected, got):
        if e.dtype != g.dtype or e.shape != g.shape:
            raise ValueError(
                f"probe parity failed on {name}: recorded "
                f"{e.shape}/{e.dtype} vs candidate {g.shape}/{g.dtype}"
            )
        if not np.array_equal(e, g):
            bad = int(np.sum(e != g))
            raise ValueError(
                f"probe parity failed on {name}: {bad}/{e.size} entries "
                "differ from the recorded answers (bit-identity required; "
                "pass refresh_probes=True for an intentional content change)"
            )
