"""The serving wire tier: a length-prefixed JSON protocol + WireServer.

`ReplicaRouter` is deliberately duck-typed over ``submit / health_state /
latency_summary`` so that "a replica" never had to mean "a thread in this
process". This module is the other half of that bet: a stdlib-only TCP
protocol that puts a :class:`~.service.LinkageService` behind a socket,
and (in :mod:`.remote`) a client that wraps the socket back into the
replica shape — so the router routes, hedges and fails over across HOSTS
with zero router changes (docs/serving.md#multi-host).

Frame format — 4-byte big-endian unsigned length prefix, then exactly
that many bytes of UTF-8 JSON (the envelope)::

    +----------+----------------------------+
    | len: u32 | envelope: JSON, len bytes  |
    +----------+----------------------------+

Envelope — versioned (``"v"``), one dict per frame::

    {"v": 2, "kind": "query",  "id": 7, "record": {...},
     "deadline_ms": 1.8, "trace": {"trace_id": "...", "attempt": 1}}
    {"v": 2, "kind": "query",  "id": 7, "records": [{...}, ...]}  (batched)
    {"v": 2, "kind": "result", "id": 7, "result": {...}, "health": "healthy",
     "server_ms": 1.2, "t_server": 812.44, "span": {...}}
    {"v": 2, "kind": "result", "id": 7, "results": [{...}, ...]}  (batched)
    {"v": 2, "kind": "health" | "latency" | "stats", "id": 8}     (request)
    {"v": 2, "kind": "health" | "latency" | "stats", "id": 8,
     "snapshot": {...}, "health": "healthy"}                      (response)
    {"v": 2, "kind": "flight_pull", "id": 9}                      (request)
    {"v": 2, "kind": "flight", "id": 9, "records": [...]}         (response)
    {"v": 2, "kind": "error", "id": 7 | null, "reason": "...",
     "health": "healthy"}

Version negotiation (wire v2, fleet observability): the server accepts
BOTH v1 and v2 request envelopes and every reply echoes the REQUEST's
version, so a v1 client talking to a v2 server sees pure v1 traffic. The
v2-only fields — ``server_ms`` + the queue/execute split inside the
result payload, the ``t_server`` monotonic timestamp (the client's
RTT-midpoint clock-offset estimate), the piggybacked ``span`` tree
(cross-host trace stitching), the ``stats`` / ``flight_pull`` kinds and
batched ``records`` frames — ride only on v2 envelopes. A v2 client
dialing a v1 server gets ``version_mismatch`` on its handshake and
re-handshakes at v1 on the same socket (serve/remote.py), degrading to
the PR 16 flat behaviour.

Contract decisions that carry the robustness weight:

* **Hostile length prefix** — a declared length over the
  ``wire_max_frame_bytes`` cap is rejected BEFORE any payload byte is
  read (one 4-byte header read, zero allocation), answered with an
  ``error`` envelope (reason ``frame_too_large``) and the connection is
  closed: past the header there is no way to resynchronise a stream whose
  framing cannot be trusted.
* **Torn frame** — EOF mid-frame raises :class:`TornFrame`; the side that
  observes it treats the CONNECTION as dead but never a request as lost:
  the client resolves every in-flight future as a machine-readable shed.
* **Corrupt payload** — a frame whose length is honest but whose JSON is
  not gets an ``error`` reply (reason ``bad_frame``) and the connection
  KEEPS SERVING: framing is intact, so one bad payload must not poison
  the requests interleaved behind it. Same for an unsupported envelope
  version (reason ``version_mismatch``).
* **Deadline propagation** — ``deadline_ms`` rides the query envelope as
  the client's REMAINING budget; the service's admission control and
  batcher then shed a lapsed request server-side, so a remote never
  scores work the caller already abandoned.
* **Health piggybacking** — every response carries the replica's
  ``health_state`` (one lock-free property read), so the client's view of
  a sickening host advances at request cadence, ahead of any watchdog.
* **Trace propagation** — the router-minted ``(trace_id, attempt)`` rides
  the envelope; the server reconstructs a :class:`~..obs.reqtrace.
  RequestTrace` around it so the replica that did the work emits the span
  tree, exactly like the in-process path (obs v2 contract).

Fault injection (``resilience/faults.py`` WIRE_SITES): ``wire_accept``,
``wire_request`` and ``wire_response`` fire the ``net_*`` kinds —
``net_drop`` (abrupt close), ``net_delay`` (stall, fired inside the
plan), ``net_torn_frame`` (cut a reply mid-frame) and ``net_partition``
(drop every connection and refuse new ones for ``delay_ms``).
``scripts/wire_chaos_smoke.py`` / ``make wire-smoke`` drive all of them
end to end.
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time

from ..analysis import lockwatch

from ..obs.events import _sanitise, publish
from ..resilience.faults import InjectedFault, active_plan

logger = logging.getLogger("splink_tpu")

#: Envelope schema version this build speaks natively; a frame carrying a
#: version outside :data:`SUPPORTED_VERSIONS` is rejected per-request
#: (reason ``version_mismatch``), not per-connection.
WIRE_VERSION = 2

#: Inbound request versions a v2 server answers (each reply echoes the
#: request's version — module docstring, version negotiation).
SUPPORTED_VERSIONS = (1, 2)

#: Default cap on one frame's payload (settings key ``wire_max_frame_bytes``).
DEFAULT_MAX_FRAME_BYTES = 4 * 1024 * 1024

#: Default cap on simultaneously open connections (settings key
#: ``wire_max_connections``). Past the cap a connection is accepted,
#: answered with ONE ``server_overloaded`` error envelope and closed — a
#: machine-readable shed, not a silent drop.
DEFAULT_MAX_CONNECTIONS = 64

_HEADER = struct.Struct(">I")
_RECV_CHUNK = 1 << 16  # bounded per-recv read; never trust the prefix


class WireError(RuntimeError):
    """Base class for wire-protocol failures."""


class FrameTooLarge(WireError):
    """A frame (outbound or declared by a length prefix) over the cap."""


class TornFrame(WireError):
    """EOF mid-frame: the peer died (or a fault cut the link) between the
    length prefix and the promised payload bytes."""


class CorruptFrame(WireError):
    """An intact frame whose payload is not valid JSON (or not a dict)."""


# -- frame layer --------------------------------------------------------


def encode_frame(
    envelope: dict, max_bytes: int = DEFAULT_MAX_FRAME_BYTES
) -> bytes:
    """Length-prefixed bytes for one envelope. ``_sanitise`` makes the
    payload JSON-safe (numpy scalars -> Python, non-finite -> null) so
    query records and results serialise without caller ceremony."""
    payload = json.dumps(
        _sanitise(envelope), separators=(",", ":")
    ).encode("utf-8")
    if len(payload) > max_bytes:
        raise FrameTooLarge(
            f"frame payload {len(payload)}B exceeds the {max_bytes}B cap"
        )
    return _HEADER.pack(len(payload)) + payload


def _recv_exact(sock: socket.socket, n: int, allow_eof: bool = False):
    """Exactly ``n`` bytes from ``sock`` in bounded chunks. A clean EOF at
    a frame boundary returns None (when ``allow_eof``); EOF anywhere else
    is a :class:`TornFrame`."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), _RECV_CHUNK))
        if not chunk:
            if allow_eof and not buf:
                return None
            raise TornFrame(
                f"connection closed {len(buf)}/{n} bytes into a frame"
            )
        buf.extend(chunk)
    return bytes(buf)


def read_frame(
    sock: socket.socket, max_bytes: int = DEFAULT_MAX_FRAME_BYTES
):
    """One envelope off the socket, or None on clean EOF.

    Raises :class:`FrameTooLarge` (hostile prefix — nothing past the
    4-byte header has been read), :class:`TornFrame` (EOF mid-frame) or
    :class:`CorruptFrame` (honest length, broken payload)."""
    hdr = _recv_exact(sock, _HEADER.size, allow_eof=True)
    if hdr is None:
        return None
    (length,) = _HEADER.unpack(hdr)
    if length == 0 or length > max_bytes:
        raise FrameTooLarge(
            f"declared frame length {length}B outside (0, {max_bytes}B]"
        )
    payload = _recv_exact(sock, length)
    try:
        env = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise CorruptFrame(f"undecodable frame payload: {e}") from e
    if not isinstance(env, dict):
        raise CorruptFrame(f"envelope must be a JSON object, got {type(env)}")
    return env


# -- server -------------------------------------------------------------


class _ServerConn:
    """One accepted connection: the socket, a write lock (responses for
    interleaved requests resolve from worker threads) and liveness."""

    __slots__ = ("sock", "peer", "wlock", "alive")

    def __init__(self, sock: socket.socket, peer):
        self.sock = sock
        self.peer = f"{peer[0]}:{peer[1]}" if isinstance(peer, tuple) else str(peer)
        self.wlock = lockwatch.new_lock("_ServerConn.wlock")
        self.alive = True

    def send(self, frame: bytes) -> None:
        with self.wlock:
            if not self.alive:
                raise BrokenPipeError("connection already closed")
            # Serializing whole-frame writes is wlock's entire job: two
            # threads interleaving partial sendall()s would corrupt the
            # stream. wlock is a leaf (never wraps another acquisition),
            # so blocking under it cannot deadlock — only queue writers.
            self.sock.sendall(frame)  # threadlint: disable=TL002 (leaf write lock; see comment)

    def abort(self) -> None:
        """Hard-close from any thread; unblocks a reader mid-recv."""
        with self.wlock:
            if not self.alive:
                return
            self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def _shed_result(reason: str):
    from .service import QueryResult  # lazy: wire stays import-light

    return QueryResult(shed=True, reason=reason)


class _SpanJoin:
    """Joins a traced request's two completion signals — the future's
    done-callback (the result payload) and the trace's ``on_close`` hook
    (the span tree) — and sends ONE combined ``result`` envelope when
    both have landed (wire v2 stitching).

    The service resolves the future before closing the trace on the same
    worker thread, so in practice ``note_result`` always arrives first
    and ``note_span`` sends microseconds later; the tiny lock makes
    either order (and a foreign service that never closes its traces,
    via ``cancel``) safe. Sending goes through ``WireServer._reply``,
    which never raises."""

    __slots__ = ("server", "wc", "req_id", "version", "_lock", "_body",
                 "_span", "_done")

    def __init__(self, server, wc, req_id, version: int):
        self.server = server
        self.wc = wc
        self.req_id = req_id
        self.version = version
        self._lock = threading.Lock()
        self._body: dict | None = None
        self._span: dict | None = None
        self._done = False

    def note_result(self, body: dict) -> None:
        with self._lock:
            if self._done:
                return
            self._body = body
            if self._span is None:
                return  # the span closes next; it sends
            self._done = True
            body = dict(self._body, span=self._span)
        self.server._reply(self.wc, body, version=self.version)

    def note_span(self, event: dict) -> None:
        with self._lock:
            if self._done:
                return
            self._span = event
            if self._body is None:
                return  # future not resolved yet; note_result sends
            self._done = True
            body = dict(self._body, span=self._span)
        self.server._reply(self.wc, body, version=self.version)

    def cancel(self) -> None:
        """An error reply already went out; drop whatever arrives."""
        with self._lock:
            self._done = True


class WireServer:
    """Serves one replica (anything in the :class:`~.router.Replica`
    shape, normally a :class:`~.service.LinkageService`) over the wire
    protocol (module docstring).

    Thread-per-connection with response demultiplexing: requests on one
    connection are submitted as they arrive and each response is written
    when ITS future resolves, under the connection write lock — so a slow
    query never convoys the fast ones interleaved behind it.

    ``partition(duration_s)`` models a network partition: every live
    connection drops abruptly and new connections are refused until the
    heal, which publishes ``wire_partition_heal``. ``kill()`` models host
    death: everything closes abruptly, nothing drains, no events.
    """

    def __init__(
        self,
        service,
        *,
        host: str = "127.0.0.1",
        port: int | None = None,
        max_frame_bytes: int | None = None,
        max_connections: int | None = None,
        name: str | None = None,
        protocol_version: int | None = None,
    ):
        settings = getattr(
            getattr(getattr(service, "engine", None), "index", None),
            "settings",
            {},
        ) or {}
        self.service = service
        self.host = host
        self._port_requested = int(
            port if port is not None else settings.get("wire_port", 0) or 0
        )
        self.max_frame_bytes = int(
            max_frame_bytes
            if max_frame_bytes is not None
            else settings.get("wire_max_frame_bytes", DEFAULT_MAX_FRAME_BYTES)
            or DEFAULT_MAX_FRAME_BYTES
        )
        self.max_connections = int(
            max_connections
            if max_connections is not None
            else settings.get("wire_max_connections", DEFAULT_MAX_CONNECTIONS)
            or DEFAULT_MAX_CONNECTIONS
        )
        if self.max_connections < 1:
            raise ValueError(
                f"wire_max_connections must be >= 1, got {self.max_connections}"
            )
        self.name = name or f"wire:{getattr(service, 'name', 'serve')}"
        # ``protocol_version=1`` makes this server behave as a legacy v1
        # peer (accepts only v1 envelopes, emits none of the v2 fields) —
        # the degradation tests' stand-in for a pre-fleet build.
        self.protocol_version = int(protocol_version or WIRE_VERSION)
        if self.protocol_version not in SUPPORTED_VERSIONS:
            raise ValueError(
                f"protocol_version must be one of {SUPPORTED_VERSIONS}, "
                f"got {self.protocol_version}"
            )
        self._stitching = bool(settings.get("fleet_stitching", True))
        self._settings = settings
        self._lock = lockwatch.new_lock("WireServer._lock")
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns: list[_ServerConn] = []
        self._threads: list[threading.Thread] = []
        self._stop = False
        self._partition_until = 0.0
        self._partition_timer: threading.Timer | None = None
        self.port: int | None = None
        self.connections_total = 0
        self.requests_total = 0
        self.errors_total = 0
        self.partitions_total = 0
        self.overloaded_total = 0

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "WireServer":
        with self._lock:
            if self._listener is not None:
                return self
            lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lst.bind((self.host, self._port_requested))
            lst.listen(128)
            self._listener = lst
            self._stop = False
            self.port = lst.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self.name}-accept", daemon=True
        )
        self._accept_thread.start()
        logger.info("wire server %s listening on %s", self.name, self.address)
        return self

    @property
    def address(self) -> str:
        # port is assigned exactly once, inside start()'s lock, before the
        # accept thread or any client exists; every later read sees the
        # final value. stats() also reads this while holding _lock, so
        # taking the (non-reentrant) lock here would self-deadlock.
        return f"{self.host}:{self.port}"  # threadlint: disable=TL001 (write-once at startup)

    def close(self) -> None:
        """Graceful stop: no new connections, live ones close, threads
        join. Idempotent."""
        self._shutdown(abrupt=False)

    def kill(self) -> None:
        """Host death: everything closes abruptly mid-whatever — clients
        must recover via their shed/reconnect paths, not via any goodbye
        this server never sends."""
        self._shutdown(abrupt=True)

    def _shutdown(self, abrupt: bool) -> None:
        with self._lock:
            if self._stop and self._listener is None:
                return
            self._stop = True
            listener, self._listener = self._listener, None
            conns = list(self._conns)
            timer, self._partition_timer = self._partition_timer, None
        if timer is not None:
            timer.cancel()
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        for wc in conns:
            wc.abort()
        if not abrupt:
            for t in list(self._threads):
                if t is not threading.current_thread():
                    t.join(timeout=2.0)
            if self._accept_thread is not None:
                self._accept_thread.join(timeout=2.0)

    # -- partition ------------------------------------------------------

    def partition(self, duration_s: float) -> None:
        """Drop every connection and refuse new ones for ``duration_s``;
        the heal publishes ``wire_partition_heal``."""
        with self._lock:
            self._partition_until = time.monotonic() + duration_s
            conns = list(self._conns)
            self.partitions_total += 1
            if self._partition_timer is not None:
                self._partition_timer.cancel()
            self._partition_timer = threading.Timer(
                duration_s, self._heal, args=(duration_s, len(conns))
            )
            self._partition_timer.daemon = True
            self._partition_timer.start()
        logger.warning(
            "wire server %s partitioned for %.0fms (%d connections dropped)",
            self.name, duration_s * 1e3, len(conns),
        )
        for wc in conns:
            wc.abort()

    def _heal(self, duration_s: float, dropped: int) -> None:
        with self._lock:
            self._partition_until = 0.0
            self._partition_timer = None
        publish(
            "wire_partition_heal",
            server=self.name,
            duration_s=round(duration_s, 3),
            dropped=dropped,
        )
        logger.info("wire server %s partition healed", self.name)

    def _partitioned(self) -> bool:
        with self._lock:
            until = self._partition_until
        return time.monotonic() < until

    # -- accept / connection loops --------------------------------------

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                listener = self._listener
                stop = self._stop
            if listener is None or stop:
                return
            try:
                sock, peer = listener.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                stop = self._stop
            if stop or self._partitioned():
                # a partitioned host is unreachable: the accepted socket
                # dies before a single byte, so the client's liveness
                # handshake reads EOF and treats the connect as failed
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._lock:
                overloaded = len(self._conns) >= self.max_connections
                if overloaded:
                    self.overloaded_total += 1
            if overloaded:
                # Explicit shed, not a silent drop: the client reads ONE
                # machine-readable error envelope (reason
                # `server_overloaded`) before EOF, so it can tell "this
                # host is full, fail over" apart from a partition or a
                # crash — and must not burn its reconnect backoff on it.
                self._refuse_overloaded(sock, peer)
                continue
            wc = _ServerConn(sock, peer)
            with self._lock:
                self.connections_total += 1
                n = self.connections_total
                self._conns.append(wc)
            try:
                active_plan(self._settings).fire("wire_accept", conn=n)
            except InjectedFault as f:
                self._net_fault(wc, f)
                continue
            publish(
                "wire_connect", server=self.name, peer=wc.peer, conn=n
            )
            t = threading.Thread(
                target=self._serve_conn,
                args=(wc,),
                name=f"{self.name}-conn{n}",
                daemon=True,
            )
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
            t.start()

    def _refuse_overloaded(self, sock: socket.socket, peer) -> None:
        """Answer an over-cap connection with one ``server_overloaded``
        error envelope and close it (module cap docstring)."""
        peer_s = f"{peer[0]}:{peer[1]}" if isinstance(peer, tuple) else str(peer)
        try:
            sock.sendall(
                encode_frame(
                    {
                        "v": self.protocol_version,
                        "kind": "error",
                        "id": None,
                        "reason": "server_overloaded",
                        "health": getattr(
                            self.service, "health_state", "degraded"
                        ),
                    },
                    self.max_frame_bytes,
                )
            )
        except OSError:
            pass  # the peer already gave up; the close below still counts
        try:
            sock.close()
        except OSError:
            pass
        publish(
            "wire_overload",
            server=self.name,
            peer=peer_s,
            max_connections=self.max_connections,
        )
        logger.warning(
            "wire server %s refused %s: %d connections open (cap %d)",
            self.name, peer_s, self.max_connections, self.max_connections,
        )

    def _net_fault(self, wc: _ServerConn, fault: InjectedFault) -> None:
        """Apply an injected network fault to a connection: every net kind
        (and any other injected raise at a wire site) ends in an abrupt
        close; ``net_partition`` additionally opens the partition window,
        and ``net_torn_frame`` is handled at the response site where there
        is a frame to tear."""
        if fault.kind == "net_partition":
            self.partition(fault.delay_ms / 1000.0)
            return  # partition() aborts every connection, including wc
        self._drop_conn(wc, reason=fault.kind)

    def _drop_conn(self, wc: _ServerConn, reason: str) -> None:
        wc.abort()
        with self._lock:
            if wc in self._conns:
                self._conns.remove(wc)
            stop = self._stop
        if not stop:
            publish(
                "wire_disconnect",
                server=self.name,
                peer=wc.peer,
                reason=reason,
            )

    def _serve_conn(self, wc: _ServerConn) -> None:
        reason = "eof"
        try:
            while wc.alive:
                try:
                    env = read_frame(wc.sock, self.max_frame_bytes)
                except FrameTooLarge as e:
                    # reject without reading the payload; the stream's
                    # framing is untrustworthy past this point, so close
                    with self._lock:
                        self.errors_total += 1
                    self._reply_error(wc, None, "frame_too_large", str(e))
                    reason = "frame_too_large"
                    break
                except CorruptFrame as e:
                    # honest length, broken payload: reject the request,
                    # keep the connection (framing is intact)
                    with self._lock:
                        self.errors_total += 1
                    self._reply_error(wc, None, "bad_frame", str(e))
                    continue
                if env is None:
                    break  # clean EOF
                self._dispatch(wc, env)
        except (TornFrame, ConnectionError, OSError):
            reason = "torn"
        finally:
            self._drop_conn(wc, reason=reason)

    # -- request dispatch -----------------------------------------------

    def _dispatch(self, wc: _ServerConn, env: dict) -> None:
        req_id = env.get("id")
        pv = env.get("v")
        accepted = (
            SUPPORTED_VERSIONS if self.protocol_version >= 2 else (1,)
        )
        if pv not in accepted:
            with self._lock:
                self.errors_total += 1
            self._reply_error(
                wc, req_id, "version_mismatch",
                f"envelope v={pv!r}, this server speaks "
                f"v={self.protocol_version}",
                version=self.protocol_version,
            )
            return
        kind = env.get("kind")
        if kind == "query":
            if isinstance(env.get("records"), list) and pv >= 2:
                self._handle_batch_query(wc, req_id, env, pv)
            else:
                self._handle_query(wc, req_id, env, pv)
        elif kind == "health":
            snap = self._safe_call(self.service.health, {})
            body = {"kind": "health", "id": req_id, "snapshot": snap}
            if pv >= 2:
                # the clock-offset sample: the client brackets this reply
                # between its send/receive stamps (RTT midpoint)
                body["t_server"] = time.monotonic()
            self._reply(wc, body, version=pv)
        elif kind == "latency":
            snap = self._safe_call(self.service.latency_summary, {})
            self._reply(
                wc, {"kind": "latency", "id": req_id, "snapshot": snap},
                version=pv,
            )
        elif kind == "stats" and pv >= 2:
            fn = getattr(self.service, "fleet_stats", None)
            snap = self._safe_call(fn, {}) if fn is not None else {}
            self._reply(
                wc, {"kind": "stats", "id": req_id, "snapshot": snap},
                version=pv,
            )
        elif kind == "flight_pull" and pv >= 2:
            fr = getattr(self.service, "flight_recorder", None)
            records = (
                self._safe_call(fr.snapshot, []) if fr is not None else []
            )
            self._reply(
                wc,
                {"kind": "flight", "id": req_id, "records": records,
                 "replica": getattr(self.service, "name", self.name)},
                version=pv,
            )
        else:
            with self._lock:
                self.errors_total += 1
            self._reply_error(
                wc, req_id, "bad_kind", f"unsupported kind {kind!r}",
                version=pv,
            )

    @staticmethod
    def _safe_call(fn, default):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - introspection must not kill the conn
            logger.warning("wire introspection call failed: %s", e)
            return default

    def _handle_query(
        self, wc: _ServerConn, req_id, env: dict, pv: int = 1
    ) -> None:
        t_recv = time.monotonic()
        with self._lock:
            self.requests_total += 1
            n = self.requests_total
        try:
            active_plan(self._settings).fire("wire_request", request=n)
        except InjectedFault as f:
            self._net_fault(wc, f)
            return
        record = env.get("record") or {}
        deadline_ms = env.get("deadline_ms")
        trace = self._inbound_trace(env.get("trace"))
        # span piggyback (v2 stitching): the service resolves the future
        # FIRST, then closes the trace on the same worker thread — so the
        # result send waits for the span via the trace's on_close hook
        # instead of racing it (obs/reqtrace.py). Both callbacks feed the
        # join; whichever lands second sends the combined envelope.
        join = None
        if (
            pv >= 2
            and trace is not None
            and self._stitching
            and getattr(self.service, "closes_traces", False)
        ):
            # only a service that closes every attempt it resolves
            # (LinkageService's contract) may gate the reply on the span;
            # plain duck-typed replicas keep the flat v1-style result
            join = _SpanJoin(self, wc, req_id, pv)
            trace.on_close = join.note_span
        try:
            if trace is not None:
                fut = self.service.submit(
                    record, deadline_ms=deadline_ms, trace=trace
                )
            else:
                fut = self.service.submit(record, deadline_ms=deadline_ms)
        except Exception as e:  # noqa: BLE001 - a throwing replica is a shed
            logger.warning("wire submit raised (replied as shed): %s", e)
            if join is not None:
                join.cancel()
            self._reply_error(
                wc, req_id, "replica_error", str(e)[:300], version=pv
            )
            return
        fut.add_done_callback(
            lambda f, wc=wc, rid=req_id, pv=pv, t0=t_recv, j=join:
            self._send_result(wc, rid, f, pv=pv, t_recv=t0, join=j)
        )

    def _handle_batch_query(
        self, wc: _ServerConn, req_id, env: dict, pv: int
    ) -> None:
        """A batched ``records`` frame (client-side envelope batching):
        every record is submitted individually — the service's own
        coalescer amortises dispatch — and ONE ``results`` reply carries
        the payloads in request order once the last future resolves.
        Batched frames carry no per-request traces (the amortisation is
        the point; per-record spans would undo it)."""
        t_recv = time.monotonic()
        records = env.get("records") or []
        with self._lock:
            self.requests_total += len(records)
            n = self.requests_total
        try:
            active_plan(self._settings).fire("wire_request", request=n)
        except InjectedFault as f:
            self._net_fault(wc, f)
            return
        deadline_ms = env.get("deadline_ms")
        count = len(records)
        if count == 0:
            self._reply(
                wc,
                {"kind": "result", "id": req_id, "results": [],
                 "server_ms": 0.0, "t_server": time.monotonic()},
                version=pv,
            )
            return
        payloads: list = [None] * count
        remaining = [count]
        rlock = threading.Lock()

        def on_done(i: int, fut) -> None:
            try:
                payloads[i] = fut.result().to_payload()
            except Exception as e:  # noqa: BLE001 - replica futures should not raise
                logger.warning("wire batched future raised: %s", e)
                payloads[i] = {"shed": True, "reason": "remote_error"}
            with rlock:
                remaining[0] -= 1
                last = remaining[0] == 0
            if last:
                now = time.monotonic()
                self._reply(
                    wc,
                    {"kind": "result", "id": req_id, "results": payloads,
                     "server_ms": (now - t_recv) * 1e3, "t_server": now},
                    version=pv,
                )

        for i, record in enumerate(records):
            try:
                fut = self.service.submit(
                    record or {}, deadline_ms=deadline_ms
                )
            except Exception as e:  # noqa: BLE001 - a throwing replica is a shed
                logger.warning("wire batched submit raised: %s", e)
                from concurrent.futures import Future

                fut = Future()
                fut.set_result(
                    _shed_result("replica_error")
                )
            fut.add_done_callback(
                lambda f, i=i: on_done(i, f)
            )

    def _inbound_trace(self, t):
        """Reconstruct the router-minted trace context so the replica that
        does the work emits the span tree (obs v2 contract) — only when
        the backing replica accepts one."""
        if not t or not getattr(self.service, "accepts_trace", False):
            return None
        try:
            from ..obs.reqtrace import RequestTrace, TraceRoot

            return RequestTrace(
                root=TraceRoot(trace_id=str(t.get("trace_id"))),
                attempt=int(t.get("attempt") or 0),
                hedge=bool(t.get("hedge")),
            )
        except Exception:  # noqa: BLE001 - tracing must never break serving
            return None

    # -- responses ------------------------------------------------------

    def _send_result(
        self, wc: _ServerConn, req_id, fut, pv: int = 1,
        t_recv: float | None = None, join=None,
    ) -> None:
        try:
            res = fut.result()
            payload = res.to_payload()
        except Exception as e:  # noqa: BLE001 - replica futures should not raise
            logger.warning("wire replica future raised: %s", e)
            if join is not None:
                join.cancel()
            self._reply_error(
                wc, req_id, "replica_error", str(e)[:300], version=pv
            )
            return
        body = {"kind": "result", "id": req_id, "result": payload}
        if pv >= 2:
            now = time.monotonic()
            body["t_server"] = now
            if t_recv is not None:
                body["server_ms"] = (now - t_recv) * 1e3
        if join is not None:
            join.note_result(body)
            return
        self._reply(wc, body, version=pv)

    def _reply_error(
        self, wc, req_id, reason: str, detail: str,
        version: int | None = None,
    ) -> None:
        self._reply(
            wc,
            {"kind": "error", "id": req_id, "reason": reason,
             "detail": detail},
            version=version,
        )

    def _reply(
        self, wc: _ServerConn, body: dict, version: int | None = None
    ) -> None:
        env = {
            # echo the request's version (negotiation contract); server-
            # initiated frames carry this build's native version
            "v": version if version is not None else self.protocol_version,
            # piggybacked health: one lock-free property read per response
            "health": getattr(self.service, "health_state", None),
            **body,
        }
        try:
            active_plan(self._settings).fire(
                "wire_response", request=body.get("id")
            )
        except InjectedFault as f:
            if f.kind == "net_torn_frame":
                self._send_torn(wc, env)
                return
            self._net_fault(wc, f)
            return
        try:
            wc.send(encode_frame(env, self.max_frame_bytes))
        except (WireError, OSError) as e:
            # a result landing on an already-dead connection (peer gone,
            # server killed mid-flight) is routine churn, not an incident
            log = logger.debug if not wc.alive else logger.warning
            log("wire response to %s failed: %s", wc.peer, e)
            self._drop_conn(wc, reason="send_failed")

    def _send_torn(self, wc: _ServerConn, env: dict) -> None:
        """Write a frame whose prefix promises more bytes than arrive,
        then die — the torn-frame failure the client reader must turn
        into sheds, never hangs."""
        frame = encode_frame(env, self.max_frame_bytes)
        cut = max(len(frame) // 2, _HEADER.size + 1)
        try:
            wc.send(frame[:cut])
        except OSError:
            pass
        self._drop_conn(wc, reason="net_torn_frame")

    # -- introspection --------------------------------------------------

    def stats(self) -> dict:
        # _partitioned() takes _lock itself — resolve it before entering
        partitioned = self._partitioned()
        with self._lock:
            return {
                "server": self.name,
                "address": self.address,
                "connections_total": self.connections_total,
                "connections_active": len(self._conns),
                "requests_total": self.requests_total,
                "errors_total": self.errors_total,
                "partitions_total": self.partitions_total,
                "overloaded_total": self.overloaded_total,
                "max_connections": self.max_connections,
                "partitioned": partitioned,
            }

    def prometheus_samples(self) -> list:
        from ..obs.exposition import Sample

        labels = {"server": self.name}
        s = self.stats()
        return [
            Sample("splink_wire_connections_total",
                   s["connections_total"], labels, "counter",
                   "Wire connections accepted"),
            Sample("splink_wire_connections_active",
                   s["connections_active"], labels, "gauge",
                   "Wire connections currently open"),
            Sample("splink_wire_requests_total", s["requests_total"],
                   labels, "counter", "Wire query requests received"),
            Sample("splink_wire_errors_total", s["errors_total"], labels,
                   "counter",
                   "Wire protocol errors (bad frame/version/kind)"),
            Sample("splink_wire_partitions_total", s["partitions_total"],
                   labels, "counter", "Injected/observed partitions"),
            Sample("splink_wire_overloaded_total", s["overloaded_total"],
                   labels, "counter",
                   "Connections refused past the wire_max_connections cap"),
        ]
