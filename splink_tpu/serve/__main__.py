"""CLI: build, query and benchmark a serving index.

    python -m splink_tpu.serve build --model model.json --data ref.csv \
        --out index_dir
    python -m splink_tpu.serve query --index index_dir --data queries.csv
    python -m splink_tpu.serve bench --index index_dir --queries 1000

``build`` loads a model saved with ``save_model_as_json`` (settings +
trained parameters), encodes the reference data and writes the frozen
artifact. ``query`` prints one JSON line per (query, match). ``bench``
warms every bucket combination, then measures steady-state latency
percentiles, throughput and the compile counter (which must stay flat
after warmup).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _read_frame(path: str):
    import pandas as pd

    if path.endswith(".parquet"):
        return pd.read_parquet(path)
    return pd.read_csv(path)


def _aot_dir(index_dir: str) -> str | None:
    """The index's AOT sidecar directory when one is present (restored
    automatically — a stale sidecar degrades to fresh compiles)."""
    import os

    d = os.path.join(index_dir, "aot")
    return d if os.path.isdir(d) else None


def _cmd_build(args) -> int:
    from ..linker import load_from_json

    df = _read_frame(args.data)
    linker = load_from_json(args.model, df=df)
    index = linker.export_index(args.out)
    aot = None
    if args.aot:
        import os

        from . import QueryEngine

        engine = QueryEngine(index, aot_dir=os.path.join(args.out, "aot"))
        warm = engine.warmup()
        engine.save_aot()
        aot = {"executables": len(engine.warmed_shapes)
               + len(engine.warmed_brownout_shapes), **warm}
    print(
        json.dumps(
            {
                "built": args.out,
                "n_rows": index.n_rows,
                "n_rules": len(index.rules),
                "n_lanes": index.n_lanes,
                "dtype": index.dtype,
                **({"aot": aot} if aot else {}),
            }
        )
    )
    return 0


def _cmd_query(args) -> int:
    from . import QueryEngine, load_index

    engine = QueryEngine(
        load_index(args.index), top_k=args.k or None,
        aot_dir=_aot_dir(args.index),
    )
    engine.warmup()
    df = _read_frame(args.data)
    out = engine.query(df)
    for rec in out.to_dict(orient="records"):
        print(json.dumps(rec, default=str))
    return 0


def _cmd_bench(args) -> int:
    import numpy as np

    from ..obs.metrics import compile_requests, install_compile_monitor
    from . import LinkageService, QueryEngine, load_index

    install_compile_monitor()
    index = load_index(args.index)
    engine = QueryEngine(
        index, top_k=args.k or None, aot_dir=_aot_dir(args.index)
    )
    warm = engine.warmup()
    c_warm = compile_requests()
    svc = LinkageService(engine, deadline_ms=args.deadline_ms)
    rng = np.random.default_rng(0)
    uid_col = index.settings["unique_id_column_name"]
    # replay reference records as queries (every record resolves a bucket)
    rows = rng.integers(0, index.n_rows, args.queries)
    # reconstruct minimal query records from the vocabularies is not
    # possible generically; bench replays the provided query file when
    # given, else synthesises key-only records per reference row
    if args.data:
        df = _read_frame(args.data)
        records = df.to_dict(orient="records")
    else:
        print(
            "bench: no --data given; provide a query file to benchmark "
            "against",
            file=sys.stderr,
        )
        return 2
    t0 = time.perf_counter()
    futs = [svc.submit(records[int(r) % len(records)]) for r in rows]
    for f in futs:
        f.result()
    wall = time.perf_counter() - t0
    svc.close()
    c_end = compile_requests()
    summary = svc.latency_summary()
    print(
        json.dumps(
            {
                "metric": "serve_queries_per_sec",
                "value": round(args.queries / wall, 1),
                "unit": "queries/sec",
                "queries": args.queries,
                "uid_column": uid_col,
                "warmup_combinations": warm["combinations"],
                "warmup_compiles": warm["compiles"],
                "warmup_cache_hits": warm["cache_hits"],
                "warmup_aot_restored": warm["aot_restored"],
                "steady_state_compiles": c_end - c_warm,
                **{k: round(v, 3) if isinstance(v, float) else v
                   for k, v in summary.items()},
            }
        )
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m splink_tpu.serve",
        description="online linkage serving (docs/serving.md)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("build", help="freeze a trained model into an index")
    b.add_argument("--model", required=True, help="save_model_as_json output")
    b.add_argument("--data", required=True, help="reference csv/parquet")
    b.add_argument("--out", required=True, help="index output directory")
    b.add_argument(
        "--aot", action="store_true",
        help="also compile the serve bucket menu and commit the AOT "
        "executable sidecar (<out>/aot) so replicas warm up without the "
        "backend compiler (docs/serving.md#cold-start)",
    )
    b.set_defaults(fn=_cmd_build)

    q = sub.add_parser("query", help="score query records against an index")
    q.add_argument("--index", required=True)
    q.add_argument("--data", required=True, help="query csv/parquet")
    q.add_argument("--k", type=int, default=0, help="top-k (settings default)")
    q.set_defaults(fn=_cmd_query)

    n = sub.add_parser("bench", help="steady-state latency/throughput bench")
    n.add_argument("--index", required=True)
    n.add_argument("--data", default="", help="query csv/parquet to replay")
    n.add_argument("--queries", type=int, default=1000)
    n.add_argument("--k", type=int, default=0)
    n.add_argument("--deadline-ms", type=float, default=2.0)
    n.set_defaults(fn=_cmd_bench)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:  # piped into head etc.
        return 0


if __name__ == "__main__":
    sys.exit(main())
