"""Per-replica serving health: a hysteretic state machine over obs signals.

A replica is more than "up or down". Between those poles sits the state
every production incident actually lives in: the queue is filling, sheds
are climbing, p95 is drifting, a compile stall just ate half a second of
the latency budget. This module folds those signals — all of them already
measured by the service/obs layers, nothing new is instrumented — into ONE
discrete state per replica:

    healthy ──► degraded ──► broken
       ▲            ▲            │
       └────────────┴────────────┘  (recovery, hysteretic)

Transitions DOWN (toward broken) are immediate: a dead worker or an open
circuit breaker must be routed around on the very next request.
Transitions UP require ``recover_ticks`` consecutive evaluations at the
better level — hysteresis, so a replica oscillating around a threshold
does not flap the router. Recovery climbs one level per satisfied streak
(broken → degraded → healthy), mirroring how operators actually re-admit
a replica: first let it take degraded-tier traffic, then full traffic.

Every transition publishes a structured ``health`` event through the
ambient obs channel (:func:`..obs.events.publish`), so the JSONL record of
a chaotic run reads as a timeline: fault → degradation → health drop →
recovery. :meth:`LinkageService.health` (service.py) is the live endpoint
over this monitor; :class:`..serve.router.ReplicaRouter` routes on it.
"""

from __future__ import annotations

import threading
import time

from ..analysis import lockwatch

HEALTHY = "healthy"
DEGRADED = "degraded"
BROKEN = "broken"

_RANK = {HEALTHY: 0, DEGRADED: 1, BROKEN: 2}
_STATES = (HEALTHY, DEGRADED, BROKEN)


class HealthMonitor:
    """One replica's health state machine (module docstring).

    ``evaluate(signals)`` classifies one snapshot of the replica's signals
    and advances the state machine; it is cheap (pure host Python) and is
    driven by the service watchdog tick plus on-demand ``health()`` calls.

    Signals (all optional; missing keys read as their benign value):

    ``worker_alive``   bool — the micro-batching worker thread is running
    ``breaker``        "closed" | "open" | "half_open"
    ``queue_fill``     0..1 — bounded-queue occupancy
    ``shed_rate``      0..1 — sheds / (sheds + served) over the window
    ``p95_ms``         recent-window p95 latency (None = no samples)
    ``compile_stall``  bool — steady-state compile time observed (the
                       zero-recompile contract broke, or an unwarmed
                       shape slipped through)
    ``brownout``       bool — the service is in the brown-out tier.
                       Informational only (kept in the snapshot, NOT
                       classified): brown-out is an OUTPUT of pressure,
                       and since degraded health is itself a brown-out
                       trigger, classifying it would make the degraded
                       state self-sustaining after the pressure clears.
    """

    def __init__(
        self,
        *,
        name: str = "replica",
        degraded_queue_fill: float = 0.5,
        degraded_shed_rate: float = 0.02,
        broken_shed_rate: float = 0.5,
        degraded_p95_ms: float | None = None,
        recover_ticks: int = 3,
    ):
        self.name = name
        self.degraded_queue_fill = float(degraded_queue_fill)
        self.degraded_shed_rate = float(degraded_shed_rate)
        self.broken_shed_rate = float(broken_shed_rate)
        self.degraded_p95_ms = degraded_p95_ms
        self.recover_ticks = int(recover_ticks)
        self._lock = lockwatch.new_lock("HealthMonitor._lock")
        self._state = HEALTHY
        self._since = time.monotonic()
        self._better_streak = 0
        self._transitions = 0
        self._last_signals: dict = {}
        self._last_reasons: list[str] = []

    # -- classification --------------------------------------------------

    def classify(self, signals: dict) -> tuple[str, list[str]]:
        """(level, reasons) for one signals snapshot, ignoring hysteresis."""
        reasons: list[str] = []
        if not signals.get("worker_alive", True):
            reasons.append("worker thread dead")
        if signals.get("breaker") == "open":
            reasons.append("circuit breaker open")
        shed_rate = float(signals.get("shed_rate") or 0.0)
        if shed_rate >= self.broken_shed_rate:
            reasons.append(
                f"shed rate {shed_rate:.2f} >= {self.broken_shed_rate:.2f}"
            )
        if reasons:
            return BROKEN, reasons
        if signals.get("breaker") == "half_open":
            reasons.append("circuit breaker probing recovery")
        if shed_rate > self.degraded_shed_rate:
            reasons.append(
                f"shed rate {shed_rate:.2f} > {self.degraded_shed_rate:.2f}"
            )
        fill = float(signals.get("queue_fill") or 0.0)
        if fill >= self.degraded_queue_fill:
            reasons.append(
                f"queue {fill:.0%} full >= {self.degraded_queue_fill:.0%}"
            )
        if signals.get("compile_stall"):
            reasons.append("steady-state compile stall")
        p95 = signals.get("p95_ms")
        if (
            self.degraded_p95_ms is not None
            and isinstance(p95, (int, float))
            and p95 > self.degraded_p95_ms
        ):
            reasons.append(
                f"p95 {p95:.1f}ms > {self.degraded_p95_ms:.1f}ms"
            )
        if reasons:
            return DEGRADED, reasons
        return HEALTHY, reasons

    # -- state machine ---------------------------------------------------

    def evaluate(self, signals: dict) -> str:
        """Advance the state machine with one snapshot; returns the state.

        Worse observations transition immediately; better ones must hold
        for ``recover_ticks`` consecutive evaluations and then improve the
        state ONE level (hysteretic recovery, module docstring)."""
        level, reasons = self.classify(signals)
        with self._lock:
            self._last_signals = dict(signals)
            self._last_reasons = reasons
            old = self._state
            if _RANK[level] > _RANK[old]:
                new = level
                self._better_streak = 0
            elif _RANK[level] < _RANK[old]:
                self._better_streak += 1
                if self._better_streak >= self.recover_ticks:
                    new = _STATES[_RANK[old] - 1]
                    self._better_streak = 0
                else:
                    new = old
            else:
                self._better_streak = 0
                new = old
            if new != old:
                self._state = new
                self._since = time.monotonic()
                self._transitions += 1
        if new != old:
            from ..obs.events import publish

            publish(
                "health",
                replica=self.name,
                **{"from": old, "to": new},
                reasons=reasons,
                signals={
                    k: v for k, v in signals.items() if not callable(v)
                },
            )
        return new

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        """JSON-ready view: state, time in state, last signals/reasons."""
        with self._lock:
            return {
                "replica": self.name,
                "state": self._state,
                "since_s": round(time.monotonic() - self._since, 3),
                "transitions": self._transitions,
                "reasons": list(self._last_reasons),
                "signals": dict(self._last_signals),
            }


def health_rank(state: str) -> int:
    """healthy=0 < degraded=1 < broken=2 (router ordering key)."""
    return _RANK.get(state, _RANK[BROKEN])


def worse(a: str, b: str) -> str:
    """The sicker of two states (max by rank; unknown reads as broken).

    The wire tier folds two views into one replica state with it: the
    remote's piggybacked self-assessment and the local link view — a
    healthy host behind a dead link is still unreachable, and a reachable
    host that reports degraded must not be promoted by the link being
    fine (:class:`~.remote.RemoteReplica.health_state`)."""
    return a if health_rank(a) >= health_rank(b) else b
