"""AOT executable store: the serve bucket menu as a restorable artifact.

The bucket-shape menu (:mod:`.bucketing`) makes steady-state serving
recompile-free, but every fresh process still pays one backend compile per
(query-bucket × candidate-bucket) combination before it can take traffic —
12.4 s of measured warmup on the CPU tier (BENCHMARKS.md), which the PR 6
``ReplicaRouter`` fleet pays on every replica restart and the PR 8
compile-stall health signal reads as a degraded window. This module
removes that cost: after :meth:`~.engine.QueryEngine.warmup`, the engine
serializes every compiled executable (``jax.experimental
.serialize_executable`` — the loaded XLA executable itself, not its HLO)
into a versioned sidecar next to the :class:`~.index.LinkageIndex`
artifact, and a fresh process restores the entire menu without ever
invoking the backend compiler (proven by the ``jax.monitoring`` compile
counter staying flat; gated by ``make warmup-smoke``).

A serialized executable is literal machine code bound to one exact
environment, so restore validity is STRICT — the sidecar meta records

  * the environment fingerprint (jax + jaxlib versions, backend, target
    features — for CPU the host ISA flag set, for accelerators the device
    kind/platform version — and the x64 switch), and
  * the engine binding (index content fingerprint + settings hash, dtype,
    top-k / brown-out budget, the full bucket menu, the fused-path flag),

and ANY mismatch invalidates the whole store with one structured
``serve_aot`` degradation event: the engine falls back to fresh compiles,
never a wrong or SIGILL-prone executable. Individual blobs are
sha256-bound by the meta (the atomic commit point, reusing the checkpoint
machinery), so a torn or tampered blob degrades that one shape to a fresh
compile instead of unpickling attacker-controlled bytes — a blob's pickle
payload is only ever deserialized AFTER its digest verifies against the
committed meta.

Durability mirrors the index artifact: blob files land first under
fingerprint-derived names, the meta JSON commits the set atomically, and
superseded blobs are swept only after the commit.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle

from ..resilience.checkpoint import atomic_write_bytes, atomic_write_json
from ..utils.logging_utils import warn_degraded

logger = logging.getLogger("splink_tpu")

AOT_FORMAT_VERSION = 1
MENU_NAME = "aot_menu.json"
BLOB_PREFIX = "exec-"


class AotStoreError(RuntimeError):
    """Unreadable / unwritable AOT sidecar."""


def _blob_file(name: str, digest: str) -> str:
    return f"{BLOB_PREFIX}{name}-{digest[:16]}.bin"


def serialize_executable(compiled) -> bytes:
    """One compiled executable (``jax.stages.Compiled``) to restorable
    bytes: the serialized XLA executable plus its argument pytree defs."""
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree))


def deserialize_executable(blob: bytes):
    """Restore a :func:`serialize_executable` blob to a callable
    ``Compiled``. Trusts its input — callers verify the sha256 binding
    first (this is a pickle load)."""
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = pickle.loads(blob)
    return se.deserialize_and_load(payload, in_tree, out_tree)


class AotStore:
    """One AOT sidecar directory (read side; :meth:`write` produces it)."""

    def __init__(self, directory: str | os.PathLike):
        self.directory = os.fspath(directory)
        self._entries: dict[str, dict] | None = None

    # -- read -----------------------------------------------------------

    def validate(self, binding: dict) -> bool:
        """Load the menu and check the full invalidation matrix against
        ``binding`` (the engine identity) and the CURRENT environment
        fingerprint. False (with exactly one structured degradation event
        naming every mismatched key) means the store must not be used and
        the caller compiles fresh."""
        from ..utils.envfp import environment_fingerprint

        menu_path = os.path.join(self.directory, MENU_NAME)
        try:
            with open(menu_path, encoding="utf-8") as fh:
                menu = json.load(fh)
        except FileNotFoundError:
            return False  # no sidecar: a plain cold start, not degraded
        except (OSError, json.JSONDecodeError, ValueError) as e:
            warn_degraded(
                "serve_aot",
                "unreadable",
                f"AOT sidecar meta at {menu_path} is unreadable ({e}); "
                "falling back to fresh compiles",
            )
            return False
        mismatches = []
        if menu.get("version") != AOT_FORMAT_VERSION:
            mismatches.append(
                f"format version {menu.get('version')!r} != "
                f"{AOT_FORMAT_VERSION}"
            )
        env = environment_fingerprint()
        saved_env = menu.get("environment") or {}
        for key, want in env.items():
            got = saved_env.get(key)
            if got != want:
                mismatches.append(
                    f"environment.{key} {got!r} != current {want!r}"
                )
        saved_binding = menu.get("binding") or {}
        for key, want in binding.items():
            got = saved_binding.get(key)
            if got != want:
                mismatches.append(f"binding.{key} {got!r} != {want!r}")
        if mismatches:
            warn_degraded(
                "serve_aot",
                "stale",
                "AOT sidecar invalidated (fresh compiles instead): "
                + "; ".join(mismatches),
                sidecar=self.directory,
            )
            return False
        self._entries = dict(menu.get("executables") or {})
        return True

    @property
    def names(self) -> list[str]:
        return sorted(self._entries or {})

    def restore(self, name: str):
        """Deserialize one executable by menu name, or None when the menu
        has no such entry or its blob is missing/corrupt (each corrupt
        blob emits one degradation event; the caller compiles fresh)."""
        if not self._entries:
            return None
        entry = self._entries.get(name)
        if entry is None:
            return None
        path = os.path.join(self.directory, entry["file"])
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as e:
            warn_degraded(
                "serve_aot",
                "corrupt_blob",
                f"AOT executable {name!r} unreadable at {path} ({e}); "
                "compiling fresh",
            )
            return None
        digest = hashlib.sha256(blob).hexdigest()
        if digest != entry.get("sha256"):
            warn_degraded(
                "serve_aot",
                "corrupt_blob",
                f"AOT executable {name!r} at {path} does not match its "
                "committed fingerprint (torn write or tampering); "
                "compiling fresh",
            )
            return None
        try:
            return deserialize_executable(blob)
        except Exception as e:  # noqa: BLE001 - every restore failure degrades
            warn_degraded(
                "serve_aot",
                "restore_failed",
                f"AOT executable {name!r} failed to deserialize "
                f"({type(e).__name__}: {e}); compiling fresh",
            )
            return None

    # -- write ----------------------------------------------------------

    @classmethod
    def write(
        cls, directory: str | os.PathLike, binding: dict, executables: dict
    ) -> str:
        """Persist ``executables`` (menu name -> compiled executable) as a
        sidecar at ``directory``: blobs first under fingerprint-derived
        names, then the meta JSON as the atomic commit point, then a
        best-effort sweep of superseded blobs. Returns the meta path."""
        from ..utils.envfp import environment_fingerprint

        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
        entries: dict[str, dict] = {}
        keep: set[str] = set()
        for name in sorted(executables):
            blob = serialize_executable(executables[name])
            digest = hashlib.sha256(blob).hexdigest()
            fname = _blob_file(name, digest)
            atomic_write_bytes(os.path.join(directory, fname), blob)
            entries[name] = {
                "file": fname,
                "sha256": digest,
                "bytes": len(blob),
            }
            keep.add(fname)
        menu = {
            "version": AOT_FORMAT_VERSION,
            "environment": environment_fingerprint(),
            "binding": binding,
            "executables": entries,
        }
        path = atomic_write_json(os.path.join(directory, MENU_NAME), menu)
        try:  # post-commit sweep (a leftover costs disk, never correctness)
            for fname in os.listdir(directory):
                if (
                    fname.startswith(BLOB_PREFIX)
                    and fname.endswith(".bin")
                    and fname not in keep
                ):
                    os.unlink(os.path.join(directory, fname))
        except OSError:  # pragma: no cover - sweep is best-effort
            pass
        logger.info(
            "AOT sidecar committed: %s (%d executables, %d bytes)",
            directory, len(entries),
            sum(e["bytes"] for e in entries.values()),
        )
        return path
