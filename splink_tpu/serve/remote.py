"""RemoteReplica: a wire-protocol client in the replica duck-type.

The router half of the multi-host story (:mod:`.wire` is the server
half): :class:`RemoteReplica` speaks the length-prefixed envelope
protocol to a :class:`~.wire.WireServer` on another host and exposes the
exact ``submit / health_state / latency_summary`` shape the
:class:`~.router.ReplicaRouter` routes, hedges and fails over across —
pinned by the :class:`~.router.Replica` Protocol, so a local
:class:`~.service.LinkageService` and a remote host are interchangeable
list entries in one router.

The robustness contract, in the same never-raise style as the service:

* ``submit`` NEVER raises and its future ALWAYS resolves — with a match
  result, or a shed carrying a machine-readable reason (``closed`` /
  ``breaker_open`` / ``remote_unreachable`` / ``connection_lost`` /
  ``deadline`` / ``timeout`` / any server-side shed reason verbatim).
* **Connection loss** resolves every in-flight future as a
  ``connection_lost`` shed immediately (one ``wire_shed`` event counts
  them) — a dead socket must cost the router one failover, never a hang.
* **Reconnect** runs in the background with the bounded exponential
  backoff of :class:`~..resilience.retry.RetryPolicy` and a liveness
  handshake (a ``health`` exchange) before a socket counts as connected —
  a partitioned host that accepts-then-drops keeps failing the handshake
  until the partition heals, at which point ``wire_reconnect`` reports
  the attempts and downtime.
* **Per-remote circuit breaker** (:class:`~.admission.CircuitBreaker`,
  the PR 6 machinery unchanged): consecutive link failures open it and
  submits fail fast as ``breaker_open`` sheds; after the cooldown one
  probe request tests the link and its outcome closes or re-opens the
  breaker — composing with, not duplicating, the server-side engine
  breaker (whose trips arrive as ordinary shed results).
* **Deadlines** ride the envelope so the server sheds lapsed work, AND a
  local sweeper resolves an expired in-flight future client-side
  (``deadline``; ``timeout`` after ``request_timeout_ms`` without a
  deadline) — the guarantee holds even when the far side is wedged.
* **Health** is the piggybacked server state from the last response,
  demoted by link state (breaker open / no live connection -> broken), so
  the router ranks a sick or unreachable host down at request cadence.

Everything is stdlib: sockets + threads + the repo's own resilience
primitives. docs/serving.md#multi-host holds the deployment sketch.
"""

from __future__ import annotations

import itertools
import logging
import socket
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

from ..analysis import lockwatch

from ..obs.events import publish
from ..resilience.retry import RetryPolicy
from .admission import CircuitBreaker
from .health import BROKEN, HEALTHY, health_rank, worse
from .service import QueryResult
from .wire import (
    DEFAULT_MAX_FRAME_BYTES,
    WIRE_VERSION,
    WireError,
    encode_frame,
    read_frame,
)

logger = logging.getLogger("splink_tpu")

_SWEEP_INTERVAL_S = 0.02  # deadline/timeout sweeper cadence
_LATENCY_RESERVOIR = 4096


class _Pending:
    """One in-flight request: its future (or, for a batched frame, the
    LIST of futures resolving from one reply), trace context and
    deadlines. ``t_sent`` is stamped after the frame hits the socket —
    ``t_sent - t0`` is the serialize phase of the wire decomposition."""

    __slots__ = ("fut", "trace", "t0", "deadline", "timeout_at", "t_sent")

    def __init__(self, fut, trace, deadline, timeout_at):
        self.fut = fut
        self.trace = trace
        self.t0 = time.monotonic()
        self.deadline = deadline
        self.timeout_at = timeout_at
        self.t_sent: float | None = None


class _RemoteConn:
    """One pooled connection: socket, write lock, pending map, reader,
    the handshake-negotiated peer protocol version and this connection's
    clock-offset estimate (fleet stitching).

    ``offset_s`` estimates ``t_server - t_client`` for the same instant:
    the client brackets a server timestamp between its send (``t0``) and
    receive (``t1``) stamps and assumes the stamp sits at the midpoint of
    the network round trip — error bounded by rtt/2, refined over the
    connection's lifetime by keeping the sample with the smallest
    server-time-excluded round trip."""

    __slots__ = ("sock", "wlock", "plock", "pending", "alive", "lost",
                 "reader", "peer_version", "offset_s", "offset_rtt_s")

    def __init__(self, sock: socket.socket, peer_version: int = WIRE_VERSION):
        self.sock = sock
        self.wlock = lockwatch.new_lock("_RemoteConn.wlock")
        self.plock = lockwatch.new_lock("_RemoteConn.plock")
        self.pending: dict[int, _Pending] = {}
        self.alive = True
        self.lost = False  # _conn_lost ran (exactly-once accounting)
        self.reader: threading.Thread | None = None
        self.peer_version = int(peer_version)
        self.offset_s: float | None = None
        self.offset_rtt_s = float("inf")

    def note_offset(self, t_server, t0: float, t1: float,
                    server_s: float = 0.0) -> None:
        """Fold one clock-offset sample (NTP-style midpoint estimate,
        lowest-residual-RTT sample wins). Single-writer: only this
        connection's reader thread (and the dialing thread, before the
        reader exists) calls it."""
        if t_server is None:
            return
        try:
            rtt_net = max((t1 - t0) - max(float(server_s), 0.0), 0.0)
            if rtt_net < self.offset_rtt_s:
                self.offset_rtt_s = rtt_net
                # the server stamps t_server just before sending the
                # reply: the client-clock instant it corresponds to is
                # t1 minus half the network round trip
                self.offset_s = float(t_server) - (t1 - rtt_net / 2.0)
        except (TypeError, ValueError):
            pass

    def mark_lost(self) -> bool:
        """True for the first caller only: the reader exit and a failed
        send can both observe the same death, but the sheds, the breaker
        failure and the event must count once."""
        with self.plock:
            if self.lost:
                return False
            self.lost = True
            return True

    def send(self, frame: bytes) -> None:
        with self.wlock:
            if not self.alive:
                raise BrokenPipeError("connection already closed")
            # Serializing whole-frame writes is wlock's entire job: two
            # threads interleaving partial sendall()s would corrupt the
            # stream. wlock is a leaf (never wraps another acquisition),
            # so blocking under it cannot deadlock — only queue writers.
            self.sock.sendall(frame)  # threadlint: disable=TL002 (leaf write lock; see comment)

    def register(self, req_id: int, p: _Pending) -> None:
        with self.plock:
            self.pending[req_id] = p

    def pop(self, req_id) -> _Pending | None:
        with self.plock:
            return self.pending.pop(req_id, None)

    def drain(self) -> list[_Pending]:
        with self.plock:
            out = list(self.pending.values())
            self.pending.clear()
        return out

    def abort(self) -> None:
        with self.wlock:
            if not self.alive:
                return
            self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class RemoteReplica:
    """A remote :class:`~.wire.WireServer` wrapped into the replica
    duck-type (module docstring).

    ``address`` is ``"host:port"`` or a ``(host, port)`` tuple;
    ``settings`` supplies the ``wire_*`` defaults when given. The
    constructor attempts one eager connection (non-fatal — an unreachable
    host starts broken and the reconnector takes over on first use).
    """

    #: the router forwards its minted trace context; it rides the
    #: envelope and the far server reconstructs it (obs v2 contract)
    accepts_trace = True

    def __init__(
        self,
        address,
        *,
        settings: dict | None = None,
        name: str | None = None,
        pool_size: int = 2,
        connect_timeout_ms: float | None = None,
        request_timeout_ms: float = 10_000.0,
        max_frame_bytes: int | None = None,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 1.0,
        retry_policy: RetryPolicy | None = None,
        eager_connect: bool = True,
    ):
        settings = settings or {}
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            self.host, self.port = host or "127.0.0.1", int(port)
        else:
            self.host, self.port = str(address[0]), int(address[1])
        self.name = name or f"remote:{self.host}:{self.port}"
        self.connect_timeout_s = (
            float(
                connect_timeout_ms
                if connect_timeout_ms is not None
                else settings.get("wire_connect_timeout_ms", 500.0) or 500.0
            )
            / 1000.0
        )
        self.request_timeout_ms = float(request_timeout_ms)
        self.max_frame_bytes = int(
            max_frame_bytes
            if max_frame_bytes is not None
            else settings.get("wire_max_frame_bytes", DEFAULT_MAX_FRAME_BYTES)
            or DEFAULT_MAX_FRAME_BYTES
        )
        self.pool_size = max(int(pool_size), 1)
        self.breaker = CircuitBreaker(
            threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
            name=self.name,
        )
        self.retry_policy = retry_policy or RetryPolicy(
            base_delay=0.05, max_delay=2.0
        )
        self._lock = lockwatch.new_lock("RemoteReplica._lock")
        self._conns: list[_RemoteConn] = []
        self._rr = 0
        self._req_ids = itertools.count(1)
        self._latencies: deque = deque(maxlen=_LATENCY_RESERVOIR)
        self._remote_health: str | None = None
        self._closed = False
        self._reconnecting = False
        self._growing = False
        self._down_since: float | None = None
        self._sweeper: threading.Thread | None = None
        self.served = 0
        self.sheds = 0
        self.reconnects = 0
        self._t_start = time.monotonic()
        # closes router-minted traces on this side of the wire (the far
        # server emits the span tree; this records the attempt outcome —
        # with stitching on, the remote span tree grafts into the close)
        from ..obs.reqtrace import ServeTracer

        self._tracer = ServeTracer(0.0, service=self.name)
        # -- fleet observability (PR 18) ---------------------------------
        # wire-overhead decomposition per response: serialize / network /
        # server-queue / server-execute / deserialize, fed into a
        # KernelWatch so the NETWORK phase gets the same two-window
        # regression alerting the serve kernels get. Host-side arithmetic
        # on stamps already taken; the wire hot path gains no sync.
        self._stitching = bool(settings.get("fleet_stitching", True))
        self._net_alert_ratio = float(
            settings.get("fleet_net_alert_ratio", 0.0) or 0.0
        )
        from ..obs.kernelwatch import KernelWatch

        self._netwatch = KernelWatch(
            window_s=30.0,
            alert_ratio=self._net_alert_ratio or 3.0,
        )
        self._net_alert_active = False
        self._last_net_eval = float("-inf")
        self._server_lat: deque = deque(maxlen=_LATENCY_RESERVOIR)
        self._network_lat: deque = deque(maxlen=_LATENCY_RESERVOIR)
        if eager_connect:
            try:
                self._add_conn(self._connect())
            except Exception as e:  # noqa: BLE001 - an unreachable host starts broken
                logger.warning(
                    "%s: eager connect failed (%s); starting broken",
                    self.name, e,
                )
                self.breaker.on_failure()
                self._note_down()
                self._kick_reconnector()

    # -- connection management ------------------------------------------

    def _handshake(self, sock: socket.socket, version: int) -> tuple:
        """One ``health`` exchange at ``version``; returns the reply
        envelope bracketed by monotonic send/receive stamps (the first
        clock-offset sample rides the handshake for free)."""
        t0 = time.monotonic()
        sock.sendall(
            encode_frame(
                {"v": version, "kind": "health", "id": 0},
                self.max_frame_bytes,
            )
        )
        env = read_frame(sock, self.max_frame_bytes)
        return env, t0, time.monotonic()

    def _connect(self) -> _RemoteConn:
        """Dial + liveness handshake: a socket only counts as connected
        after a ``health`` exchange round-trips — a partitioned host that
        accepts-then-drops fails here, not on the first real request.

        The handshake doubles as version negotiation: dial at v2; a v1
        server answers ``version_mismatch``, and the client re-handshakes
        at v1 on the same socket (the connection then carries no fleet
        fields — stitching and federation degrade to PR 16 behaviour)."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            env, t0, t1 = self._handshake(sock, WIRE_VERSION)
            peer_version = WIRE_VERSION
            if (
                env is not None
                and env.get("kind") == "error"
                and env.get("reason") == "version_mismatch"
            ):
                # a v1-only peer: downgrade on the same socket
                env, t0, t1 = self._handshake(sock, 1)
                peer_version = 1
            if env is None or env.get("v") not in (1, WIRE_VERSION):
                raise ConnectionError(
                    f"liveness handshake failed: {env!r}"
                )
            if env.get("kind") == "error":
                # the server answered the dial itself with a shed (e.g.
                # `server_overloaded` past wire_max_connections): the
                # socket is already dead, surface the reason verbatim
                raise ConnectionError(
                    "remote refused connection: "
                    f"{env.get('reason') or 'error'}"
                )
            peer_version = min(peer_version, int(env.get("v") or 1))
            self._remote_health = env.get("health") or self._remote_health
            sock.settimeout(None)
        except Exception:
            try:
                sock.close()
            except OSError:
                pass
            raise
        conn = _RemoteConn(sock, peer_version=peer_version)
        conn.note_offset(env.get("t_server"), t0, t1)
        conn.reader = threading.Thread(
            target=self._reader_loop,
            args=(conn,),
            name=f"{self.name}-reader",
            daemon=True,
        )
        conn.reader.start()
        return conn

    def _add_conn(self, conn: _RemoteConn) -> None:
        with self._lock:
            self._conns.append(conn)
            self._down_since = None

    def _live_conn(self) -> _RemoteConn | None:
        """Round-robin over the live pool. An empty pool dials ONE inline
        connection (bounded by the connect timeout — the cost of the first
        request after a cold start); a pool merely below ``pool_size``
        grows in the background so steady-state submits never block on a
        dial."""
        with self._lock:
            conns = [c for c in self._conns if c.alive]
            self._conns = conns
            self._rr += 1
            pick = conns[self._rr % len(conns)] if conns else None
            need_grow = bool(conns) and len(conns) < self.pool_size
        if pick is None:
            with self._lock:
                down_since = self._down_since
            try:
                fresh = self._connect()
            except Exception:  # noqa: BLE001 - dial failure -> caller sheds
                return None
            self._add_conn(fresh)
            if down_since is not None:
                # the inline dial raced ahead of the background
                # reconnector and re-admitted the host: that IS the
                # reconnect, record it as one
                self._note_reconnected(down_since, attempts=1)
            return fresh
        if need_grow:
            self._kick_pool_grow()
        return pick

    def _kick_pool_grow(self) -> None:
        with self._lock:
            if self._growing or self._closed:
                return
            self._growing = True

        def grow():
            try:
                conn = self._connect()
            except Exception:  # noqa: BLE001 - the pool stays small, submits still work
                return
            else:
                self._add_conn(conn)
            finally:
                with self._lock:
                    self._growing = False

        threading.Thread(
            target=grow, name=f"{self.name}-pool", daemon=True
        ).start()

    def _note_down(self) -> None:
        with self._lock:
            if self._down_since is None:
                self._down_since = time.monotonic()

    def _note_reconnected(
        self, down_since: float | None, attempts: int
    ) -> None:
        """Re-admission bookkeeping, whichever dial path got there first
        (the background reconnector or a submit's inline dial)."""
        with self._lock:
            self.reconnects += 1
        downtime = (
            time.monotonic() - down_since if down_since is not None else 0.0
        )
        # a completed handshake is a served request: it counts as the
        # breaker's recovery probe succeeding
        self.breaker.on_success()
        publish(
            "wire_reconnect",
            replica=self.name,
            address=f"{self.host}:{self.port}",
            attempts=attempts,
            downtime_s=round(downtime, 3),
        )
        logger.info(
            "%s: reconnected after %d attempt(s), %.0fms down",
            self.name, attempts, downtime * 1e3,
        )

    def _kick_reconnector(self) -> None:
        with self._lock:
            if self._reconnecting or self._closed:
                return
            self._reconnecting = True
        t = threading.Thread(
            target=self._reconnect_loop,
            name=f"{self.name}-reconnect",
            daemon=True,
        )
        t.start()

    def _reconnect_loop(self) -> None:
        """Background redial with RetryPolicy's bounded exponential
        backoff — unbounded attempts (a healed host must be re-admitted
        whenever it heals) but delays cap at ``max_delay``."""
        attempt = 0
        try:
            while True:
                with self._lock:
                    if self._closed or any(c.alive for c in self._conns):
                        return
                time.sleep(
                    self.retry_policy.delay(min(attempt, 16))
                )
                with self._lock:
                    if self._closed:
                        return
                    down_since = self._down_since
                try:
                    conn = self._connect()
                except Exception:  # noqa: BLE001 - keep backing off
                    attempt += 1
                    continue
                self._add_conn(conn)
                self._note_reconnected(down_since, attempts=attempt + 1)
                return
        finally:
            with self._lock:
                self._reconnecting = False

    def _conn_lost(self, conn: _RemoteConn, why: str) -> None:
        """A dead socket: shed every in-flight request on it (machine-
        readable, immediate — never a hung future), count the link
        failure, start reconnecting."""
        conn.abort()
        if not conn.mark_lost():
            return  # the other observer of this death already accounted it
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)
            closed = self._closed
            any_alive = any(c.alive for c in self._conns)
        pend = conn.drain()
        reason = "closed" if closed else "connection_lost"
        for p in pend:
            self._resolve_shed(p, reason)
        if closed:
            return
        if pend:
            publish(
                "wire_shed",
                replica=self.name,
                reason=reason,
                n=len(pend),
                why=why,
            )
        self.breaker.on_failure()
        if not any_alive:
            self._note_down()
            self._kick_reconnector()

    # -- reader ---------------------------------------------------------

    def _reader_loop(self, conn: _RemoteConn) -> None:
        why = "eof"
        try:
            while conn.alive:
                env = read_frame(conn.sock, self.max_frame_bytes)
                if env is None:
                    break
                self._on_frame(conn, env)
        except (WireError, ConnectionError, OSError) as e:
            why = f"{type(e).__name__}"
        self._conn_lost(conn, why)

    def _on_frame(self, conn: _RemoteConn, env: dict) -> None:
        self._remote_health = env.get("health") or self._remote_health
        req_id = env.get("id")
        p = conn.pop(req_id) if req_id is not None else None
        kind = env.get("kind")
        if env.get("v") not in (1, WIRE_VERSION):
            if p is not None:
                self._resolve_shed(p, "version_mismatch")
            return
        if kind == "result" and p is not None:
            t1 = time.monotonic()
            server_ms = env.get("server_ms")
            conn.note_offset(
                env.get("t_server"), p.t0, t1,
                server_s=(server_ms or 0.0) / 1e3,
            )
            if isinstance(p.fut, list):
                self._on_batch_result(conn, p, env, t1)
                return
            t_des = time.monotonic()
            res = QueryResult.from_payload(env.get("result") or {})
            deserialize_ms = (time.monotonic() - t_des) * 1e3
            rtt_ms = (t1 - p.t0) * 1e3
            wire_ms = self._decompose(
                p, rtt_ms, server_ms, deserialize_ms, res
            )
            with self._lock:
                self._latencies.append(rtt_ms)
                if server_ms is not None:
                    self._server_lat.append(float(server_ms))
                    self._network_lat.append(wire_ms["network"])
                if res.shed:
                    self.sheds += 1
                else:
                    self.served += 1
            # the LINK worked; a server-side shed is the far replica's
            # admission/breaker talking, not this link's failure
            self.breaker.on_success()
            self._net_tick()
            if res.shed:
                self._tracer.close(p.trace, "shed", reason=res.reason)
            else:
                span = env.get("span") if self._stitching else None
                if span is not None:
                    self._tracer.close(
                        p.trace, "delivered",
                        remote_span=self._graft(span, conn),
                        wire_ms=wire_ms,
                        clock_offset_s=conn.offset_s,
                    )
                else:
                    self._tracer.close(p.trace, "delivered")
            self._set_result(p.fut, res)
        elif kind in ("health", "latency", "stats", "flight") and p is not None:
            if kind == "flight":
                self._set_result(p.fut, {
                    "replica": env.get("replica"),
                    "records": env.get("records") or [],
                })
            else:
                self._set_result(p.fut, env.get("snapshot") or {})
        elif kind == "error":
            if p is not None:
                self._resolve_shed(
                    p, str(env.get("reason") or "remote_error")
                )
        # responses for ids already swept (deadline/timeout) are dropped

    def _on_batch_result(
        self, conn: _RemoteConn, p: _Pending, env: dict, t1: float
    ) -> None:
        """Resolve one batched reply frame: ``results`` is positional
        against the futures list registered by :meth:`submit_many`; a
        short or missing list sheds the tail (``remote_error``) so every
        future still resolves."""
        payloads = env.get("results") or []
        rtt_ms = (t1 - p.t0) * 1e3
        served = shed = 0
        for i, fut in enumerate(p.fut):
            if i < len(payloads):
                res = QueryResult.from_payload(payloads[i] or {})
            else:
                res = QueryResult(shed=True, reason="remote_error")
            if res.shed:
                shed += 1
            else:
                served += 1
            self._set_result(fut, res)
        with self._lock:
            self._latencies.append(rtt_ms)
            self.served += served
            self.sheds += shed
        self.breaker.on_success()

    # -- wire-overhead decomposition (fleet observability) --------------

    def _decompose(
        self, p: _Pending, rtt_ms: float, server_ms,
        deserialize_ms: float, res: QueryResult,
    ) -> dict:
        """Split one round trip into serialize / network / server-queue /
        server-execute / deserialize (ms) and feed the netwatch. With a
        v1 peer (no ``server_ms``) everything between serialize and
        deserialize is attributed to ``network`` — the honest answer when
        the far side declines to decompose itself."""
        serialize_ms = (
            (p.t_sent - p.t0) * 1e3 if p.t_sent is not None else 0.0
        )
        srv = float(server_ms) if server_ms is not None else 0.0
        network_ms = max(rtt_ms - serialize_ms - srv - deserialize_ms, 0.0)
        out = {
            "serialize": round(serialize_ms, 4),
            "network": round(network_ms, 4),
            "server": round(srv, 4),
            "deserialize": round(deserialize_ms, 4),
        }
        if res.queue_ms is not None:
            out["server_queue"] = round(float(res.queue_ms), 4)
        if res.execute_ms is not None:
            out["server_execute"] = round(float(res.execute_ms), 4)
        w = self._netwatch
        w.observe("serialize", serialize_ms / 1e3)
        w.observe("network", network_ms / 1e3)
        w.observe("deserialize", deserialize_ms / 1e3)
        if server_ms is not None:
            if res.queue_ms is not None:
                w.observe("server_queue", float(res.queue_ms) / 1e3)
            if res.execute_ms is not None:
                w.observe("server_execute", float(res.execute_ms) / 1e3)
        return out

    def _graft(self, span: dict, conn: _RemoteConn) -> dict:
        """Rebase the remote span tree onto this host's clock using the
        connection's midpoint offset estimate, so the stitched waterfall
        renders on one time axis. The raw remote ``t0`` survives as
        ``t0_remote`` for audit."""
        out = dict(span)
        offset = conn.offset_s
        if offset is not None and span.get("t0") is not None:
            try:
                out["t0_remote"] = float(span["t0"])
                out["t0"] = float(span["t0"]) - offset
            except (TypeError, ValueError):
                pass
        return out

    def _net_tick(self) -> None:
        """Edge-triggered two-window alerting on the NETWORK phase of the
        wire decomposition (same shape as the service's ``perf_alert``):
        rate-limited evaluation, level-triggered state, events only on
        the edges. Off unless ``fleet_net_alert_ratio`` > 0."""
        if self._net_alert_ratio <= 0:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_net_eval < 1.0:
                return
            self._last_net_eval = now
            was_active = self._net_alert_active
        fired = [
            a for a in self._netwatch.alerts() if a["phase"] == "network"
        ]
        if fired and not was_active:
            with self._lock:
                self._net_alert_active = True
            publish(
                "fleet_net_alert",
                replica=self.name,
                address=f"{self.host}:{self.port}",
                alerts=fired,
            )
            logger.warning(
                "%s: network-phase latency regression: %s",
                self.name, fired,
            )
        elif not fired and was_active:
            with self._lock:
                self._net_alert_active = False
            publish("fleet_net_clear", replica=self.name)

    # -- shed plumbing --------------------------------------------------

    def _resolve_shed(self, p: _Pending, reason: str) -> None:
        futs = p.fut if isinstance(p.fut, list) else [p.fut]
        with self._lock:
            self.sheds += len(futs)
        self._tracer.close(p.trace, "shed", reason=reason)
        res = QueryResult(shed=True, reason=reason)
        for fut in futs:
            self._set_result(fut, res)

    @staticmethod
    def _set_result(fut: Future, value) -> None:
        try:
            fut.set_result(value)
        except InvalidStateError:  # lost a sweep/response race
            pass

    def _shed_now(self, reason: str, trace=None) -> Future:
        fut: Future = Future()
        with self._lock:
            self.sheds += 1
        self._tracer.close(trace, "shed", reason=reason)
        fut.set_result(QueryResult(shed=True, reason=reason))
        return fut

    # -- sweeper --------------------------------------------------------

    def _ensure_sweeper(self) -> None:
        with self._lock:
            if self._closed or (
                self._sweeper is not None and self._sweeper.is_alive()
            ):
                return
            self._sweeper = threading.Thread(
                target=self._sweep_loop,
                name=f"{self.name}-sweeper",
                daemon=True,
            )
            self._sweeper.start()

    def _sweep_loop(self) -> None:
        """Client-side guarantee that no future outlives its budget: an
        expired deadline sheds as ``deadline`` (the caller already
        abandoned it — a late server answer is dropped on arrival), and
        ``request_timeout_ms`` bounds deadline-less requests (``timeout``)
        so a wedged-but-connected server cannot hang the router."""
        while True:
            time.sleep(_SWEEP_INTERVAL_S)
            with self._lock:
                if self._closed:
                    return
                conns = list(self._conns)
            now = time.monotonic()
            for conn in conns:
                expired = []
                with conn.plock:
                    for rid, p in list(conn.pending.items()):
                        if p.deadline is not None and now > p.deadline:
                            expired.append((rid, p, "deadline"))
                        elif p.timeout_at is not None and now > p.timeout_at:
                            expired.append((rid, p, "timeout"))
                    for rid, _, _ in expired:
                        conn.pending.pop(rid, None)
                for _, p, reason in expired:
                    self._resolve_shed(p, reason)

    # -- the replica duck-type ------------------------------------------

    def submit(
        self,
        record: dict,
        deadline_ms: float | None = None,
        trace=None,
    ) -> Future:
        """Enqueue one query on the remote host; never raises, always
        resolves (module docstring for the shed taxonomy)."""
        with self._lock:
            closed = self._closed
        if closed:
            return self._shed_now("closed", trace)
        if deadline_ms is not None and deadline_ms <= 0:
            return self._shed_now("deadline", trace)
        if self.breaker.should_fail_fast():
            return self._shed_now("breaker_open", trace)
        conn = self._live_conn()
        if conn is None:
            self.breaker.on_failure()
            self._note_down()
            self._kick_reconnector()
            return self._shed_now("remote_unreachable", trace)
        self._ensure_sweeper()
        fut: Future = Future()
        req_id = next(self._req_ids)
        now = time.monotonic()
        p = _Pending(
            fut,
            trace,
            deadline=(
                None if deadline_ms is None else now + deadline_ms / 1000.0
            ),
            timeout_at=(
                now + self.request_timeout_ms / 1000.0
                if self.request_timeout_ms
                else None
            ),
        )
        env = {
            "v": conn.peer_version,
            "kind": "query",
            "id": req_id,
            "record": record,
            "deadline_ms": deadline_ms,
        }
        if trace is not None:
            env["trace"] = {
                "trace_id": trace.trace_id,
                "attempt": trace.attempt,
                "hedge": trace.hedge,
            }
        conn.register(req_id, p)
        try:
            conn.send(encode_frame(env, self.max_frame_bytes))
            p.t_sent = time.monotonic()
        except (WireError, OSError) as e:
            logger.warning("%s: send failed: %s", self.name, e)
            self._conn_lost(conn, f"send:{type(e).__name__}")
            # _conn_lost drains and sheds what was registered at drain
            # time; if this request registered after that drain (send vs
            # reader-death race) it must still resolve — pop is the
            # idempotence guard, a double resolve is impossible
            if conn.pop(req_id) is not None:
                self._resolve_shed(p, "connection_lost")
        return fut

    def submit_many(
        self,
        records: list,
        deadline_ms: float | None = None,
    ) -> list[Future]:
        """Enqueue N queries as ONE wire frame (v2 batched envelope): one
        serialize, one network round trip, one reply carrying positional
        results. Returns one future per record, each with the full
        never-raises / always-resolves contract of :meth:`submit`. A v1
        peer gets a per-record :meth:`submit` loop — same futures, no
        frame savings."""
        records = list(records)
        if not records:
            return []
        with self._lock:
            closed = self._closed
        if closed:
            return [self._shed_now("closed") for _ in records]
        if deadline_ms is not None and deadline_ms <= 0:
            return [self._shed_now("deadline") for _ in records]
        if self.breaker.should_fail_fast():
            return [self._shed_now("breaker_open") for _ in records]
        conn = self._live_conn()
        if conn is None:
            self.breaker.on_failure()
            self._note_down()
            self._kick_reconnector()
            return [self._shed_now("remote_unreachable") for _ in records]
        if conn.peer_version < 2:
            return [
                self.submit(r, deadline_ms=deadline_ms) for r in records
            ]
        self._ensure_sweeper()
        futs: list[Future] = [Future() for _ in records]
        req_id = next(self._req_ids)
        now = time.monotonic()
        p = _Pending(
            futs,
            None,
            deadline=(
                None if deadline_ms is None else now + deadline_ms / 1000.0
            ),
            timeout_at=(
                now + self.request_timeout_ms / 1000.0
                if self.request_timeout_ms
                else None
            ),
        )
        env = {
            "v": conn.peer_version,
            "kind": "query",
            "id": req_id,
            "records": records,
            "deadline_ms": deadline_ms,
        }
        conn.register(req_id, p)
        try:
            conn.send(encode_frame(env, self.max_frame_bytes))
            p.t_sent = time.monotonic()
        except (WireError, OSError) as e:
            logger.warning("%s: batched send failed: %s", self.name, e)
            self._conn_lost(conn, f"send:{type(e).__name__}")
            if conn.pop(req_id) is not None:
                self._resolve_shed(p, "connection_lost")
        return futs

    # -- fleet RPC helpers ----------------------------------------------

    def _rpc(self, kind: str, timeout_s: float = 1.5):
        """One v2 request/response exchange off the hot path (stats /
        flight_pull). None when unreachable or when the peer negotiated
        v1 (a v1 server answers these kinds with ``bad_kind``)."""
        with self._lock:
            conns = [c for c in self._conns if c.alive]
        conn = conns[0] if conns else self._live_conn()
        if conn is None or conn.peer_version < 2:
            return None
        fut: Future = Future()
        req_id = next(self._req_ids)
        conn.register(
            req_id,
            _Pending(fut, None, deadline=None,
                     timeout_at=time.monotonic() + timeout_s),
        )
        self._ensure_sweeper()
        try:
            conn.send(
                encode_frame(
                    {"v": conn.peer_version, "kind": kind, "id": req_id},
                    self.max_frame_bytes,
                )
            )
            out = fut.result(timeout=timeout_s + 0.5)
        except Exception as e:  # noqa: BLE001 - fleet pulls must not raise into the aggregator
            logger.warning("%s: %s pull failed: %s", self.name, kind, e)
            return None
        if isinstance(out, QueryResult):  # swept into a shed
            return None
        return out

    def fetch_stats(self) -> dict | None:
        """Pull the remote's federated-metrics snapshot
        (:meth:`~.service.LinkageService.fleet_stats` over the wire).
        None when the peer is v1 or unreachable."""
        return self._rpc("stats")

    def pull_flight(self) -> dict | None:
        """Pull the remote's flight-recorder ring for an incident bundle:
        ``{"replica": name, "records": [...]}`` or None (v1 peer /
        unreachable / no recorder on the far side)."""
        return self._rpc("flight_pull", timeout_s=3.0)

    @property
    def peer_version(self) -> int | None:
        """The negotiated wire version of the first live connection, or
        None while disconnected."""
        with self._lock:
            for c in self._conns:
                if c.alive:
                    return c.peer_version
        return None

    @property
    def health_state(self) -> str:
        """The worse of the remote's piggybacked self-assessment and the
        local link view: an open breaker or an empty pool means the host
        is unreachable from here, which is what broken means to a router
        (:func:`~.health.worse`)."""
        with self._lock:
            any_alive = any(c.alive for c in self._conns)
            closed = self._closed
        link = (
            BROKEN
            if closed or self.breaker.state == "open" or not any_alive
            else HEALTHY
        )
        return worse(self._remote_health or HEALTHY, link)

    def health(self) -> dict:
        """A live round-trip health snapshot from the remote (falls back
        to the local link view when the wire is down)."""
        with self._lock:
            n_conns = len(self._conns)
            reconnects = self.reconnects
            conns = [c for c in self._conns if c.alive]
        local = {
            "replica": self.name,
            "state": self.health_state,
            "link": {
                "breaker": self.breaker.snapshot(),
                "connections": n_conns,
                "reconnects": reconnects,
            },
        }
        if not conns:
            return local
        fut: Future = Future()
        req_id = next(self._req_ids)
        conn = conns[0]
        conn.register(
            req_id,
            _Pending(fut, None, deadline=None,
                     timeout_at=time.monotonic() + 1.0),
        )
        self._ensure_sweeper()
        try:
            conn.send(
                encode_frame(
                    {"v": WIRE_VERSION, "kind": "health", "id": req_id},
                    self.max_frame_bytes,
                )
            )
            snap = fut.result(timeout=1.5)
        except Exception as e:  # noqa: BLE001 - health must answer even when the wire cannot
            local["error"] = str(e)[:200]
            return local
        if isinstance(snap, QueryResult):  # swept into a shed
            local["error"] = snap.reason
            return local
        snap = dict(snap)
        snap["link"] = local["link"]
        return snap

    def latency_summary(self) -> dict:
        """Round-trip latency percentiles measured from THIS side of the
        wire (what the router's p95 hedging should key on — it includes
        the network), plus the link counters. With a v2 peer the round
        trip also splits into network-vs-server time (``server_ms``
        rides every result envelope), so "the remote is slow" and "the
        path to the remote is slow" stop being the same symptom."""
        with self._lock:
            lats = sorted(self._latencies)
            srv = sorted(self._server_lat)
            net = sorted(self._network_lat)
            served, sheds = self.served, self.sheds
            reconnects = self.reconnects
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        out = {
            "replica": self.name,
            "served": served,
            "shed": sheds,
            "queries_per_sec": served / elapsed,
            "reconnects": reconnects,
            "breaker_state": self.breaker.state,
            "health": self.health_state,
        }

        def _q(vals, p):
            return vals[min(int(p * len(vals)), len(vals) - 1)]

        if lats:
            out.update(
                p50_ms=_q(lats, 0.50), p95_ms=_q(lats, 0.95),
                p99_ms=_q(lats, 0.99), mean_ms=sum(lats) / len(lats),
            )
        if srv:
            out["server"] = {
                "p50_ms": _q(srv, 0.50), "p95_ms": _q(srv, 0.95),
                "mean_ms": sum(srv) / len(srv), "n": len(srv),
            }
        if net:
            out["network"] = {
                "p50_ms": _q(net, 0.50), "p95_ms": _q(net, 0.95),
                "mean_ms": sum(net) / len(net), "n": len(net),
            }
        return out

    def wire_phases(self) -> dict:
        """Rolling stats for the wire-overhead phases (serialize /
        network / server_queue / server_execute / deserialize) the
        netwatch accumulates — the per-remote per-hop attribution the
        fleet dashboard and ``bench.py fleet`` render."""
        return {
            p: self._netwatch.phase_stats(p)
            for p in self._netwatch.phases()
        }

    def prometheus_samples(self) -> list:
        from ..obs.exposition import Sample

        labels = {"replica": self.name}
        s = self.latency_summary()
        out = [
            Sample("splink_remote_served_total", s["served"], labels,
                   "counter", "Remote requests delivered over the wire"),
            Sample("splink_remote_shed_total", s["shed"], labels,
                   "counter", "Remote requests shed (link + server)"),
            Sample("splink_remote_reconnects_total", s["reconnects"],
                   labels, "counter", "Background reconnects completed"),
            Sample("splink_remote_health_rank",
                   health_rank(self.health_state), labels, "gauge",
                   "0 healthy / 1 degraded / 2 broken"),
        ]
        for side in ("server", "network"):
            split = s.get(side)
            if split:
                out.append(
                    Sample(
                        f"splink_remote_{side}_p95_ms",
                        round(split["p95_ms"], 4), labels, "gauge",
                        f"p95 {side}-attributed ms of the remote round trip",
                    )
                )
        return out

    def close(self) -> None:
        """Stop threads, close the pool, resolve anything in flight as a
        ``closed`` shed. Idempotent; never touches the remote server."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._conns)
            self._conns = []
        for conn in conns:
            pend = conn.drain()
            conn.abort()
            for p in pend:
                self._resolve_shed(p, "closed")
