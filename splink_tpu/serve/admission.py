"""Admission control for the serving tier: deadlines, brown-out, breaker.

PR 5's admission control was binary — a bounded queue that sheds when
full. Production overload is rarely binary: the queue is *filling*, batch
service time is *drifting*, and requests carry their own latency budgets.
This module adds the three graduated mechanisms the service threads
through its submit/dispatch path:

* :class:`WaitEstimator` — an EWMA model of batch service time that turns
  "how many requests are ahead of me" into an estimated queue wait, so a
  request whose deadline cannot be met is rejected AT ADMISSION (cheap,
  immediate, honest) instead of timing out after consuming queue space.
* Brown-out (:func:`brownout_active`) — the tier between full service and
  shedding. Under pressure the service keeps answering, but through the
  engine's budgeted brown-out kernel: a reduced candidate capacity and a
  smaller top-k (Progressive-Blocking-style "serve the best candidates a
  budget allows" — Pan et al.), with results tagged ``degraded=True``.
* :class:`CircuitBreaker` — after N consecutive engine batch failures the
  breaker OPENS and requests fail fast as shed (no queue time wasted on a
  broken engine) while probes test recovery: the first batch after the
  cooldown — or the watchdog's synthetic engine probe when there is no
  traffic — runs half-open, and its outcome closes or re-opens the
  breaker.

Everything here is host-side bookkeeping on the request path; nothing
touches the jax dataflow.
"""

from __future__ import annotations

import math
import threading
import time

from ..analysis import lockwatch

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure circuit breaker around the engine dispatch.

    ``threshold`` consecutive failures open the breaker; while open,
    :meth:`should_fail_fast` is True until ``cooldown_s`` has elapsed, at
    which point the next caller runs HALF-OPEN (one probe in flight) and
    its ``on_success``/``on_failure`` closes or re-opens the breaker.
    Thread-safe: the worker, the watchdog probe and ``health()`` all read
    it."""

    def __init__(
        self, threshold: int = 3, cooldown_s: float = 1.0, name: str = ""
    ):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        # which breaker this is (the wire tier runs one per remote next
        # to the engine's own; snapshots must say whose state they are)
        self.name = str(name)
        self._lock = lockwatch.new_lock("CircuitBreaker._lock")
        self._state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self.opened_total = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def should_fail_fast(self) -> bool:
        """True while open and cooling down. After the cooldown the caller
        is admitted as the half-open probe (returns False exactly once per
        cooldown window; a failed probe restarts the window)."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return False
            if self._state == BREAKER_OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown_s:
                    self._state = BREAKER_HALF_OPEN
                    return False
                return True
            return False  # half-open: the probe (and its coalesced batch)

    def probe_due(self) -> bool:
        """True when open with the cooldown elapsed — the watchdog uses
        this to run a synthetic probe when no traffic is arriving."""
        with self._lock:
            return (
                self._state == BREAKER_OPEN
                and time.monotonic() - self._opened_at >= self.cooldown_s
            )

    def on_success(self) -> bool:
        """Record a successful dispatch; returns True when this CLOSED a
        previously open/half-open breaker (caller emits the event)."""
        with self._lock:
            recovered = self._state != BREAKER_CLOSED
            self._state = BREAKER_CLOSED
            self._failures = 0
            return recovered

    def on_failure(self) -> bool:
        """Record a failed dispatch; returns True when this OPENED the
        breaker (threshold reached, or a half-open probe failed)."""
        with self._lock:
            self._failures += 1
            if self._state == BREAKER_HALF_OPEN or (
                self._state == BREAKER_CLOSED
                and self._failures >= self.threshold
            ):
                self._state = BREAKER_OPEN
                self._opened_at = time.monotonic()
                self.opened_total += 1
                return True
            if self._state == BREAKER_OPEN:
                self._opened_at = time.monotonic()
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                **({"name": self.name} if self.name else {}),
                "state": self._state,
                "consecutive_failures": self._failures,
                "opened_total": self.opened_total,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
            }


class WaitEstimator:
    """EWMA batch-service-time model -> estimated queue wait.

    ``observe(ms)`` feeds each served batch's wall time; ``estimate_wait_ms``
    answers "if I enqueue now behind ``queued`` requests, how long until
    MY batch completes": the coalescing deadline (the batcher always waits
    it out under load) plus one EWMA batch time per full batch ahead of —
    and including — this request. Batch size is deliberately not part of
    the model: bucketed dispatch pads every batch to a shape-menu bucket,
    so cost per batch is dominated by the bucket, not the occupancy.
    Before any observation the prior is deliberately modest (one
    coalescing window); admission must not reject the first requests of a
    cold service on a made-up number."""

    def __init__(self, alpha: float = 0.3, prior_ms: float = 0.0):
        self.alpha = float(alpha)
        self._lock = lockwatch.new_lock("WaitEstimator._lock")
        self._batch_ms = float(prior_ms)
        self._observed = prior_ms > 0

    def observe(self, batch_ms: float) -> None:
        with self._lock:
            if not self._observed:
                self._batch_ms = float(batch_ms)
                self._observed = True
            else:
                self._batch_ms += self.alpha * (batch_ms - self._batch_ms)

    @property
    def batch_ms(self) -> float:
        with self._lock:
            return self._batch_ms

    def estimate_wait_ms(
        self, queued: int, max_batch: int, coalesce_ms: float,
        inflight_batches: int = 0,
    ) -> float:
        """``inflight_batches`` counts batches already dispatched but not
        yet finished — a request admitted behind one waits it out before
        its own queue position even starts moving."""
        batches = math.ceil((queued + 1) / max(max_batch, 1))
        return coalesce_ms + (batches + inflight_batches) * self.batch_ms


def brownout_active(
    queue_fill: float, health_state: str, *, enabled: bool,
    fill_threshold: float = 0.5,
) -> bool:
    """The brown-out tier engages when enabled AND pressure is visible:
    the queue is past ``fill_threshold`` or the replica's health has
    already left ``healthy``. (Broken replicas still brown-out rather
    than upgrade: the breaker/shed paths decide what broken means.)"""
    if not enabled:
        return False
    if queue_fill >= fill_threshold:
        return True
    from .health import HEALTHY

    return health_state != HEALTHY
