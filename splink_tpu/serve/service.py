"""Micro-batching front-end: single queries in, coalesced device batches out.

Accelerators amortise dispatch over batches; online traffic arrives one
record at a time. :class:`LinkageService` bridges the two with the classic
micro-batching loop: ``submit`` enqueues a record and returns a future, a
worker thread coalesces everything queued within ``deadline_ms`` of the
FIRST waiting record (or until a full largest query bucket accumulates,
whichever comes first) into one engine dispatch, and each future resolves
with its record's matches.

Resilience is graduated, not binary (serve/admission.py, serve/health.py):

* **Admission control** — the bounded queue still SHEDS instead of OOMing
  when ``queue_depth`` records wait, and a request carrying its own
  ``deadline_ms`` is rejected AT ADMISSION when the estimated queue wait
  (EWMA batch-time model) cannot meet it; queued requests whose deadline
  lapses before dispatch are shed at the batcher, never scored late.
* **Brown-out** — between full service and shedding sits the budgeted
  tier: under pressure (queue past ``brownout_fill``, or health already
  degraded) batches run the engine's brown-out program — reduced top-k
  over the cheapest candidate bucket — and results are tagged
  ``degraded=True``. Enabled by ``serve_brownout_top_k`` > 0.
* **Circuit breaker** — ``serve_breaker_threshold`` consecutive batch
  failures open the breaker: requests fail fast as shed (reason
  ``breaker_open``) instead of queueing behind a broken engine, while the
  first post-cooldown batch — or the watchdog's synthetic engine probe
  when traffic has stopped — tests recovery.
* **Watchdog** — a supervisor thread that detects a dead worker, resolves
  its orphaned futures shed (a crashed worker previously hung every
  outstanding future forever), restarts the thread, runs breaker recovery
  probes, and drives the per-replica health state machine
  (:class:`~.health.HealthMonitor`) from live signals: queue fill, shed
  rate, recent p95, compile stalls, breaker state.

Nothing raises on the submit path, no exception ever escapes to a caller
through a future, and every degradation flows through the structured
channel (``logging_utils.warn_degraded`` + ambient obs events) — overload
and faults are measured, observable states rather than crashes.
``scripts/chaos_smoke.py`` (`make chaos-smoke`) drives every registered
serve fault site against these guarantees.

Per-request latency (enqueue -> result set) feeds a bounded reservoir;
:meth:`latency_summary` reports p50/p95/p99 and throughput, and with a
telemetry ``RunContext`` the summary lands in the run record (``python -m
splink_tpu.obs summarize``) alongside per-batch ``serve_batch`` spans.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FuturesTimeout
from dataclasses import dataclass, field

import numpy as np

from ..resilience.faults import active_plan
from ..utils.logging_utils import warn_degraded
from .admission import CircuitBreaker, WaitEstimator, brownout_active
from .health import HealthMonitor

logger = logging.getLogger("splink_tpu")

_LATENCY_RESERVOIR = 65536  # newest-N latency samples kept for percentiles
_RECENT_WINDOW = 512  # newest-N samples for the health monitor's p95


@dataclass
class QueryResult:
    """One query's outcome.

    ``shed`` requests carry a machine-readable ``reason``:
    ``queue_full`` / ``deadline`` / ``timeout`` / ``breaker_open`` /
    ``batch_error`` / ``worker_restart`` / ``closed``. ``degraded`` marks
    a brown-out answer (served under a reduced candidate/top-k budget)."""

    matches: list = field(default_factory=list)  # [(ref_uid, probability)]
    n_candidates: int = 0
    shed: bool = False
    latency_ms: float | None = None
    degraded: bool = False
    reason: str | None = None


class LinkageService:
    """Micro-batching query front-end over a :class:`~.engine.QueryEngine`
    (module docstring)."""

    def __init__(
        self,
        engine,
        *,
        queue_depth: int | None = None,
        deadline_ms: float | None = None,
        autostart: bool = True,
        telemetry=None,
        name: str = "serve",
        breaker_threshold: int | None = None,
        breaker_cooldown_s: float = 1.0,
        brownout_fill: float = 0.5,
        watchdog_interval_s: float = 0.1,
        compile_stall_s: float = 0.25,
        probe_queries: int | None = None,
        health_monitor: HealthMonitor | None = None,
    ):
        settings = engine.index.settings
        self.engine = engine
        self.name = name
        self.queue_depth = int(
            queue_depth
            if queue_depth is not None
            else settings.get("serve_queue_depth", 1024) or 1024
        )
        self.deadline_ms = float(
            deadline_ms
            if deadline_ms is not None
            else settings.get("serve_deadline_ms", 5.0)
        )
        self.breaker = CircuitBreaker(
            threshold=int(
                breaker_threshold
                if breaker_threshold is not None
                else settings.get("serve_breaker_threshold", 3) or 3
            ),
            cooldown_s=breaker_cooldown_s,
        )
        self.brownout_fill = float(brownout_fill)
        self.brownout_enabled = engine.brownout_top_k > 0
        self.watchdog_interval_s = float(watchdog_interval_s)
        self.compile_stall_s = float(compile_stall_s)
        self._probe_queries = int(
            probe_queries
            if probe_queries is not None
            else settings.get("serve_probe_queries", 16) or 0
        )
        self._settings = settings
        self._obs = telemetry
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queue: deque = deque()  # (record, future, t_enqueue, deadline)
        self._inflight: list = []  # entries popped by the worker, unresolved
        self._probe_buffer: list = []  # records accumulating toward capture
        self._latencies: deque = deque(maxlen=_LATENCY_RESERVOIR)
        self._recent_lat: deque = deque(maxlen=_RECENT_WINDOW)
        self._admission = WaitEstimator()
        self._health = health_monitor or HealthMonitor(name=name)
        self._shed_count = 0
        self._served = 0
        self._batches = 0
        self._timeouts = 0
        self._degraded_served = 0
        self._brownout_episodes = 0
        self._worker_crashes = 0
        self._brownout_active = False
        self._take_fill = 0.0
        self._swap_in_progress = False
        self._summary_recorded = False
        self._t_start = time.monotonic()
        self._stop = False
        self._thread: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        self._watchdog_stop = threading.Event()
        # health-window marks (consumed by _health_signals deltas; the
        # watchdog and on-demand health() calls share them, so updates go
        # through _signals_lock)
        self._signals_lock = threading.Lock()
        self._hw_served = 0
        self._hw_shed = 0
        self._stall_accum = 0.0
        self._last_health_eval = float("-inf")
        from ..obs.metrics import compile_totals

        self._last_compile_s = compile_totals()[1]
        if autostart:
            self.start()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "LinkageService":
        """Start (or restart after :meth:`close`) the worker + watchdog."""
        with self._nonempty:
            if self._thread is None:
                self._stop = False
                self._summary_recorded = False  # a reopen closes again later
                self._thread = threading.Thread(
                    target=self._worker, name="splink-serve", daemon=True
                )
                self._thread.start()
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog_stop = threading.Event()
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="splink-serve-watchdog",
                daemon=True,
            )
            self._watchdog.start()
        return self

    def close(self, drain: bool = True) -> None:
        """Stop the worker and watchdog. With ``drain`` (default) queued
        requests are served first; otherwise they resolve shed. Idempotent
        — a second close is a no-op and never hangs a future."""
        self._watchdog_stop.set()
        watchdog = self._watchdog
        if watchdog is not None and watchdog is not threading.current_thread():
            watchdog.join(timeout=10)
        self._watchdog = None
        to_shed: list = []
        with self._nonempty:
            self._stop = True
            if not drain:
                while self._queue:
                    to_shed.append(self._queue.popleft())
            self._nonempty.notify_all()
        for entry in to_shed:
            self._resolve_shed(entry[1], "closed")
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # a submit racing the shutdown can enqueue after the worker's last
        # batch — and a worker that DIED mid-batch leaves in-flight entries
        # — resolve all stragglers shed so no future hangs forever
        with self._nonempty:
            stragglers = list(self._queue) + self._inflight
            self._queue.clear()
            self._inflight = []
        for entry in stragglers:
            self._resolve_shed(entry[1], "closed")
        if self._obs is not None and not self._summary_recorded:
            # once per lifetime: close() is idempotent and must not emit
            # duplicate serve_latency records on repeated calls
            self._summary_recorded = True
            self._obs.record("serve_latency", self.latency_summary())

    def __enter__(self) -> "LinkageService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission -----------------------------------------------------

    def submit(self, record: dict, deadline_ms: float | None = None) -> Future:
        """Enqueue one query record; never raises. Sheds immediately
        (future resolves ``shed=True`` + degradation event) when the
        service is closed, the bounded queue is full, or ``deadline_ms``
        is given and the estimated queue wait already exceeds it
        (reject-early admission, module docstring). A queued request's
        ``deadline_ms`` also rides into the batcher: lapsed requests are
        shed at dispatch, never scored late."""
        fut: Future = Future()
        reason = None
        with self._nonempty:
            closed = self._stop and self._thread is None
            if closed:
                reason = "closed"
                reason_text = "service is closed; submissions resolve shed"
            elif len(self._queue) >= self.queue_depth:
                reason = "queue_full"
                reason_text = (
                    f"bounded queue full ({self.queue_depth} waiting); "
                    "shedding instead of growing without bound"
                )
            elif deadline_ms is not None:
                est = self._admission.estimate_wait_ms(
                    len(self._queue),
                    self.engine.policy.max_batch,
                    self.deadline_ms,
                    inflight_batches=1 if self._inflight else 0,
                )
                if est > deadline_ms:
                    reason = "deadline"
                    reason_text = (
                        f"estimated queue wait {est:.1f}ms exceeds the "
                        f"request deadline {deadline_ms:.1f}ms; rejected at "
                        "admission instead of timing out in the queue"
                    )
            if reason is not None:
                self._shed_count += 1
                shed_total = self._shed_count
                fut.set_result(QueryResult(shed=True, reason=reason))
            else:
                deadline = (
                    None
                    if deadline_ms is None
                    else time.monotonic() + deadline_ms / 1000.0
                )
                self._queue.append((record, fut, time.monotonic(), deadline))
                self._nonempty.notify()
                return fut
        # outside the lock: warn_degraded publishes + warns, both of which
        # may run user hooks
        warn_degraded(
            "serve_admission" if reason == "deadline" else "serve_queue",
            "shed",
            reason_text,
            shed_total=shed_total,
        )
        return fut

    def query(
        self,
        record: dict,
        timeout: float | None = None,
        deadline_ms: float | None = None,
    ) -> QueryResult:
        """Submit one record and wait for its result. A ``timeout`` that
        expires CANCELS the request: it is removed from the queue (a
        timed-out request used to stay queued and get scored anyway),
        counted shed (reason ``timeout``) and the degradation event is
        emitted — unless its real result won the race, which is returned."""
        fut = self.submit(record, deadline_ms=deadline_ms)
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeout:
            return self._cancel_timed_out(fut, timeout)

    def _cancel_timed_out(self, fut: Future, timeout) -> QueryResult:
        with self._nonempty:
            for i, entry in enumerate(self._queue):
                if entry[1] is fut:
                    del self._queue[i]
                    break
        res = QueryResult(shed=True, reason="timeout")
        won = False
        if not fut.done():
            try:
                fut.set_result(res)
                won = True
            except InvalidStateError:  # the worker resolved it first
                pass
        if not won:
            return fut.result(timeout=0)
        with self._lock:
            self._shed_count += 1
            self._timeouts += 1
        warn_degraded(
            "serve_timeout",
            "shed",
            f"request result not ready within its {timeout}s timeout; "
            "cancelled (dequeued) and counted shed",
            timeout_s=timeout,
        )
        return res

    # -- worker ---------------------------------------------------------

    def _worker(self) -> None:
        try:
            while True:
                # fault site OUTSIDE the batch try-block: a raise here
                # kills the worker thread — the failure mode the watchdog
                # recovers from (resilience/faults.py SERVE_SITES)
                active_plan(self._settings).fire(
                    "serve_worker", batch=self._batches
                )
                batch = self._take_batch()
                if batch is None:
                    return
                self._serve_batch(batch)
        except Exception:  # noqa: BLE001 - a dying worker must not spam stderr
            logger.exception(
                "serve worker thread died; the watchdog will shed its "
                "orphaned requests and restart it"
            )

    def _take_batch(self):
        """Block until work exists, then coalesce until the deadline (from
        the FIRST waiting record) or a full largest bucket. The taken
        entries are tracked as in-flight so a worker death cannot orphan
        them past the watchdog."""
        max_batch = self.engine.policy.max_batch
        with self._nonempty:
            while not self._queue:
                if self._stop:
                    return None
                self._nonempty.wait(timeout=0.1)
            deadline = self._queue[0][2] + self.deadline_ms / 1000.0
            while len(self._queue) < max_batch and not self._stop:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(timeout=remaining)
            # pressure is measured BEFORE the take: a large coalesced batch
            # drains the queue, which must not hide the pressure it is
            # itself the evidence of (the brown-out decision reads this)
            self._take_fill = len(self._queue) / self.queue_depth
            take = min(len(self._queue), max_batch)
            batch = [self._queue.popleft() for _ in range(take)]
            self._inflight = batch
            return batch

    def _clear_inflight(self) -> None:
        with self._lock:
            self._inflight = []

    def _resolve_shed(self, fut: Future, reason: str) -> bool:
        """Resolve one future shed (if still unresolved) and count it."""
        if fut.done():
            return False
        try:
            fut.set_result(QueryResult(shed=True, reason=reason))
        except InvalidStateError:  # lost a resolution race
            return False
        with self._lock:
            self._shed_count += 1
        return True

    def _serve_batch(self, batch) -> None:
        import pandas as pd

        now = time.monotonic()
        live, expired = [], 0
        for entry in batch:
            fut = entry[1]
            if fut.done():  # cancelled on timeout; already counted
                continue
            dl = entry[3]
            if dl is not None and now > dl:
                self._resolve_shed(fut, "deadline")
                expired += 1
                continue
            live.append(entry)
        if expired:
            warn_degraded(
                "serve_deadline",
                "shed",
                f"{expired} request(s) exceeded their deadline waiting in "
                "the queue; shed at dispatch instead of scored late",
                expired=expired,
            )
        if not live:
            self._clear_inflight()
            return
        if self.breaker.should_fail_fast():
            for entry in live:
                self._resolve_shed(entry[1], "breaker_open")
            warn_degraded(
                "serve_breaker",
                "shed",
                f"circuit breaker open ({self.breaker.threshold} "
                "consecutive batch failures); failing fast until a "
                "recovery probe succeeds",
                requests=len(live),
            )
            self._clear_inflight()
            return
        q_fill = self._take_fill
        degraded = brownout_active(
            q_fill,
            self._health.state,
            enabled=self.brownout_enabled,
            fill_threshold=self.brownout_fill,
        )
        self._note_brownout(degraded, q_fill)
        records = [e[0] for e in live]
        futures = [e[1] for e in live]
        t_enq = [e[2] for e in live]
        t0 = time.perf_counter()
        try:
            active_plan(self._settings).fire(
                "serve_batch", batch=self._batches
            )
            df = pd.DataFrame.from_records(records)
            if self._obs is not None:
                with self._obs.span(
                    "serve_batch", batch=len(live), degraded=degraded
                ):
                    results = self._score(df, degraded)
            else:
                results = self._score(df, degraded)
        except Exception as e:  # noqa: BLE001 - one bad batch must not kill the loop
            logger.exception("serve batch failed; shedding %d request(s)",
                             len(live))
            opened = self.breaker.on_failure()
            for fut in futures:
                self._resolve_shed(fut, "batch_error")
            warn_degraded(
                "serve_batch",
                "shed",
                f"batch scoring failed ({type(e).__name__}: {e}); "
                f"{len(live)} request(s) resolved shed, no exception "
                "escapes to callers",
                requests=len(live),
            )
            if opened:
                warn_degraded(
                    "serve_engine",
                    "breaker_open",
                    f"{self.breaker.threshold} consecutive batch failures; "
                    "failing fast while probes test recovery",
                    cooldown_s=self.breaker.cooldown_s,
                )
            self._clear_inflight()
            return
        batch_ms = (time.perf_counter() - t0) * 1000.0
        if self.breaker.on_success():
            from ..obs.events import publish

            publish("breaker", state="closed", reason="probe batch succeeded")
            logger.info("serve circuit breaker closed: probe batch succeeded")
        self._admission.observe(batch_ms)
        now = time.monotonic()
        # deliver first, count after: a request cancelled by
        # query(timeout=) mid-score was already counted shed there —
        # counting it served too would make served+shed exceed
        # submissions and skew the health monitor's shed-rate window
        delivered = []
        for i, fut in enumerate(futures):
            res = results[i]
            res.degraded = degraded
            res.latency_ms = (now - t_enq[i]) * 1000.0
            if fut.done():
                continue
            try:
                fut.set_result(res)
            except InvalidStateError:  # timed out in the same instant
                continue
            delivered.append(res)
            if self._obs is not None:
                self._obs.observe("serve_latency_ms", res.latency_ms)
        # counters AND latency deques under the lock: _health_signals
        # list()s the deques concurrently, and deque iteration raises on
        # mutation mid-iteration
        with self._lock:
            self._batches += 1
            first_batch = self._batches == 1
            self._served += len(delivered)
            if degraded:
                self._degraded_served += len(delivered)
            for res in delivered:
                self._latencies.append(res.latency_ms)
                self._recent_lat.append(res.latency_ms)
        if first_batch:
            # re-baseline compile-stall detection at first traffic: an
            # engine warmed AFTER service construction must not read as a
            # steady-state compile stall (stall means compiles while
            # serving, not before it)
            from ..obs.metrics import compile_totals

            with self._signals_lock:
                self._last_compile_s = compile_totals()[1]
                self._stall_accum = 0.0
        self._clear_inflight()
        if (
            self._probe_queries
            and not degraded
            and self.engine.probe_count == 0
        ):
            # seed the hot-swap parity probe set from live traffic:
            # accumulate full-service records across batches until the
            # probe budget is met (a single small batch must not leave a
            # one-probe parity set), then capture once; best-effort.
            # capture_probes deliberately RE-SCORES the set as one batch
            # (one extra dispatch, once per lifetime): the stored answers
            # then come from exactly the single-batch scoring the swap
            # replay performs, not rows stitched from differently-shaped
            # batches
            need = self._probe_queries - len(self._probe_buffer)
            if need > 0:
                self._probe_buffer.extend(records[:need])
            if len(self._probe_buffer) >= self._probe_queries:
                try:
                    self.engine.capture_probes(
                        pd.DataFrame.from_records(self._probe_buffer)
                    )
                except Exception as e:  # noqa: BLE001 - probes must not break serving
                    logger.debug("probe capture failed: %s", e)
                self._probe_buffer = []

    def _note_brownout(self, active: bool, q_fill: float) -> None:
        if active == self._brownout_active:
            return
        self._brownout_active = active
        from ..obs.events import publish

        if active:
            with self._lock:
                self._brownout_episodes += 1
            warn_degraded(
                "serve_brownout",
                "active",
                f"pressure (queue {q_fill:.0%} full, health "
                f"{self._health.state}); serving budgeted top-"
                f"{self.engine.brownout_top_k} answers instead of shedding",
                queue_fill=round(q_fill, 3),
            )
        else:
            publish("brownout_end", queue_fill=round(q_fill, 3))
            logger.info("serve brown-out ended (queue %.0f%% full)",
                        q_fill * 100)

    def _score(self, df, degraded: bool = False) -> list[QueryResult]:
        top_p, top_rows, top_valid, n_cand = self.engine.query_arrays(
            df, degraded=degraded
        )
        uids = self.engine.index.unique_id
        out = []
        for i in range(len(df)):
            matches = [
                (uids[top_rows[i, r]], float(top_p[i, r]))
                for r in range(top_p.shape[1])
                if top_valid[i, r]
            ]
            out.append(
                QueryResult(matches=matches, n_candidates=int(n_cand[i]))
            )
        return out

    # -- watchdog -------------------------------------------------------

    def _watchdog_loop(self) -> None:
        while not self._watchdog_stop.wait(self.watchdog_interval_s):
            try:
                self._watchdog_tick()
            except Exception as e:  # noqa: BLE001 - the supervisor must survive
                logger.warning("serve watchdog tick failed: %s", e)

    def _watchdog_tick(self) -> None:
        from ..obs.events import publish

        # 1. dead-worker recovery: shed orphans, restart, emit events
        orphans = None
        with self._nonempty:
            t = self._thread
            if t is not None and not t.is_alive() and not self._stop:
                orphans = self._inflight + list(self._queue)
                self._inflight = []
                self._queue.clear()
                self._worker_crashes += 1
                crashes = self._worker_crashes
                self._thread = threading.Thread(
                    target=self._worker, name="splink-serve", daemon=True
                )
                self._thread.start()
        if orphans is not None:
            n = sum(
                self._resolve_shed(entry[1], "worker_restart")
                for entry in orphans
            )
            publish("serve_worker_restart", orphaned=n, crashes=crashes)
            warn_degraded(
                "serve_worker",
                "restarted",
                f"worker thread died; {n} orphaned request(s) resolved "
                "shed and the worker was restarted",
                orphaned=n,
                crashes=crashes,
            )
        # 2. breaker recovery probe when traffic has stopped
        if self.breaker.probe_due():
            with self._lock:
                idle = not self._queue and not self._inflight
            if idle:
                try:
                    self.engine.probe()
                except Exception as e:  # noqa: BLE001 - a failed probe re-opens
                    self.breaker.on_failure()
                    logger.warning("breaker recovery probe failed: %s", e)
                else:
                    if self.breaker.on_success():
                        publish(
                            "breaker",
                            state="closed",
                            reason="watchdog probe succeeded",
                        )
                        logger.info(
                            "serve circuit breaker closed: watchdog probe "
                            "succeeded"
                        )
        # 3. health evaluation from live signals
        self._maybe_evaluate_health()

    # -- health ---------------------------------------------------------

    def _health_signals(self) -> dict:
        from ..obs.metrics import compile_totals

        with self._lock:
            served, shed = self._served, self._shed_count
            q_fill = (
                len(self._queue) / self.queue_depth if self.queue_depth else 0.0
            )
            worker = self._thread
            alive = worker is not None and worker.is_alive()
            brownout = self._brownout_active
            recent = list(self._recent_lat)
            swapping = self._swap_in_progress
        _, c_secs = compile_totals()
        # the window marks are shared state consumed by BOTH the watchdog
        # tick and on-demand health() calls: the read-update must be
        # atomic, and compile-stall detection accumulates across windows
        # so a real stall cannot hide in the slivers concurrent pollers
        # split the window into (a compile-free window clears it)
        with self._signals_lock:
            d_served = served - self._hw_served
            d_shed = shed - self._hw_shed
            self._hw_served, self._hw_shed = served, shed
            delta_c = c_secs - self._last_compile_s
            self._last_compile_s = c_secs
            if swapping or delta_c <= 0:
                self._stall_accum = 0.0
            else:
                self._stall_accum += delta_c
            stall = self._stall_accum > self.compile_stall_s
        total = d_served + d_shed
        shed_rate = (d_shed / total) if total else 0.0
        p95 = (
            float(np.percentile(np.asarray(recent, np.float64), 95))
            if recent
            else None
        )
        return {
            "worker_alive": alive,
            "breaker": self.breaker.state,
            "queue_fill": round(q_fill, 4),
            "shed_rate": round(shed_rate, 4),
            "p95_ms": p95,
            "compile_stall": stall,
            "brownout": brownout,
        }

    def _maybe_evaluate_health(self) -> None:
        """Advance the health state machine at most once per watchdog
        interval: ``recover_ticks`` hysteresis is calibrated to that
        cadence, and a fast external poller must not inflate the recovery
        streak (or starve the shed-rate window)."""
        now = time.monotonic()
        with self._signals_lock:
            if now - self._last_health_eval < self.watchdog_interval_s:
                return
            self._last_health_eval = now
        self._health.evaluate(self._health_signals())

    def health(self) -> dict:
        """The replica's live health: advances the state machine (rate-
        limited to the watchdog cadence — polling cannot defeat the
        recovery hysteresis) and returns its snapshot plus breaker/engine
        context (the endpoint the :class:`~.router.ReplicaRouter` routes
        on)."""
        self._maybe_evaluate_health()
        snap = self._health.snapshot()
        snap["breaker"] = self.breaker.snapshot()
        snap["generation"] = self.engine.generation
        snap["worker_crashes"] = self._worker_crashes
        snap["brownout_episodes"] = self._brownout_episodes
        return snap

    @property
    def health_state(self) -> str:
        """Current state WITHOUT re-evaluating (router fast path)."""
        return self._health.state

    # -- index hot-swap -------------------------------------------------

    def swap_index(self, source, *, refresh_probes: bool = False) -> dict:
        """Hot-swap the engine's index (see
        :meth:`~.engine.QueryEngine.swap_index`): validation and pre-warm
        happen while this service KEEPS SERVING the old index; the flip is
        atomic and in-flight batches drain on the old index. The swap's
        own compiles are excluded from the health monitor's compile-stall
        signal."""
        from ..obs.metrics import compile_totals

        self._swap_in_progress = True
        try:
            return self.engine.swap_index(source, refresh_probes=refresh_probes)
        finally:
            self._swap_in_progress = False
            with self._signals_lock:
                self._last_compile_s = compile_totals()[1]
                self._stall_accum = 0.0

    # -- reporting ------------------------------------------------------

    def latency_summary(self) -> dict:
        """p50/p95/p99 request latency (ms), counts, throughput and the
        resilience counters over the service's lifetime."""
        # snapshot under the lock: the worker appends concurrently and
        # deque iteration raises on mutation
        with self._lock:
            lats = np.asarray(self._latencies, np.float64)
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        out = {
            "served": self._served,
            "shed": self._shed_count,
            "batches": self._batches,
            "queries_per_sec": self._served / elapsed,
            "degraded_served": self._degraded_served,
            "timeouts": self._timeouts,
            "brownout_episodes": self._brownout_episodes,
            "worker_crashes": self._worker_crashes,
            "breaker_state": self.breaker.state,
            "breaker_opened_total": self.breaker.opened_total,
            "health": self._health.state,
            "index_generation": self.engine.generation,
        }
        if len(lats):
            p50, p95, p99 = np.percentile(lats, [50, 95, 99])
            out.update(
                p50_ms=float(p50), p95_ms=float(p95), p99_ms=float(p99),
                mean_ms=float(lats.mean()),
            )
        return out
