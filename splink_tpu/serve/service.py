"""Micro-batching front-end: single queries in, coalesced device batches out.

Accelerators amortise dispatch over batches; online traffic arrives one
record at a time. :class:`LinkageService` bridges the two with the classic
micro-batching loop: ``submit`` enqueues a record and returns a future, a
worker thread coalesces everything queued within ``deadline_ms`` of the
FIRST waiting record (or until a full largest query bucket accumulates,
whichever comes first) into one engine dispatch, and each future resolves
with its record's matches.

Resilience is graduated, not binary (serve/admission.py, serve/health.py):

* **Admission control** — the bounded queue still SHEDS instead of OOMing
  when ``queue_depth`` records wait, and a request carrying its own
  ``deadline_ms`` is rejected AT ADMISSION when the estimated queue wait
  (EWMA batch-time model) cannot meet it; queued requests whose deadline
  lapses before dispatch are shed at the batcher, never scored late.
* **Brown-out** — between full service and shedding sits the budgeted
  tier: under pressure (queue past ``brownout_fill``, or health already
  degraded) batches run the engine's brown-out program — reduced top-k
  over the cheapest candidate bucket — and results are tagged
  ``degraded=True``. Enabled by ``serve_brownout_top_k`` > 0.
* **Circuit breaker** — ``serve_breaker_threshold`` consecutive batch
  failures open the breaker: requests fail fast as shed (reason
  ``breaker_open``) instead of queueing behind a broken engine, while the
  first post-cooldown batch — or the watchdog's synthetic engine probe
  when traffic has stopped — tests recovery.
* **Watchdog** — a supervisor thread that detects a dead worker, resolves
  its orphaned futures shed (a crashed worker previously hung every
  outstanding future forever), restarts the thread, runs breaker recovery
  probes, and drives the per-replica health state machine
  (:class:`~.health.HealthMonitor`) from live signals: queue fill, shed
  rate, recent p95, compile stalls, breaker state.

Nothing raises on the submit path, no exception ever escapes to a caller
through a future, and every degradation flows through the structured
channel (``logging_utils.warn_degraded`` + ambient obs events) — overload
and faults are measured, observable states rather than crashes.
``scripts/chaos_smoke.py`` (`make chaos-smoke`) drives every registered
serve fault site against these guarantees.

Per-request latency (enqueue -> result set) feeds a bounded reservoir;
:meth:`latency_summary` reports p50/p95/p99 and throughput, and with a
telemetry ``RunContext`` the summary lands in the run record (``python -m
splink_tpu.obs summarize``) alongside per-batch ``serve_batch`` spans.

Request-level observability (obs v2, docs/observability.md#serve-tracing):

* **Tracing** — with ``serve_trace_sample_rate`` > 0, sampled requests
  carry a trace context (:mod:`..obs.reqtrace`) through the queue,
  coalescer and engine dispatch; the span tree closes exactly once at
  delivery/shed/cancel with phase durations (admission / queue_wait /
  coalesce / dispatch / compile / execute / transfer / deliver) that sum
  to the measured wall latency. ``python -m splink_tpu.obs attribute``
  decomposes the tail; ``make trace-smoke`` gates the invariant.
* **SLO** — every request (sampled or not) feeds an
  :class:`~..obs.slo.SLOTracker`: delivered = good, shed = bad, rolling
  hit rate + multi-window burn rate via :meth:`slo_snapshot`.
* **Flight recorder** — a bounded ring (``obs_flight_records``) of recent
  span trees and health/breaker/swap transitions, dumped atomically to
  JSONL on breaker-open, worker restart, swap rollback or SIGUSR2
  (:mod:`..obs.flight`).
* **Exposition** — ``obs_exposition_port`` serves all of the above in
  Prometheus text format (:mod:`..obs.exposition`); ``obs serve-dash``
  renders it live.

All of it is host-side bookkeeping: compiled programs are untouched, the
hot path gains no host sync, and sampling keeps obs-on overhead within the
bench-measured budget (BENCHMARKS.md round 9).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FuturesTimeout
from dataclasses import dataclass, field

from ..analysis import lockwatch

import numpy as np

from ..resilience.faults import active_plan
from ..utils.logging_utils import warn_degraded
from .admission import CircuitBreaker, WaitEstimator, brownout_active
from .health import HealthMonitor

logger = logging.getLogger("splink_tpu")

_LATENCY_RESERVOIR = 65536  # newest-N latency samples kept for percentiles
_RECENT_WINDOW = 512  # newest-N samples for the health monitor's p95


@dataclass
class QueryResult:
    """One query's outcome.

    ``shed`` requests carry a machine-readable ``reason``:
    ``queue_full`` / ``deadline`` / ``timeout`` / ``breaker_open`` /
    ``batch_error`` / ``worker_restart`` / ``closed``. ``degraded`` marks
    a brown-out answer (served under a reduced candidate/top-k budget)."""

    matches: list = field(default_factory=list)  # [(ref_uid, probability)]
    n_candidates: int = 0
    shed: bool = False
    latency_ms: float | None = None
    degraded: bool = False
    # the query's exact blocking keys hit no bucket and the matches came
    # from the approx LSH fallback bucket path (docs/blocking.md)
    approx: bool = False
    reason: str | None = None
    # server-side latency split (fleet observability, PR 18): time this
    # request waited in the replica's queue vs the engine wall it shared.
    # Always stamped on delivered results — even with fleet features off —
    # so a wire client can answer "is it the link or the replica?" from
    # two JSON fields (queue_ms + execute_ms = the server's share of RTT).
    queue_ms: float | None = None
    execute_ms: float | None = None

    # -- wire round-trip (serve/wire.py envelope "result" field) --------
    # JSON float serialisation is exact (repr round-trips every double),
    # so a result that crosses the wire is bit-identical to the local one
    # — the parity contract make wire-smoke asserts.

    def to_payload(self) -> dict:
        return {
            "matches": [[uid, p] for uid, p in self.matches],
            "n_candidates": int(self.n_candidates),
            "shed": bool(self.shed),
            "latency_ms": self.latency_ms,
            "degraded": bool(self.degraded),
            "approx": bool(self.approx),
            "reason": self.reason,
            "queue_ms": self.queue_ms,
            "execute_ms": self.execute_ms,
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "QueryResult":
        return cls(
            matches=[
                (m[0], m[1]) for m in (payload.get("matches") or [])
            ],
            n_candidates=int(payload.get("n_candidates") or 0),
            shed=bool(payload.get("shed")),
            latency_ms=payload.get("latency_ms"),
            degraded=bool(payload.get("degraded")),
            approx=bool(payload.get("approx")),
            reason=payload.get("reason"),
            queue_ms=payload.get("queue_ms"),
            execute_ms=payload.get("execute_ms"),
        )


class LinkageService:
    """Micro-batching query front-end over a :class:`~.engine.QueryEngine`
    (module docstring)."""

    #: routers check this before forwarding a trace context (duck-typed
    #: replicas without it keep the PR 6 submit signature)
    accepts_trace = True

    #: every attempt this service resolves closes its span tree exactly
    #: once — the contract the wire tier's v2 span piggyback gates the
    #: result reply on (serve/wire.py ``_SpanJoin``)
    closes_traces = True

    def __init__(
        self,
        engine,
        *,
        queue_depth: int | None = None,
        deadline_ms: float | None = None,
        autostart: bool = True,
        telemetry=None,
        name: str = "serve",
        breaker_threshold: int | None = None,
        breaker_cooldown_s: float = 1.0,
        brownout_fill: float = 0.5,
        watchdog_interval_s: float = 0.1,
        compile_stall_s: float = 0.25,
        probe_queries: int | None = None,
        health_monitor: HealthMonitor | None = None,
        trace_sample_rate: float | None = None,
        slo_objective: float = 0.999,
        flight_records: int | None = None,
        exposition_port: int | None = None,
        perf_alert_ratio: float | None = None,
        perf_window_s: float | None = None,
    ):
        settings = engine.index.settings
        self.engine = engine
        self.name = name
        self.queue_depth = int(
            queue_depth
            if queue_depth is not None
            else settings.get("serve_queue_depth", 1024) or 1024
        )
        self.deadline_ms = float(
            deadline_ms
            if deadline_ms is not None
            else settings.get("serve_deadline_ms", 5.0)
        )
        self.breaker = CircuitBreaker(
            threshold=int(
                breaker_threshold
                if breaker_threshold is not None
                else settings.get("serve_breaker_threshold", 3) or 3
            ),
            cooldown_s=breaker_cooldown_s,
        )
        self.brownout_fill = float(brownout_fill)
        self.brownout_enabled = engine.brownout_top_k > 0
        self.watchdog_interval_s = float(watchdog_interval_s)
        self.compile_stall_s = float(compile_stall_s)
        self._probe_queries = int(
            probe_queries
            if probe_queries is not None
            else settings.get("serve_probe_queries", 16) or 0
        )
        self._settings = settings
        self._obs = telemetry
        self._lock = lockwatch.new_lock("LinkageService._lock")
        self._nonempty = threading.Condition(self._lock)
        # (record, future, t_enqueue, deadline, trace) — trace is None for
        # unsampled requests, so the tracing-off path costs one tuple slot
        self._queue: deque = deque()
        self._inflight: list = []  # entries popped by the worker, unresolved
        self._probe_buffer: list = []  # records accumulating toward capture
        self._latencies: deque = deque(maxlen=_LATENCY_RESERVOIR)
        self._recent_lat: deque = deque(maxlen=_RECENT_WINDOW)
        self._admission = WaitEstimator()
        self._health = health_monitor or HealthMonitor(name=name)
        self._shed_count = 0
        self._served = 0
        self._batches = 0
        self._timeouts = 0
        self._degraded_served = 0
        self._brownout_episodes = 0
        self._worker_crashes = 0
        self._brownout_active = False
        self._take_fill = 0.0
        self._swap_in_progress = False
        self._summary_recorded = False
        self._t_start = time.monotonic()
        self._stop = False
        self._thread: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        self._watchdog_stop = threading.Event()
        # health-window marks (consumed by _health_signals deltas; the
        # watchdog and on-demand health() calls share them, so updates go
        # through _signals_lock)
        self._signals_lock = lockwatch.new_lock("LinkageService._signals_lock")
        self._hw_served = 0
        self._hw_shed = 0
        self._stall_accum = 0.0
        self._last_health_eval = float("-inf")
        from ..obs.metrics import compile_totals

        self._last_compile_s = compile_totals()[1]
        # -- obs v2: request tracing, SLO, flight recorder, exposition ---
        from ..obs.events import register_ambient
        from ..obs.flight import FlightRecorder
        from ..obs.reqtrace import ServeTracer
        from ..obs.slo import SLOTracker

        rate = float(
            trace_sample_rate
            if trace_sample_rate is not None
            else settings.get("serve_trace_sample_rate", 0.0) or 0.0
        )
        n_flight = int(
            flight_records
            if flight_records is not None
            else settings.get("obs_flight_records", 256) or 0
        )
        self._flight = FlightRecorder(
            n_flight,
            dump_dir=(settings.get("telemetry_dir") or None),
            name=name,
        )
        if self._flight.enabled:
            register_ambient(self._flight)
        self._tracer = ServeTracer(rate, service=name, flight=self._flight)
        if self._tracer.enabled:
            from ..obs.metrics import install_compile_monitor

            install_compile_monitor()  # the per-batch compile split
        self._slo = SLOTracker(objective=slo_objective)
        # -- drift observatory (obs/drift.py): present only when the
        # engine sketches (quality_profile on AND a profiled index) ------
        self._drift_alert_active = False
        self._drift = self._make_drift_monitor()
        # -- kernel performance watch (obs/kernelwatch.py): rolling-window
        # execute-latency regression alerts over the batch wall and the
        # PhaseProfile splits the engine already measures — host-side
        # arithmetic only, zero new syncs on the hot path ----------------
        self._perf_alert_active = False
        self._last_perf_window = float("-inf")
        self._last_perf_eval = float("-inf")
        ratio = float(
            perf_alert_ratio
            if perf_alert_ratio is not None
            else settings.get("perf_alert_ratio", 3.0) or 0.0
        )
        self._kwatch = None
        if ratio > 0:
            from ..obs.kernelwatch import KernelWatch

            self._kwatch = KernelWatch(
                window_s=float(
                    perf_window_s
                    if perf_window_s is not None
                    else settings.get("perf_window_s", 30.0) or 30.0
                ),
                alert_ratio=ratio,
            )
        self._exposition = None
        port = int(
            exposition_port
            if exposition_port is not None
            else settings.get("obs_exposition_port", 0) or 0
        )
        if port:
            try:
                from ..obs.exposition import ExpositionServer

                self._exposition = ExpositionServer(port)
                self._exposition.add_source(name, self.prometheus_samples)
                self._exposition.start()
                logger.info(
                    "serve metrics exposition on %s", self._exposition.url
                )
            except Exception as e:  # noqa: BLE001 - obs must not block serving
                logger.warning("metrics exposition failed to start: %s", e)
                self._exposition = None
        if autostart:
            self.start()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "LinkageService":
        """Start (or restart after :meth:`close`) the worker + watchdog."""
        with self._nonempty:
            if self._thread is None:
                self._stop = False
                self._summary_recorded = False  # a reopen closes again later
                self._thread = threading.Thread(
                    target=self._worker, name="splink-serve", daemon=True
                )
                self._thread.start()
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog_stop = threading.Event()
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="splink-serve-watchdog",
                daemon=True,
            )
            self._watchdog.start()
        return self

    def close(self, drain: bool = True) -> None:
        """Stop the worker and watchdog. With ``drain`` (default) queued
        requests are served first; otherwise they resolve shed. Idempotent
        — a second close is a no-op and never hangs a future."""
        self._watchdog_stop.set()
        watchdog = self._watchdog
        if watchdog is not None and watchdog is not threading.current_thread():
            watchdog.join(timeout=10)
        self._watchdog = None
        to_shed: list = []
        with self._nonempty:
            self._stop = True
            if not drain:
                while self._queue:
                    to_shed.append(self._queue.popleft())
            self._nonempty.notify_all()
        for entry in to_shed:
            self._resolve_shed(entry[1], "closed", entry[4])
        # take the worker handle under the lock: a concurrent close must
        # not race this read/None write (close is documented idempotent)
        with self._lock:
            worker = self._thread
            self._thread = None
        if worker is not None:
            worker.join(timeout=30)
        # a submit racing the shutdown can enqueue after the worker's last
        # batch — and a worker that DIED mid-batch leaves in-flight entries
        # — resolve all stragglers shed so no future hangs forever
        with self._nonempty:
            stragglers = list(self._queue) + self._inflight
            self._queue.clear()
            self._inflight = []
        for entry in stragglers:
            self._resolve_shed(entry[1], "closed", entry[4])
        # final drift drain: the tail window must not die in the device
        # accumulator (short-lived services still report their drift)
        self._drift_tick(force=True)
        if self._exposition is not None:
            self._exposition.close()
            self._exposition = None
        self._flight.close()  # unregister; the ring stays dump-able
        # once per lifetime: close() is idempotent and must not emit
        # duplicate serve_latency records on repeated calls (the
        # check-and-set is atomic so concurrent closes cannot both record)
        with self._lock:
            record_summary = (
                self._obs is not None and not self._summary_recorded
            )
            self._summary_recorded = True
        if record_summary:
            self._obs.record("serve_latency", self.latency_summary())

    def __enter__(self) -> "LinkageService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission -----------------------------------------------------

    def submit(
        self,
        record: dict,
        deadline_ms: float | None = None,
        trace=None,
    ) -> Future:
        """Enqueue one query record; never raises. Sheds immediately
        (future resolves ``shed=True`` + degradation event) when the
        service is closed, the bounded queue is full, or ``deadline_ms``
        is given and the estimated queue wait already exceeds it
        (reject-early admission, module docstring). A queued request's
        ``deadline_ms`` also rides into the batcher: lapsed requests are
        shed at dispatch, never scored late.

        ``trace`` is an inbound :class:`~..obs.reqtrace.RequestTrace`
        (router-minted attempt context); without one, the service's own
        sampler decides. The trace closes exactly once, wherever this
        request's future resolves."""
        fut: Future = Future()
        if trace is None:
            trace = self._tracer.maybe_start()
        reason = None
        with self._nonempty:
            closed = self._stop and self._thread is None
            if closed:
                reason = "closed"
                reason_text = "service is closed; submissions resolve shed"
            elif len(self._queue) >= self.queue_depth:
                reason = "queue_full"
                reason_text = (
                    f"bounded queue full ({self.queue_depth} waiting); "
                    "shedding instead of growing without bound"
                )
            elif deadline_ms is not None:
                est = self._admission.estimate_wait_ms(
                    len(self._queue),
                    self.engine.policy.max_batch,
                    self.deadline_ms,
                    inflight_batches=1 if self._inflight else 0,
                )
                if est > deadline_ms:
                    reason = "deadline"
                    reason_text = (
                        f"estimated queue wait {est:.1f}ms exceeds the "
                        f"request deadline {deadline_ms:.1f}ms; rejected at "
                        "admission instead of timing out in the queue"
                    )
            if reason is not None:
                self._shed_count += 1
                shed_total = self._shed_count
            else:
                deadline = (
                    None
                    if deadline_ms is None
                    else time.monotonic() + deadline_ms / 1000.0
                )
                if trace is not None:
                    trace.mark("admit")
                self._queue.append(
                    (record, fut, time.monotonic(), deadline, trace)
                )
                self._nonempty.notify()
                return fut
        # outside the lock: resolving the future runs done-callbacks, and
        # warn_degraded publishes + warns — all of which may run user hooks
        fut.set_result(QueryResult(shed=True, reason=reason))
        self._slo.observe(False)
        self._tracer.close(trace, "shed", reason=reason)
        warn_degraded(
            "serve_admission" if reason == "deadline" else "serve_queue",
            "shed",
            reason_text,
            shed_total=shed_total,
        )
        return fut

    def query(
        self,
        record: dict,
        timeout: float | None = None,
        deadline_ms: float | None = None,
    ) -> QueryResult:
        """Submit one record and wait for its result. A ``timeout`` that
        expires CANCELS the request: it is removed from the queue (a
        timed-out request used to stay queued and get scored anyway),
        counted shed (reason ``timeout``) and the degradation event is
        emitted — unless its real result won the race, which is returned."""
        fut = self.submit(record, deadline_ms=deadline_ms)
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeout:
            return self._cancel_timed_out(fut, timeout)

    def _cancel_timed_out(self, fut: Future, timeout) -> QueryResult:
        trace = None
        with self._nonempty:
            for i, entry in enumerate(self._queue):
                if entry[1] is fut:
                    trace = entry[4]
                    del self._queue[i]
                    break
            else:
                # mid-score: still in flight — find the trace so a won
                # cancellation closes its span tree with the shed reason
                for entry in self._inflight:
                    if entry[1] is fut:
                        trace = entry[4]
                        break
        res = QueryResult(shed=True, reason="timeout")
        won = False
        if not fut.done():
            try:
                fut.set_result(res)
                won = True
            except InvalidStateError:  # the worker resolved it first
                pass
        if not won:
            return fut.result(timeout=0)
        with self._lock:
            self._shed_count += 1
            self._timeouts += 1
        self._slo.observe(False)
        self._tracer.close(trace, "shed", reason="timeout")
        warn_degraded(
            "serve_timeout",
            "shed",
            f"request result not ready within its {timeout}s timeout; "
            "cancelled (dequeued) and counted shed",
            timeout_s=timeout,
        )
        return res

    # -- worker ---------------------------------------------------------

    def _worker(self) -> None:
        try:
            while True:
                # fault site OUTSIDE the batch try-block: a raise here
                # kills the worker thread — the failure mode the watchdog
                # recovers from (resilience/faults.py SERVE_SITES)
                with self._lock:
                    batch_no = self._batches
                active_plan(self._settings).fire(
                    "serve_worker", batch=batch_no
                )
                batch = self._take_batch()
                if batch is None:
                    return
                self._serve_batch(batch)
                # drift drains ride BETWEEN batches (one bounded device
                # fetch per drain cadence, never inside a dispatch)
                self._drift_tick()
                self._perf_tick()
        except Exception:  # noqa: BLE001 - a dying worker must not spam stderr
            logger.exception(
                "serve worker thread died; the watchdog will shed its "
                "orphaned requests and restart it"
            )

    def _take_batch(self):
        """Block until work exists, then coalesce until the deadline (from
        the FIRST waiting record) or a full largest bucket. The taken
        entries are tracked as in-flight so a worker death cannot orphan
        them past the watchdog."""
        max_batch = self.engine.policy.max_batch
        with self._nonempty:
            while not self._queue:
                if self._stop:
                    return None
                self._nonempty.wait(timeout=0.1)
            # trace boundary: batch formation starts here — for a request
            # already waiting, [enqueue, t_form) was queue_wait (time the
            # worker spent on earlier batches); [t_form, pop) is coalesce
            t_form = time.monotonic()
            deadline = self._queue[0][2] + self.deadline_ms / 1000.0
            while len(self._queue) < max_batch and not self._stop:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(timeout=remaining)
            # pressure is measured BEFORE the take: a large coalesced batch
            # drains the queue, which must not hide the pressure it is
            # itself the evidence of (the brown-out decision reads this)
            self._take_fill = len(self._queue) / self.queue_depth
            take = min(len(self._queue), max_batch)
            batch = [self._queue.popleft() for _ in range(take)]
            self._inflight = batch
            t_pop = time.monotonic()
            for entry in batch:
                tr = entry[4]
                if tr is not None:
                    # clamping in phase_durations handles entries that
                    # enqueued after t_form (their queue_wait is zero)
                    tr.marks["form"] = t_form
                    tr.marks["pop"] = t_pop
            return batch

    def _clear_inflight(self) -> None:
        with self._lock:
            self._inflight = []

    def _resolve_shed(self, fut: Future, reason: str, trace=None) -> bool:
        """Resolve one future shed (if still unresolved), count it, feed
        the SLO tracker and close the request's span tree with the
        machine-readable reason."""
        if fut.done():
            return False
        try:
            fut.set_result(QueryResult(shed=True, reason=reason))
        except InvalidStateError:  # lost a resolution race
            return False
        with self._lock:
            self._shed_count += 1
        self._slo.observe(False)
        self._tracer.close(trace, "shed", reason=reason)
        return True

    def _serve_batch(self, batch) -> None:
        import pandas as pd

        now = time.monotonic()
        live, expired = [], 0
        for entry in batch:
            fut = entry[1]
            if fut.done():  # cancelled on timeout; already counted
                continue
            dl = entry[3]
            if dl is not None and now > dl:
                self._resolve_shed(fut, "deadline", entry[4])
                expired += 1
                continue
            live.append(entry)
        if expired:
            warn_degraded(
                "serve_deadline",
                "shed",
                f"{expired} request(s) exceeded their deadline waiting in "
                "the queue; shed at dispatch instead of scored late",
                expired=expired,
            )
        if not live:
            self._clear_inflight()
            return
        if self.breaker.should_fail_fast():
            for entry in live:
                self._resolve_shed(entry[1], "breaker_open", entry[4])
            warn_degraded(
                "serve_breaker",
                "shed",
                f"circuit breaker open ({self.breaker.threshold} "
                "consecutive batch failures); failing fast until a "
                "recovery probe succeeds",
                requests=len(live),
            )
            self._clear_inflight()
            return
        with self._lock:
            q_fill = self._take_fill
            batch_no = self._batches
            swap_overlapped = self._swap_in_progress
        degraded = brownout_active(
            q_fill,
            self._health.state,
            enabled=self.brownout_enabled,
            fill_threshold=self.brownout_fill,
        )
        self._note_brownout(degraded, q_fill)
        records = [e[0] for e in live]
        futures = [e[1] for e in live]
        t_enq = [e[2] for e in live]
        traces = [e[4] for e in live]
        # one batch-level phase profile when any request is traced — every
        # request in the batch waited through the same engine window, so
        # the batch splits ARE each request's attribution — or when the
        # kernel watch wants the execute split (profiling divides the
        # engine's single existing rendezvous; it adds no host sync)
        profile = None
        if any(tr is not None for tr in traces) or self._kwatch is not None:
            from ..obs.reqtrace import PhaseProfile

            profile = PhaseProfile()
        # queue/execute split stamp (fleet observability): everything up
        # to here was queueing/coalescing; the engine window follows
        t_dispatch = time.monotonic()
        t0 = time.perf_counter()
        try:
            active_plan(self._settings).fire(
                "serve_batch", batch=batch_no
            )
            df = pd.DataFrame.from_records(records)
            if self._obs is not None:
                with self._obs.span(
                    "serve_batch", batch=len(live), degraded=degraded
                ):
                    results = self._score(df, degraded, profile)
            else:
                results = self._score(df, degraded, profile)
        except Exception as e:  # noqa: BLE001 - one bad batch must not kill the loop
            logger.exception("serve batch failed; shedding %d request(s)",
                             len(live))
            opened = self.breaker.on_failure()
            for entry in live:
                self._resolve_shed(entry[1], "batch_error", entry[4])
            warn_degraded(
                "serve_batch",
                "shed",
                f"batch scoring failed ({type(e).__name__}: {e}); "
                f"{len(live)} request(s) resolved shed, no exception "
                "escapes to callers",
                requests=len(live),
            )
            if opened:
                warn_degraded(
                    "serve_engine",
                    "breaker_open",
                    f"{self.breaker.threshold} consecutive batch failures; "
                    "failing fast while probes test recovery",
                    cooldown_s=self.breaker.cooldown_s,
                    replica=self.name,
                )
            self._clear_inflight()
            return
        batch_ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            swap_overlapped = swap_overlapped or self._swap_in_progress
        if profile is not None and swap_overlapped:
            # the compile split reads the PROCESS-global compile counter: a
            # concurrent swap_index pre-warm (which deliberately compiles
            # outside the dispatch lock while the old index keeps serving)
            # would be mis-attributed as this batch's phantom steady-state
            # compile — fold it into the dispatch residual instead, the
            # same exclusion the health monitor's stall signal applies
            profile.compile_s = 0.0
        if self.breaker.on_success():
            from ..obs.events import publish

            publish("breaker", state="closed", reason="probe batch succeeded")
            logger.info("serve circuit breaker closed: probe batch succeeded")
        self._admission.observe(batch_ms)
        if self._kwatch is not None and not degraded:
            # compiling batches are warmup, not steady state — the watch
            # anchors on (and alerts over) post-warmup execute only; the
            # brown-out program's reduced shapes are likewise excluded
            if profile is None or profile.compile_s <= 0.0:
                self._kwatch.observe("batch", batch_ms / 1000.0)
                if profile is not None:
                    self._kwatch.observe("execute", profile.execute_s)
                    self._kwatch.observe("transfer", profile.transfer_s)
        now = time.monotonic()
        generation = self.engine.generation
        for tr in traces:
            if tr is not None:
                tr.marks["engine_out"] = now
        # deliver first, count after: a request cancelled by
        # query(timeout=) mid-score was already counted shed there —
        # counting it served too would make served+shed exceed
        # submissions and skew the health monitor's shed-rate window
        delivered = []
        for i, fut in enumerate(futures):
            res = results[i]
            res.degraded = degraded
            res.latency_ms = (now - t_enq[i]) * 1000.0
            # per-request queue wait + the shared engine wall: host-side
            # subtraction on stamps already taken, no new clock reads
            res.queue_ms = (t_dispatch - t_enq[i]) * 1000.0
            res.execute_ms = batch_ms
            if fut.done():
                continue
            try:
                fut.set_result(res)
            except InvalidStateError:  # timed out in the same instant
                continue
            delivered.append(res)
            self._slo.observe(True)
            # close the span tree AT resolution: the shared-root claim
            # makes a hedge race yield exactly one delivered tree (the
            # later delivery closes as `discarded`)
            self._tracer.close(
                traces[i],
                "delivered",
                profile=profile,
                batch=len(live),
                degraded=degraded,
                generation=generation,
            )
            if self._obs is not None:
                self._obs.observe("serve_latency_ms", res.latency_ms)
        # counters AND latency deques under the lock: _health_signals
        # list()s the deques concurrently, and deque iteration raises on
        # mutation mid-iteration
        with self._lock:
            self._batches += 1
            first_batch = self._batches == 1
            self._served += len(delivered)
            if degraded:
                self._degraded_served += len(delivered)
            for res in delivered:
                self._latencies.append(res.latency_ms)
                self._recent_lat.append(res.latency_ms)
        if first_batch:
            # re-baseline compile-stall detection at first traffic: an
            # engine warmed AFTER service construction must not read as a
            # steady-state compile stall (stall means compiles while
            # serving, not before it)
            from ..obs.metrics import compile_totals

            with self._signals_lock:
                self._last_compile_s = compile_totals()[1]
                self._stall_accum = 0.0
        self._clear_inflight()
        if (
            self._probe_queries
            and not degraded
            and self.engine.probe_count == 0
        ):
            # seed the hot-swap parity probe set from live traffic:
            # accumulate full-service records across batches until the
            # probe budget is met (a single small batch must not leave a
            # one-probe parity set), then capture once; best-effort.
            # capture_probes deliberately RE-SCORES the set as one batch
            # (one extra dispatch, once per lifetime): the stored answers
            # then come from exactly the single-batch scoring the swap
            # replay performs, not rows stitched from differently-shaped
            # batches
            need = self._probe_queries - len(self._probe_buffer)
            if need > 0:
                self._probe_buffer.extend(records[:need])
            if len(self._probe_buffer) >= self._probe_queries:
                try:
                    self.engine.capture_probes(
                        pd.DataFrame.from_records(self._probe_buffer)
                    )
                except Exception as e:  # noqa: BLE001 - probes must not break serving
                    logger.debug("probe capture failed: %s", e)
                self._probe_buffer = []

    def _note_brownout(self, active: bool, q_fill: float) -> None:
        # edge-detect and count under the lock (health() reads both);
        # publish/warn after releasing it — they run subscriber hooks
        with self._lock:
            if active == self._brownout_active:
                return
            self._brownout_active = active
            if active:
                self._brownout_episodes += 1
        from ..obs.events import publish

        if active:
            warn_degraded(
                "serve_brownout",
                "active",
                f"pressure (queue {q_fill:.0%} full, health "
                f"{self._health.state}); serving budgeted top-"
                f"{self.engine.brownout_top_k} answers instead of shedding",
                queue_fill=round(q_fill, 3),
            )
        else:
            publish("brownout_end", queue_fill=round(q_fill, 3))
            logger.info("serve brown-out ended (queue %.0f%% full)",
                        q_fill * 100)

    def _score(self, df, degraded: bool = False,
               profile=None) -> list[QueryResult]:
        approx_out: list = []
        top_p, top_rows, top_valid, n_cand = self.engine.query_arrays(
            df, degraded=degraded, profile=profile, approx_out=approx_out
        )
        approx_used = approx_out[0]
        uids = self.engine.index.unique_id
        out = []
        for i in range(len(df)):
            matches = [
                (uids[top_rows[i, r]], float(top_p[i, r]))
                for r in range(top_p.shape[1])
                if top_valid[i, r]
            ]
            out.append(
                QueryResult(
                    matches=matches,
                    n_candidates=int(n_cand[i]),
                    approx=bool(approx_used[i]),
                )
            )
        return out

    # -- watchdog -------------------------------------------------------

    def _watchdog_loop(self) -> None:
        while not self._watchdog_stop.wait(self.watchdog_interval_s):
            try:
                self._watchdog_tick()
            except Exception as e:  # noqa: BLE001 - the supervisor must survive
                logger.warning("serve watchdog tick failed: %s", e)

    def _watchdog_tick(self) -> None:
        from ..obs.events import publish

        # 1. dead-worker recovery: shed orphans, restart, emit events
        orphans = None
        with self._nonempty:
            t = self._thread
            if t is not None and not t.is_alive() and not self._stop:
                orphans = self._inflight + list(self._queue)
                self._inflight = []
                self._queue.clear()
                self._worker_crashes += 1
                crashes = self._worker_crashes
                self._thread = threading.Thread(
                    target=self._worker, name="splink-serve", daemon=True
                )
                self._thread.start()
        if orphans is not None:
            n = sum(
                self._resolve_shed(entry[1], "worker_restart", entry[4])
                for entry in orphans
            )
            publish("serve_worker_restart", orphaned=n, crashes=crashes,
                    replica=self.name)
            warn_degraded(
                "serve_worker",
                "restarted",
                f"worker thread died; {n} orphaned request(s) resolved "
                "shed and the worker was restarted",
                orphaned=n,
                crashes=crashes,
            )
        # 2. breaker recovery probe when traffic has stopped
        if self.breaker.probe_due():
            with self._lock:
                idle = not self._queue and not self._inflight
            if idle:
                try:
                    self.engine.probe()
                except Exception as e:  # noqa: BLE001 - a failed probe re-opens
                    self.breaker.on_failure()
                    logger.warning("breaker recovery probe failed: %s", e)
                else:
                    if self.breaker.on_success():
                        publish(
                            "breaker",
                            state="closed",
                            reason="watchdog probe succeeded",
                        )
                        logger.info(
                            "serve circuit breaker closed: watchdog probe "
                            "succeeded"
                        )
        # 3. health evaluation from live signals
        self._maybe_evaluate_health()
        # 4. drift windows advance even when traffic stops (an idle
        # service must still age out its rolling drift windows), and the
        # perf-alert state machine ages out of alerting the same way
        self._drift_tick()
        self._perf_tick()

    # -- drift observatory ----------------------------------------------

    def _make_drift_monitor(self):
        sketch = getattr(self.engine, "sketch", None)
        if sketch is None:
            return None
        from ..obs.drift import DriftMonitor

        s = self._settings
        profile = self.engine.index.profile
        # the served-score distribution and the profile's must describe
        # the SAME scoring (both TF-adjusted or both not) — either
        # mismatch (TF engine over a pre-fold unadjusted profile, OR a
        # tf_adjust=False engine over an adjusted profile) would alert on
        # the adjustment delta itself. Re-anchor the score channel dark
        # with a reason instead; the fold-invariant gamma channels stay.
        score_reference = bool(
            getattr(self.engine, "tf_active", False)
        ) == bool(getattr(profile, "tf_adjusted", False))
        return DriftMonitor(
            profile,
            window_s=float(s.get("drift_window_s", 60.0) or 60.0),
            alert_psi=float(s.get("drift_alert_psi", 0.25) or 0.0),
            score_reference=score_reference,
        )

    def _drift_tick(self, force: bool = False) -> None:
        """Drain the engine's drift accumulator when a window bucket is
        due, score the rolling windows and drive the two-window alert
        state machine. Never raises into the worker/watchdog."""
        drift = self._drift
        if drift is None:
            return
        try:
            if not force and not self.engine.drift_drain_due(
                drift.drain_cadence_s
            ):
                return
            window = self.engine.drain_drift()
            if window is None:
                return
            drift.observe(window)
            from ..obs.events import publish

            short = drift.window_drift(drift.window_s)
            if short is not None:
                publish(
                    "drift_window",
                    replica=self.name,
                    window_s=short["window_s"],
                    queries=short["queries"],
                    pairs=short["pairs"],
                    served_pairs=short["served_pairs"],
                    match_yield=short["match_yield"],
                    max_psi=short["max_psi"],
                    channels={
                        ch: v.get("psi")
                        for ch, v in short["channels"].items()
                    },
                    oov_rate=short["oov_rate"],
                    exact_miss_rate=short["exact_miss_rate"],
                    approx_rate=short["approx_rate"],
                )
            self._evaluate_drift_alerts(drift, short=short)
        except Exception as e:  # noqa: BLE001 - obs must not break serving
            logger.warning("drift tick failed: %s", e)

    def _evaluate_drift_alerts(self, drift, short=None) -> None:
        """Alert transitions: entering publishes one ``drift_alert``
        event (which also triggers a flight-recorder dump — the incident
        artifact for "the answers changed"); leaving publishes
        ``drift_clear``. Level-triggered state, edge-triggered events."""
        from ..obs.events import publish

        fired = drift.alerts(short=short)
        if fired and not self._drift_alert_active:
            self._drift_alert_active = True
            publish("drift_alert", replica=self.name, alerts=fired)
            logger.warning(
                "serve drift alert: %s exceed PSI %.3g over both the "
                "%.0fs and %.0fs windows — the served distribution has "
                "moved off the training reference (retrain trigger)",
                ", ".join(a["channel"] for a in fired),
                drift.alert_psi, drift.window_s, drift.long_window_s,
            )
        elif not fired and self._drift_alert_active:
            self._drift_alert_active = False
            publish("drift_clear", replica=self.name)
            logger.info("serve drift alert cleared (replica %s)", self.name)

    def drift_snapshot(self) -> dict:
        """The drift observatory's live report: per-channel PSI/JS over
        the short and long rolling windows vs the training-reference
        profile, serve-side OOV/approx/null rates, fired alerts. A
        profile-less index (or quality_profile off) reports
        ``reference: False`` with the reason — it never raises."""
        from ..obs.drift import no_reference_snapshot

        if self._drift is None:
            if getattr(self.engine.index, "profile", None) is None:
                return no_reference_snapshot()
            return no_reference_snapshot(
                "drift sketching disabled (quality_profile off)"
            )
        snap = self._drift.snapshot()
        snap["alert_active"] = self._drift_alert_active
        return snap

    # -- kernel performance watch ----------------------------------------

    def _perf_tick(self, force: bool = False) -> None:
        """Advance the perf-regression alert state machine (edge-triggered
        ``perf_alert``/``perf_clear`` events — the alert carries the window
        snapshot and dumps the flight recorder) and publish the periodic
        ``perf_window`` report. Host-side only; never raises into the
        worker/watchdog. Evaluation is rate-limited (the drift-tick
        shape): a snapshot sorts every phase's windows, which is O(window)
        work the per-batch path must not pay — ``observe`` stays the only
        per-batch cost. ``force`` skips the cadence gate (tests)."""
        kw = self._kwatch
        if kw is None:
            return
        now = time.monotonic()
        if not force and now - self._last_perf_eval < min(
            1.0, kw.window_s / 8.0
        ):
            return
        self._last_perf_eval = now
        try:
            from ..obs.events import publish

            snap = kw.snapshot()
            fired = snap["alerts"]
            if fired and not self._perf_alert_active:
                self._perf_alert_active = True
                publish(
                    "perf_alert", replica=self.name, alerts=fired,
                    snapshot=snap,
                )
                logger.warning(
                    "serve perf alert: %s p95 regressed past %.3gx the "
                    "post-warmup anchor over both the %.0fs and %.0fs "
                    "windows — the serving kernels got slower",
                    ", ".join(a["phase"] for a in fired),
                    kw.alert_ratio, kw.window_s, kw.long_window_s,
                )
            elif not fired and self._perf_alert_active:
                self._perf_alert_active = False
                publish("perf_clear", replica=self.name)
                logger.info("serve perf alert cleared (replica %s)",
                            self.name)
            now = time.monotonic()
            if now - self._last_perf_window >= kw.window_s / 2.0:
                phases = {
                    name: {
                        "anchor_ms": st["anchor_ms"],
                        "ewma_ms": st["ewma_ms"],
                        "p95_ms": st["short"]["p95_ms"],
                        "n": st["short"]["n"],
                    }
                    for name, st in snap["phases"].items()
                    if st is not None
                }
                if any(p["n"] for p in phases.values()):
                    self._last_perf_window = now
                    publish(
                        "perf_window",
                        replica=self.name,
                        window_s=kw.window_s,
                        phases=phases,
                        alert_active=self._perf_alert_active,
                    )
        except Exception as e:  # noqa: BLE001 - obs must not break serving
            logger.warning("perf tick failed: %s", e)

    def perf_snapshot(self) -> dict:
        """The kernel watch's live report: per-phase post-warmup anchor,
        EWMA and short/long-window p95 plus fired alerts. A service
        without the watch (``perf_alert_ratio`` 0) reports
        ``enabled: False`` with the reason — it never raises."""
        if self._kwatch is None:
            return {
                "enabled": False,
                "reason": "kernel watch disabled (perf_alert_ratio 0)",
                "alerts": [],
            }
        snap = self._kwatch.snapshot()
        snap["enabled"] = True
        snap["alert_active"] = self._perf_alert_active
        return snap

    # -- health ---------------------------------------------------------

    def _health_signals(self) -> dict:
        from ..obs.metrics import compile_totals

        with self._lock:
            served, shed = self._served, self._shed_count
            q_fill = (
                len(self._queue) / self.queue_depth if self.queue_depth else 0.0
            )
            worker = self._thread
            alive = worker is not None and worker.is_alive()
            brownout = self._brownout_active
            recent = list(self._recent_lat)
            swapping = self._swap_in_progress
        _, c_secs = compile_totals()
        # the window marks are shared state consumed by BOTH the watchdog
        # tick and on-demand health() calls: the read-update must be
        # atomic, and compile-stall detection accumulates across windows
        # so a real stall cannot hide in the slivers concurrent pollers
        # split the window into (a compile-free window clears it)
        with self._signals_lock:
            d_served = served - self._hw_served
            d_shed = shed - self._hw_shed
            self._hw_served, self._hw_shed = served, shed
            delta_c = c_secs - self._last_compile_s
            self._last_compile_s = c_secs
            if swapping or delta_c <= 0:
                self._stall_accum = 0.0
            else:
                self._stall_accum += delta_c
            stall = self._stall_accum > self.compile_stall_s
        total = d_served + d_shed
        shed_rate = (d_shed / total) if total else 0.0
        p95 = (
            float(np.percentile(np.asarray(recent, np.float64), 95))
            if recent
            else None
        )
        return {
            "worker_alive": alive,
            "breaker": self.breaker.state,
            "queue_fill": round(q_fill, 4),
            "shed_rate": round(shed_rate, 4),
            "p95_ms": p95,
            "compile_stall": stall,
            "brownout": brownout,
        }

    def _maybe_evaluate_health(self) -> None:
        """Advance the health state machine at most once per watchdog
        interval: ``recover_ticks`` hysteresis is calibrated to that
        cadence, and a fast external poller must not inflate the recovery
        streak (or starve the shed-rate window)."""
        now = time.monotonic()
        with self._signals_lock:
            if now - self._last_health_eval < self.watchdog_interval_s:
                return
            self._last_health_eval = now
        self._health.evaluate(self._health_signals())

    def health(self) -> dict:
        """The replica's live health: advances the state machine (rate-
        limited to the watchdog cadence — polling cannot defeat the
        recovery hysteresis) and returns its snapshot plus breaker/engine
        context (the endpoint the :class:`~.router.ReplicaRouter` routes
        on)."""
        self._maybe_evaluate_health()
        snap = self._health.snapshot()
        snap["breaker"] = self.breaker.snapshot()
        snap["generation"] = self.engine.generation
        with self._lock:
            snap["worker_crashes"] = self._worker_crashes
            snap["brownout_episodes"] = self._brownout_episodes
        return snap

    @property
    def health_state(self) -> str:
        """Current state WITHOUT re-evaluating (router fast path)."""
        return self._health.state

    # -- index hot-swap -------------------------------------------------

    def swap_index(self, source, *, refresh_probes: bool = False) -> dict:
        """Hot-swap the engine's index (see
        :meth:`~.engine.QueryEngine.swap_index`): validation and pre-warm
        happen while this service KEEPS SERVING the old index; the flip is
        atomic and in-flight batches drain on the old index. The swap's
        own compiles are excluded from the health monitor's compile-stall
        signal."""
        from ..obs.metrics import compile_totals

        with self._lock:
            self._swap_in_progress = True
        try:
            stats = self.engine.swap_index(
                source, refresh_probes=refresh_probes
            )
        finally:
            with self._lock:
                self._swap_in_progress = False
            with self._signals_lock:
                self._last_compile_s = compile_totals()[1]
                self._stall_accum = 0.0
        # the committed index may carry a different (or no) reference
        # profile: rebind the drift observatory to the new engine state —
        # old windows describe the old reference and must not score
        # against the new one
        self._drift = self._make_drift_monitor()
        self._drift_alert_active = False
        # a new index changes the legitimate steady-state cost of every
        # phase: re-anchor the kernel watch on post-swap traffic (a stale
        # anchor would judge the new index against the old one's speed —
        # false latched alerts after growing the index, masked
        # regressions after shrinking it)
        if self._kwatch is not None:
            from ..obs.kernelwatch import KernelWatch

            self._kwatch = KernelWatch(
                window_s=self._kwatch.window_s,
                alert_ratio=self._kwatch.alert_ratio,
            )
            self._perf_alert_active = False
        return stats

    # -- reporting ------------------------------------------------------

    def latency_summary(self) -> dict:
        """p50/p95/p99 request latency (ms), counts, throughput and the
        resilience counters over the service's lifetime."""
        # snapshot under the lock: the worker appends concurrently (deque
        # iteration raises on mutation) and bumps every counter below
        with self._lock:
            lats = np.asarray(self._latencies, np.float64)
            served = self._served
            shed = self._shed_count
            batches = self._batches
            degraded_served = self._degraded_served
            timeouts = self._timeouts
            brownout_episodes = self._brownout_episodes
            worker_crashes = self._worker_crashes
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        out = {
            "served": served,
            "shed": shed,
            "batches": batches,
            "queries_per_sec": served / elapsed,
            "degraded_served": degraded_served,
            "timeouts": timeouts,
            "brownout_episodes": brownout_episodes,
            "worker_crashes": worker_crashes,
            "breaker_state": self.breaker.state,
            "breaker_opened_total": self.breaker.opened_total,
            "health": self._health.state,
            "index_generation": self.engine.generation,
        }
        if len(lats):
            p50, p95, p99 = np.percentile(lats, [50, 95, 99])
            out.update(
                p50_ms=float(p50), p95_ms=float(p95), p99_ms=float(p99),
                mean_ms=float(lats.mean()),
            )
        if self._tracer.enabled:
            out["traces"] = self._tracer.snapshot()
        return out

    def phase_summary(self) -> dict:
        """p50/p99 per phase (ms) over the recent delivered traces —
        empty when tracing is off (``serve_trace_sample_rate`` 0). The
        tail-latency attribution bench.py's serve mode emits."""
        return self._tracer.phase_summary()

    def slo_snapshot(self) -> dict:
        """Rolling hit rate + multi-window burn rates
        (:class:`~..obs.slo.SLOTracker`): delivered = good, shed = bad."""
        return self._slo.snapshot()

    def fleet_stats(self) -> dict:
        """Mergeable, JSON-serialisable stats export for metric federation
        (:mod:`..obs.fleet`; served over the wire as the ``stats``
        envelope). Everything here merges by construction: counters add,
        the kernel watch's log2-bucket histograms add element-wise with
        an exact ``sum``, the SLO tracker's time-bucketed ring adds per
        bucket index, and the drift aggregates are integer count tensors
        — so a :class:`~..obs.fleet.FleetAggregator` merge of N hosts'
        exports equals the single-tracker view of the union of raw
        observations bit-exactly (``make fleet-smoke`` gates this)."""
        with self._lock:
            served = self._served
            shed = self._shed_count
            batches = self._batches
            timeouts = self._timeouts
            degraded_served = self._degraded_served
            worker_crashes = self._worker_crashes
            brownout_episodes = self._brownout_episodes
        out = {
            "replica": self.name,
            "t_mono": time.monotonic(),
            "health": self._health.state,
            "breaker_state": self.breaker.state,
            "index_generation": self.engine.generation,
            "counters": {
                "served": served,
                "shed": shed,
                "batches": batches,
                "timeouts": timeouts,
                "degraded_served": degraded_served,
                "worker_crashes": worker_crashes,
                "brownout_episodes": brownout_episodes,
            },
            "slo": self._slo.export(),
        }
        kw = self._kwatch
        if kw is not None:
            from ..obs.kernelwatch import HIST_EDGES

            phases = {}
            for phase in kw.phases():
                hist = kw.histogram(phase)
                if hist is None:
                    continue
                counts, _edges, total, n = hist
                if n:
                    phases[phase] = {
                        "counts": [int(c) for c in counts],
                        "sum": float(total),
                        "n": int(n),
                    }
            out["perf"] = {"edges": list(HIST_EDGES), "phases": phases}
        drift = self._drift
        if drift is not None:
            try:
                out["drift"] = drift.export_aggregate()
            except Exception as e:  # noqa: BLE001 - federation must not break serving
                logger.warning("drift export failed: %s", e)
        return out

    @property
    def flight_recorder(self):
        return self._flight

    def prometheus_samples(self) -> list:
        """The service's metric families for the text-exposition endpoint
        (:mod:`..obs.exposition`). Reads the same locked snapshots the
        JSON endpoints use; safe from the scrape thread."""
        from ..obs.exposition import Sample

        from .health import health_rank

        replica = {"replica": self.name}
        summary = self.latency_summary()
        with self._lock:
            queue_len = len(self._queue)
        out = [
            Sample("splink_serve_served_total", summary["served"], replica,
                   "counter", "Requests delivered with matches"),
            Sample("splink_serve_shed_total", summary["shed"], replica,
                   "counter", "Requests shed (all machine-readable reasons)"),
            Sample("splink_serve_batches_total", summary["batches"], replica,
                   "counter", "Engine batches dispatched"),
            Sample("splink_serve_timeouts_total", summary["timeouts"],
                   replica, "counter", "query(timeout=) cancellations"),
            Sample("splink_serve_worker_crashes_total",
                   summary["worker_crashes"], replica, "counter",
                   "Worker deaths recovered by the watchdog"),
            Sample("splink_serve_brownout_episodes_total",
                   summary["brownout_episodes"], replica, "counter",
                   "Brown-out episodes entered"),
            Sample("splink_serve_queries_per_sec",
                   summary["queries_per_sec"], replica, "gauge",
                   "Lifetime served throughput"),
            Sample("splink_serve_queue_fill",
                   (queue_len / self.queue_depth)
                   if self.queue_depth else 0.0,
                   replica, "gauge", "Bounded-queue occupancy 0..1"),
            Sample("splink_serve_health_rank",
                   health_rank(self._health.state), replica, "gauge",
                   "0 healthy / 1 degraded / 2 broken"),
            Sample("splink_serve_breaker_open",
                   1.0 if self.breaker.state == "open" else 0.0, replica,
                   "gauge", "Circuit breaker open"),
            Sample("splink_serve_index_generation",
                   summary["index_generation"], replica, "gauge",
                   "Committed hot-swaps"),
        ]
        for q in ("p50_ms", "p95_ms", "p99_ms"):
            if q in summary:
                out.append(Sample(
                    "splink_serve_latency_ms", summary[q],
                    {**replica, "quantile": q[:-3]}, "gauge",
                    "Request latency quantiles (ms)",
                ))
        for phase, stats in self.phase_summary().items():
            # "wall" is the pseudo-series totalling the real phases: keep
            # it OUT of the phase label — phases already sum to wall, so a
            # PromQL sum over the label would double-count
            metric = (
                "splink_serve_trace_wall_ms"
                if phase == "wall"
                else "splink_serve_phase_ms"
            )
            for q in ("p50_ms", "p99_ms"):
                labels = {**replica, "quantile": q[:-3]}
                if phase != "wall":
                    labels["phase"] = phase
                out.append(Sample(
                    metric, stats[q], labels, "gauge",
                    "Traced wall latency (ms)" if phase == "wall"
                    else "Tail-latency attribution per phase (ms)",
                ))
        slo = self._slo.snapshot()
        out.append(Sample(
            "splink_serve_slo_objective", slo["objective"], replica,
            "gauge", "Delivery objective",
        ))
        for window, stats in slo["windows"].items():
            labels = {**replica, "window_s": window}
            if stats["hit_rate"] is not None:
                out.append(Sample(
                    "splink_serve_slo_hit_rate", stats["hit_rate"], labels,
                    "gauge", "Rolling delivered/total per window",
                ))
            out.append(Sample(
                "splink_serve_slo_burn_rate", stats["burn_rate"], labels,
                "gauge", "Error-budget burn rate per window",
            ))
        if self._tracer.enabled:
            trace = self._tracer.snapshot()
            out.append(Sample(
                "splink_serve_traces_sampled_total", trace["sampled"],
                replica, "counter", "Requests sampled for tracing",
            ))
            for outcome, n in trace["outcomes"].items():
                out.append(Sample(
                    "splink_serve_traces_closed_total", n,
                    {**replica, "outcome": outcome}, "counter",
                    "Closed span trees by outcome",
                ))
        out.extend(self._drift_samples(replica))
        out.extend(self._perf_samples(replica))
        from ..obs.exposition import process_samples

        out.extend(process_samples())
        return out

    def _perf_samples(self, replica: dict) -> list:
        """Kernel-watch series: watch presence, the alert gauge,
        per-phase anchor/EWMA/window-p95 gauges and the per-phase
        execute-time distribution as a NATIVE Prometheus histogram with
        an exact ``_sum`` (the watch accumulates raw seconds)."""
        from ..obs.exposition import HistogramSample, Sample

        kw = self._kwatch
        out = [Sample(
            "splink_serve_perf_watch",
            1.0 if kw is not None else 0.0, replica, "gauge",
            "KernelWatch execute-latency regression monitor enabled",
        )]
        if kw is None:
            return out
        out.append(Sample(
            "splink_serve_perf_alert",
            1.0 if self._perf_alert_active else 0.0, replica, "gauge",
            "Two-window execute-latency regression alert firing",
        ))
        for phase in kw.phases():
            st = kw.phase_stats(phase)
            if st is None:
                continue
            labels = {**replica, "phase": phase}
            if st["anchor_ms"] is not None:
                out.append(Sample(
                    "splink_serve_perf_anchor_ms", st["anchor_ms"], labels,
                    "gauge", "Post-warmup steady-state anchor (ms)",
                ))
            if st["ewma_ms"] is not None:
                out.append(Sample(
                    "splink_serve_perf_ewma_ms", st["ewma_ms"], labels,
                    "gauge", "Smoothed execute-time trend (ms)",
                ))
            for window in ("short", "long"):
                p95 = st[window]["p95_ms"]
                if p95 is not None:
                    out.append(Sample(
                        "splink_serve_perf_p95_ms", p95,
                        {**labels, "window": window}, "gauge",
                        "Rolling-window p95 execute time (ms)",
                    ))
            hist = kw.histogram(phase)
            if hist is not None:
                # n can exceed sum(counts): past-last-edge observations
                # live only in the +Inf bucket the renderer appends
                counts, edges, total, n = hist
                if n:
                    cum = 0
                    buckets = []
                    for c, e in zip(counts, edges):
                        cum += c
                        buckets.append((e, cum))
                    out.append(HistogramSample(
                        name="splink_serve_phase_seconds",
                        buckets=buckets,
                        sum=total,
                        count=n,
                        labels=labels,
                        help="Per-phase execute-time distribution "
                             "(seconds; exact sum)",
                    ))
        return out

    def _drift_samples(self, replica: dict) -> list:
        """Drift-observatory series: reference presence, per-channel PSI
        over the short window, serve-side rates, the alert gauge and the
        served-score distribution as a NATIVE Prometheus histogram
        (``_bucket``/``_sum``/``_count`` with cumulative ``le`` bounds)."""
        from ..obs.exposition import Sample, histogram_from_counts

        drift = self.drift_snapshot()
        out = [Sample(
            "splink_serve_drift_reference",
            1.0 if drift.get("reference") else 0.0, replica, "gauge",
            "Training-reference quality profile present and sketching on",
        )]
        if not drift.get("reference"):
            return out
        out.append(Sample(
            "splink_serve_drift_alert",
            1.0 if drift.get("alerts") else 0.0, replica, "gauge",
            "Two-window PSI drift alert firing",
        ))
        short = drift.get("short") or {}
        for channel, v in sorted((short.get("channels") or {}).items()):
            if v.get("psi") is not None:
                out.append(Sample(
                    "splink_serve_drift_psi", v["psi"],
                    {**replica, "channel": channel}, "gauge",
                    "PSI of the rolling short window vs the training "
                    "reference, per channel",
                ))
        for key, metric in (
            ("oov_rate", "splink_serve_drift_oov_rate"),
            ("exact_miss_rate", "splink_serve_drift_exact_miss_rate"),
            ("approx_rate", "splink_serve_drift_approx_rate"),
        ):
            if short.get(key) is not None:
                out.append(Sample(
                    metric, short[key], replica, "gauge",
                    "Serve-side rate over the short drift window",
                ))
        if short.get("match_yield") is not None:
            out.append(Sample(
                "splink_serve_drift_match_yield", short["match_yield"],
                replica, "gauge",
                "Matched top-k pairs / served top-k pairs over the short "
                "drift window (collapse = catastrophic upstream drift)",
            ))
        monitor = self._drift
        if monitor is not None and monitor.profile is not None:
            counts = monitor.score_window_counts(monitor.window_s)
            if counts is not None and counts.sum() > 0:
                bins = monitor.profile.bins
                edges = [(i + 1) / bins for i in range(bins)]
                out.append(histogram_from_counts(
                    "splink_serve_drift_score", counts, edges, replica,
                    "Served match-probability distribution over the short "
                    "drift window (sum approximated from bin midpoints)",
                ))
        return out
