"""Micro-batching front-end: single queries in, coalesced device batches out.

Accelerators amortise dispatch over batches; online traffic arrives one
record at a time. :class:`LinkageService` bridges the two with the classic
micro-batching loop: ``submit`` enqueues a record and returns a future, a
worker thread coalesces everything queued within ``deadline_ms`` of the
FIRST waiting record (or until a full largest query bucket accumulates,
whichever comes first) into one engine dispatch, and each future resolves
with its record's matches.

Admission control is a bounded queue that SHEDS instead of OOMing: when
``queue_depth`` records are already waiting, ``submit`` resolves the future
immediately with ``shed=True`` and emits the structured degradation record
(``logging_utils.warn_degraded`` — the same channel the offline degradation
ladder uses), so overload is a measured, observable state rather than a
crash. Nothing raises on the submit path.

Per-request latency (enqueue -> result set) feeds a bounded reservoir;
:meth:`latency_summary` reports p50/p95/p99 and throughput, and with a
telemetry ``RunContext`` the summary lands in the run record (``python -m
splink_tpu.obs summarize`` renders it) alongside per-batch ``serve_batch``
spans.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from ..utils.logging_utils import warn_degraded

logger = logging.getLogger("splink_tpu")

_LATENCY_RESERVOIR = 65536  # newest-N latency samples kept for percentiles


@dataclass
class QueryResult:
    """One query's outcome."""

    matches: list = field(default_factory=list)  # [(ref_uid, probability)]
    n_candidates: int = 0
    shed: bool = False
    latency_ms: float | None = None


class LinkageService:
    """Micro-batching query front-end over a :class:`~.engine.QueryEngine`
    (module docstring)."""

    def __init__(
        self,
        engine,
        *,
        queue_depth: int | None = None,
        deadline_ms: float | None = None,
        autostart: bool = True,
        telemetry=None,
    ):
        settings = engine.index.settings
        self.engine = engine
        self.queue_depth = int(
            queue_depth
            if queue_depth is not None
            else settings.get("serve_queue_depth", 1024) or 1024
        )
        self.deadline_ms = float(
            deadline_ms
            if deadline_ms is not None
            else settings.get("serve_deadline_ms", 5.0)
        )
        self._obs = telemetry
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queue: deque = deque()  # (record, future, t_enqueue)
        self._latencies: deque = deque(maxlen=_LATENCY_RESERVOIR)
        self._shed_count = 0
        self._served = 0
        self._batches = 0
        self._t_start = time.monotonic()
        self._stop = False
        self._thread: threading.Thread | None = None
        if autostart:
            self.start()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "LinkageService":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._worker, name="splink-serve", daemon=True
            )
            self._thread.start()
        return self

    def close(self, drain: bool = True) -> None:
        """Stop the worker. With ``drain`` (default) queued requests are
        served first; otherwise they resolve shed."""
        with self._nonempty:
            self._stop = True
            if not drain:
                while self._queue:
                    _, fut, _ = self._queue.popleft()
                    self._shed_count += 1
                    fut.set_result(QueryResult(shed=True))
            self._nonempty.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # a submit racing the shutdown can enqueue after the worker's last
        # batch; resolve any stragglers shed so no future hangs forever
        with self._nonempty:
            while self._queue:
                _, fut, _ = self._queue.popleft()
                self._shed_count += 1
                if not fut.done():
                    fut.set_result(QueryResult(shed=True))
        if self._obs is not None:
            self._obs.record("serve_latency", self.latency_summary())

    def __enter__(self) -> "LinkageService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission -----------------------------------------------------

    def submit(self, record: dict) -> Future:
        """Enqueue one query record; never raises. Over ``queue_depth``
        waiting records — or after :meth:`close` (no worker will ever
        drain the queue again) — the request is shed: the future resolves
        immediately with ``shed=True`` and a degradation event is
        emitted."""
        fut: Future = Future()
        with self._nonempty:
            closed = self._stop and self._thread is None
            if closed or len(self._queue) >= self.queue_depth:
                self._shed_count += 1
                shed_total = self._shed_count
                fut.set_result(QueryResult(shed=True))
                reason = (
                    "service is closed; submissions resolve shed"
                    if closed
                    else f"bounded queue full ({self.queue_depth} waiting); "
                    "shedding instead of growing without bound"
                )
            else:
                self._queue.append((record, fut, time.monotonic()))
                self._nonempty.notify()
                return fut
        # outside the lock: warn_degraded publishes + warns, both of which
        # may run user hooks
        warn_degraded("serve_queue", "shed", reason, shed_total=shed_total)
        return fut

    def query(self, record: dict, timeout: float | None = None) -> QueryResult:
        """Submit one record and wait for its result."""
        return self.submit(record).result(timeout=timeout)

    # -- worker ---------------------------------------------------------

    def _worker(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            self._serve_batch(batch)

    def _take_batch(self):
        """Block until work exists, then coalesce until the deadline (from
        the FIRST waiting record) or a full largest bucket."""
        max_batch = self.engine.policy.max_batch
        with self._nonempty:
            while not self._queue:
                if self._stop:
                    return None
                self._nonempty.wait(timeout=0.1)
            deadline = self._queue[0][2] + self.deadline_ms / 1000.0
            while len(self._queue) < max_batch and not self._stop:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(timeout=remaining)
            take = min(len(self._queue), max_batch)
            return [self._queue.popleft() for _ in range(take)]

    def _serve_batch(self, batch) -> None:
        import pandas as pd

        records = [b[0] for b in batch]
        futures = [b[1] for b in batch]
        t_enq = [b[2] for b in batch]
        try:
            df = pd.DataFrame.from_records(records)
            if self._obs is not None:
                with self._obs.span("serve_batch", batch=len(batch)):
                    results = self._score(df)
            else:
                results = self._score(df)
        except Exception as e:  # noqa: BLE001 - one bad batch must not kill the loop
            logger.exception("serve batch failed")
            for fut in futures:
                if not fut.done():
                    fut.set_exception(e)
            return
        now = time.monotonic()
        self._batches += 1
        for i, fut in enumerate(futures):
            res = results[i]
            res.latency_ms = (now - t_enq[i]) * 1000.0
            self._latencies.append(res.latency_ms)
            self._served += 1
            if self._obs is not None:
                self._obs.observe("serve_latency_ms", res.latency_ms)
            if not fut.done():
                fut.set_result(res)

    def _score(self, df) -> list[QueryResult]:
        top_p, top_rows, top_valid, n_cand = self.engine.query_arrays(df)
        uids = self.engine.index.unique_id
        out = []
        for i in range(len(df)):
            matches = [
                (uids[top_rows[i, r]], float(top_p[i, r]))
                for r in range(top_p.shape[1])
                if top_valid[i, r]
            ]
            out.append(
                QueryResult(matches=matches, n_candidates=int(n_cand[i]))
            )
        return out

    # -- reporting ------------------------------------------------------

    def latency_summary(self) -> dict:
        """p50/p95/p99 request latency (ms), counts and throughput over the
        service's lifetime."""
        lats = np.asarray(self._latencies, np.float64)
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        out = {
            "served": self._served,
            "shed": self._shed_count,
            "batches": self._batches,
            "queries_per_sec": self._served / elapsed,
        }
        if len(lats):
            p50, p95, p99 = np.percentile(lats, [50, 95, 99])
            out.update(
                p50_ms=float(p50), p95_ms=float(p95), p99_ms=float(p99),
                mean_ms=float(lats.mean()),
            )
        return out
