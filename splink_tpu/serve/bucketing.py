"""Shape buckets: the compile-cache contract between serving and XLA.

XLA compiles one executable per input shape, and a compile costs seconds to
tens of seconds — catastrophic inside a latency budget. Serving therefore
quantises both dynamic axes to a small fixed menu of power-of-two buckets:

  * the QUERY axis (how many records a micro-batch coalesced), and
  * the CANDIDATE axis (the padded per-query candidate capacity, driven by
    the largest blocking bucket the batch touches).

A batch pads up to the next bucket on each axis, so every dispatch hits one
of ``len(query_buckets) x len(candidate_buckets)`` compiled programs. The
policy's :meth:`warmup_combinations` enumerates them for the engine's
warmup pass; after warmup, steady-state serving performs ZERO recompiles —
measured, not assumed, via the ``jax.monitoring`` compile counter already
wired into :mod:`..obs.metrics` (the bucketing test and ``make
serve-smoke`` both assert the counter stays flat).

Buckets are configurable through the ``serve_query_buckets`` /
``serve_candidate_buckets`` settings keys (power-of-two, ascending). A
query batch larger than the largest query bucket splits into chunks; a
blocking block larger than the largest candidate bucket is truncated with
a structured degradation warning (the skewed-block hazard
``blocking.block_size_stats`` reports offline).
"""

from __future__ import annotations

from dataclasses import dataclass


def _validate_buckets(name: str, buckets) -> tuple[int, ...]:
    out = tuple(int(b) for b in buckets)
    if not out:
        raise ValueError(f"{name} must not be empty")
    for b in out:
        if b < 1 or (b & (b - 1)) != 0:
            raise ValueError(
                f"{name} entries must be powers of two >= 1, got {b}"
            )
    if list(out) != sorted(set(out)):
        raise ValueError(f"{name} must be strictly ascending, got {list(out)}")
    return out


def bucket_for(n: int, buckets: tuple[int, ...]) -> int | None:
    """The smallest bucket >= n, or None when n exceeds the largest."""
    for b in buckets:
        if n <= b:
            return b
    return None


@dataclass(frozen=True)
class BucketPolicy:
    """The serving shape menu (see module docstring)."""

    query_buckets: tuple[int, ...]
    candidate_buckets: tuple[int, ...]

    DEFAULT_QUERY_BUCKETS = (16, 128, 1024)
    DEFAULT_CANDIDATE_BUCKETS = (32, 256, 2048)

    @classmethod
    def from_settings(cls, settings: dict) -> "BucketPolicy":
        return cls(
            query_buckets=_validate_buckets(
                "serve_query_buckets",
                settings.get("serve_query_buckets")
                or cls.DEFAULT_QUERY_BUCKETS,
            ),
            candidate_buckets=_validate_buckets(
                "serve_candidate_buckets",
                settings.get("serve_candidate_buckets")
                or cls.DEFAULT_CANDIDATE_BUCKETS,
            ),
        )

    def __post_init__(self):
        _validate_buckets("serve_query_buckets", self.query_buckets)
        _validate_buckets("serve_candidate_buckets", self.candidate_buckets)

    @property
    def max_batch(self) -> int:
        """The largest query micro-batch one dispatch serves."""
        return self.query_buckets[-1]

    def query_bucket(self, n: int) -> int | None:
        return bucket_for(n, self.query_buckets)

    def candidate_bucket(self, n: int) -> int | None:
        return bucket_for(n, self.candidate_buckets)

    def iter_query_chunks(self, n: int):
        """Yield ``(q_pad, start, stop)`` chunks covering ``n`` queries:
        full largest-bucket chunks, then one bucketed tail."""
        start = 0
        biggest = self.query_buckets[-1]
        while n - start > biggest:
            yield biggest, start, start + biggest
            start += biggest
        if n - start > 0:
            yield self.query_bucket(n - start), start, n

    def warmup_combinations(self) -> list[tuple[int, int]]:
        """Every (query_bucket, candidate_bucket) shape the steady state
        can dispatch — the engine warmup compiles each exactly once."""
        return [
            (qb, cb)
            for qb in self.query_buckets
            for cb in self.candidate_buckets
        ]
