"""Online linkage serving: frozen index artifact, shape-bucketed query
engine, micro-batching front-end.

The offline pipeline answers "score every candidate pair of these tables";
this package answers "which reference records match THIS record, now":

    linker = Splink(settings, df=reference_df)
    linker.estimate_parameters()
    index = linker.export_index("index_dir")         # frozen artifact

    # in the serving process
    from splink_tpu.serve import load_index, QueryEngine, LinkageService
    engine = QueryEngine(load_index("index_dir"),
                         aot_dir="index_dir/aot")     # AOT sidecar (if built)
    engine.warmup()     # restore the whole bucket menu without the backend
    engine.save_aot()   # compiler (zero compiles), or compile + persist it
    with LinkageService(engine) as svc:
        result = svc.query({"first_name": "amelia", "surname": "smith",
                            "dob": "1987"})

Resilience (docs/serving.md#resilience): per-replica health states with
hysteresis (:mod:`.health`), deadline admission + brown-out + circuit
breaker (:mod:`.admission`, threaded through the service), health-aware
replica routing with hedged requests (:mod:`.router`), chaos-tested index
hot-swap with parity probes and rollback
(:meth:`QueryEngine.swap_index`), and a watchdog that recovers from
worker-thread death. ``make chaos-smoke`` drives every registered serve
fault site against those guarantees.

Multi-host (docs/serving.md#multi-host): :class:`WireServer` puts a
service behind a stdlib-only length-prefixed TCP protocol and
:class:`RemoteReplica` wraps the far end back into the :class:`Replica`
duck-type, so the SAME router routes, hedges and fails over across hosts
— connection pools with bounded-backoff reconnect, per-remote circuit
breakers, deadline propagation and piggybacked health included. ``make
wire-smoke`` drives the network fault kinds (drop, delay, torn frame,
partition) against the same no-hang / no-escape guarantees.

See docs/serving.md for the artifact format, bucket policy and latency
tuning knobs, and ``python -m splink_tpu.serve`` for the CLI.
"""

from .admission import CircuitBreaker, WaitEstimator
from .aot import AotStore, AotStoreError
from .bucketing import BucketPolicy, bucket_for
from .engine import IndexSwapError, QueryEngine
from .health import BROKEN, DEGRADED, HEALTHY, HealthMonitor
from .index import (
    IndexMismatchError,
    LinkageIndex,
    QueryBatch,
    ServeIndexError,
    ServeRule,
    build_index,
    load_index,
)
from .remote import RemoteReplica
from .router import Replica, ReplicaRouter
from .service import LinkageService, QueryResult
from .wire import (
    CorruptFrame,
    FrameTooLarge,
    TornFrame,
    WireError,
    WireServer,
)

__all__ = [
    "AotStore",
    "AotStoreError",
    "BucketPolicy",
    "bucket_for",
    "QueryEngine",
    "IndexSwapError",
    "LinkageIndex",
    "QueryBatch",
    "ServeRule",
    "ServeIndexError",
    "IndexMismatchError",
    "build_index",
    "load_index",
    "LinkageService",
    "QueryResult",
    "Replica",
    "ReplicaRouter",
    "RemoteReplica",
    "WireServer",
    "WireError",
    "FrameTooLarge",
    "TornFrame",
    "CorruptFrame",
    "HealthMonitor",
    "HEALTHY",
    "DEGRADED",
    "BROKEN",
    "CircuitBreaker",
    "WaitEstimator",
]
