"""Blocking diagnostics: predict skew / explosion before running.

Port of the reference's get_largest_blocks
(/root/reference/splink/comparison_evaluation.py:12-34): extract the columns
a blocking rule keys on, and report the most frequent key values — the blocks
that will dominate pair generation.
"""

from __future__ import annotations

import re


def blocking_rule_columns(blocking_rule: str) -> list[str]:
    """Every l.-side column the rule references, in order, deduplicated —
    robust to function-of-column keys (``substr(l.surname, 1, 3) = ...``)
    and cross-column equalities (``l.first_name = r.surname``), which the
    reference's split-on-space-or-'=' parse would mangle into pseudo-column
    names. For a derived key the diagnostic groups by the underlying raw
    column — a superset blocking of the derived key, so still the right
    skew probe."""
    seen: dict[str, None] = {}
    for m in re.finditer(r"\bl\.(\w+)", blocking_rule):
        seen.setdefault(m.group(1))
    return list(seen)


def get_largest_blocks(blocking_rule: str, df, limit: int = 5):
    """Top-``limit`` key values by row count for a rule's join columns.

    Args:
        blocking_rule: e.g. ``"l.first_name = r.first_name"``.
        df: the input pandas DataFrame.

    Returns a DataFrame of the key columns plus a ``count`` column,
    descending — block pair counts scale with count^2.
    """
    cols = blocking_rule_columns(blocking_rule)
    if not cols:
        raise ValueError(f"Could not find any l.column references in {blocking_rule!r}")
    sub = df[cols].dropna()
    counts = (
        sub.groupby(cols, sort=False)
        .size()
        .reset_index(name="count")
        .sort_values("count", ascending=False, kind="stable")
        .head(limit)
        .reset_index(drop=True)
    )
    return counts
