"""Translation of reference-splink SQL surface syntax into splink_tpu specs.

The reference configures comparisons with SQL CASE expressions
(/root/reference/splink/case_statements.py:62-277) and blocking with SQL join
predicates (/root/reference/splink/blocking.py:95-160). splink_tpu's native
configuration is declarative spec dicts, but for drop-in compatibility we
recognise the reference's generated CASE shapes and equality-join blocking
rules and translate them. Anything unrecognised raises with a pointer to the
native spec format.
"""

from __future__ import annotations

import re

_NUM = r"([0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)"


class SqlTranslationError(ValueError):
    pass


def _normalise(expr: str) -> str:
    s = expr.replace("\n", " ").replace("\r", " ")
    s = re.sub(r"\s+", " ", s).strip()
    return s


def parse_case_expression(expr: str, num_levels: int) -> dict:
    """Translate a recognised SQL CASE expression into a comparison spec dict.

    Recognised families (the shapes the reference's generators emit):
      * strict equality          -> {"kind": "exact"}
      * jaro_winkler_sim(...) > t chains -> {"kind": "jaro_winkler", "thresholds": [...]}
      * levenshtein(...)/avg-len <= t chains (with equality top level)
                                 -> {"kind": "levenshtein", "thresholds": [...]}
      * abs(a - b) < t chains    -> {"kind": "numeric_abs", "thresholds": [...]}
      * abs(a - b)/abs(max) < t  -> {"kind": "numeric_perc", "thresholds": [...]}

    thresholds[0] always gates the top similarity level.
    """
    s = _normalise(expr).lower()

    if "jaro_winkler_sim" in s and "ifnull" in s:
        # The reference's name-inversion generator
        # (/root/reference/splink/case_statements.py:254-277): an OR-list of
        # jw(col_l, ifnull(other_r, ...)) terms at level 2.
        spec = _parse_name_inversion(s)
        if spec is not None:
            if num_levels != 4:
                raise SqlTranslationError(
                    "name-inversion case_expression emits gamma levels 0-3 "
                    f"but num_levels={num_levels}; set num_levels to 4: {expr!r}"
                )
            return spec

    if "jaro_winkler_sim" in s:
        pairs = re.findall(rf"jaro_winkler_sim\([^)]*\)\s*>\s*{_NUM}\s*then\s*(\d+)", s)
        if pairs:
            _check_generated_frame(expr, s)
            _check_level_coverage(expr, pairs, num_levels)
            by_level = sorted(pairs, key=lambda p: -int(p[1]))
            return {"kind": "jaro_winkler", "thresholds": [float(t) for t, _ in by_level]}

    if "levenshtein" in s:
        # Reference shape (/root/reference/splink/case_statements.py:117-141):
        # strict equality gates the TOP level, levenshtein-ratio thresholds
        # gate levels num_levels-2 .. 1.
        pairs = re.findall(rf"<=\s*{_NUM}\s*then\s*(\d+)", s)
        anchored = re.findall(
            rf"levenshtein\([^)]*\)\s*/[^<]*<=\s*{_NUM}\s*then\s*(\d+)", s
        )
        if pairs and len(anchored) != len(pairs):
            raise SqlTranslationError(
                "case_expression mixes levenshtein-ratio thresholds with "
                f"other <= conditions; not a generated shape: {expr!r}"
            )
        if pairs:
            _check_generated_frame(expr, s)
            levels = {int(lv) for _, lv in pairs}
            eq = re.search(r"when\s+(\w+)_l\s*=\s*\1_r\s+then\s+(\d+)", s)
            if (
                levels != set(range(1, num_levels - 1))
                or not eq
                or int(eq.group(2)) != num_levels - 1
            ):
                raise SqlTranslationError(
                    f"levenshtein case_expression gates levels {sorted(levels)} "
                    f"(equality level: {eq.group(2) if eq else 'missing'}) but "
                    f"num_levels={num_levels}; this CASE shape is not fully "
                    f"recognised: {expr!r}. Provide a native 'comparison' spec."
                )
            return {"kind": "levenshtein", "thresholds": [
                float(t) for t, _ in sorted(pairs, key=lambda p: -int(p[1]))
            ]}

    if re.search(r"abs\(", s) and "/" in s:
        # Every `< t then n` must be the generated relative-difference term
        # (abs(diff)/denominator < t); a mix of relative and absolute
        # thresholds is a hand-written CASE and must not be collapsed into a
        # single all-relative kernel.
        pairs = re.findall(rf"<\s*{_NUM}\s*then\s*(\d+)", s)
        anchored = re.findall(
            rf"abs\([^)]*\)\s*\)*\s*/[^<]*<\s*{_NUM}\s*then\s*(\d+)", s
        )
        if pairs and len(anchored) != len(pairs):
            raise SqlTranslationError(
                "case_expression mixes relative-difference thresholds with "
                f"other < conditions; not a generated shape: {expr!r}"
            )
        if pairs:
            _check_generated_frame(expr, s)
            _check_level_coverage(expr, pairs, num_levels)
            by_level = sorted(pairs, key=lambda p: -int(p[1]))
            return {"kind": "numeric_perc", "thresholds": [float(t) for t, _ in by_level]}

    if re.search(r"abs\(", s):
        pairs = re.findall(rf"<\s*{_NUM}\s*then\s*(\d+)", s)
        anchored = re.findall(
            rf"abs\([^)]*\)\s*\)*\s*<\s*{_NUM}\s*then\s*(\d+)", s
        )
        if pairs and len(anchored) != len(pairs):
            raise SqlTranslationError(
                "case_expression mixes abs-difference thresholds with other "
                f"< conditions; not a generated shape: {expr!r}"
            )
        if pairs:
            _check_generated_frame(expr, s)
            _check_level_coverage(expr, pairs, num_levels)
            by_level = sorted(pairs, key=lambda p: -int(p[1]))
            return {"kind": "numeric_abs", "thresholds": [float(t) for t, _ in by_level]}

    if "dmetaphone" in s:
        # DoubleMetaphone-UDF comparison shapes: phonetic equality at level 1,
        # optionally under strict equality at level 2. Full-shape match only —
        # extra branches/conjuncts route to the general CASE compiler.
        _NULLB = (
            r"(?:when\s+(?P<nb>\w+)_l\s+is\s+null\s+or\s+(?P=nb)_r\s+is\s+null\s+"
            r"then\s*-1\s+)?"
        )
        m3 = re.fullmatch(
            r"case\s+" + _NULLB +
            r"when\s+(?P<c>\w+)_l\s*=\s*(?P=c)_r\s+then\s+2\s+when\s+"
            r"dmetaphone\(\s*(?P=c)_l\s*\)\s*=\s*dmetaphone\(\s*(?P=c)_r\s*\)\s*"
            r"then\s+1\s+else\s+0\s+end",
            s,
        )
        if m3 and num_levels == 3 and m3.group("nb") == m3.group("c"):
            return {"kind": "dmetaphone"}
        m2 = re.fullmatch(
            r"case\s+" + _NULLB +
            r"when\s+dmetaphone\(\s*(?P<c>\w+)_l\s*\)\s*=\s*"
            r"dmetaphone\(\s*(?P=c)_r\s*\)\s*then\s+1\s+else\s+0\s+end",
            s,
        )
        if m2 and num_levels == 2 and m2.group("nb") == m2.group("c"):
            return {"kind": "dmetaphone"}
        raise SqlTranslationError(
            f"Unrecognised dmetaphone case_expression shape: {expr!r}. "
            'Provide a native spec {"comparison": {"kind": "dmetaphone"}} '
            "with num_levels 2 (phonetic equality) or 3 (exact, then phonetic), "
            "or rely on the general CASE compiler for hand-written variants."
        )

    # Strict-equality fast path: only the exact generated shape
    # (/root/reference/splink/case_statements.py:62-71) — null branch,
    # equality, else 0. Anything else (extra conditions, missing ELSE with
    # its SQL-NULL semantics) belongs to the general CASE compiler.
    m = re.fullmatch(
        r"case\s+when\s+(\w+)_l\s+is\s+null\s+or\s+\1_r\s+is\s+null\s+"
        r"then\s*-1\s+when\s+(\w+)_l\s*=\s*\2_r\s+then\s+1\s+"
        r"else\s+0\s+end",
        s,
    )
    if m and num_levels == 2 and m.group(1) == m.group(2):
        return {"kind": "exact"}

    raise SqlTranslationError(
        "Could not translate this case_expression into a splink_tpu comparison "
        f"spec: {expr!r}.\n"
        "Recognised CASE families (the shapes the reference's generators "
        "emit, /root/reference/splink/case_statements.py:62-277):\n"
        "  * strict equality                  -> kind 'exact'\n"
        "  * jaro_winkler_sim(...) > t chains -> kind 'jaro_winkler'\n"
        "  * levenshtein ratio <= t chains    -> kind 'levenshtein'\n"
        "  * abs(a - b) < t chains            -> kind 'numeric_abs'\n"
        "  * abs(a - b)/abs(max) < t chains   -> kind 'numeric_perc'\n"
        "  * dmetaphone equality (2/3 level)  -> kind 'dmetaphone'\n"
        "  * name-inversion jw + ifnull OR    -> kind 'name_inversion'\n"
        "Hand-written CASE expressions outside these shapes are compiled by "
        "the general CASE compiler (splink_tpu/case_compiler.py) when used "
        "via settings; alternatively provide a native spec, e.g. "
        '{"comparison": {"kind": "jaro_winkler", "thresholds": [0.94, 0.88]}}, '
        "or implement the logic with splink_tpu.register_comparison() and "
        '{"comparison": {"kind": "custom", "name": ...}}.'
    )


def _check_generated_frame(expr: str, s: str) -> None:
    """The reference's generated CASE shapes all share one frame: a leading
    ``X_l is null or X_r is null then -1`` branch, no AND anywhere and no
    other OR. A hand-written CASE with extra conjuncts or without the null
    branch must NOT be collapsed onto a narrower native kernel — raising here
    routes it to the general CASE compiler, which executes it faithfully."""
    if re.search(r"\band\b", s):
        raise SqlTranslationError(
            "case_expression contains AND conjuncts, which the generated "
            f"shapes never do; not a generated shape: {expr!r}"
        )
    if len(re.findall(r"\bor\b", s)) != 1 or not re.search(
        r"when\s+(\w+)_l\s+is\s+null\s+or\s+\1_r\s+is\s+null\s+then\s*-1", s
    ):
        raise SqlTranslationError(
            "case_expression lacks the generated shapes' single "
            f"'X_l is null or X_r is null then -1' branch: {expr!r}"
        )


def _check_level_coverage(expr: str, pairs, num_levels: int) -> None:
    """Every level 1..num_levels-1 must be gated by an extracted threshold;
    a partial extraction means an unrecognised CASE shape and silent
    mistranslation, so raise instead."""
    levels = {int(lv) for _, lv in pairs}
    if levels != set(range(1, num_levels)):
        raise SqlTranslationError(
            f"case_expression gates levels {sorted(levels)} but num_levels="
            f"{num_levels} requires levels {list(range(1, num_levels))}; this "
            f"CASE shape is not fully recognised: {expr!r}. Provide a native "
            "'comparison' spec instead."
        )


def _parse_name_inversion(s: str) -> dict | None:
    main = re.search(rf"jaro_winkler_sim\((\w+)_l,\s*\1_r\)\s*>\s*{_NUM}\s*then\s*3", s)
    low = re.search(rf"jaro_winkler_sim\((\w+)_l,\s*\1_r\)\s*>\s*{_NUM}\s*then\s*1", s)
    others = re.findall(r"ifnull\((\w+)_r", s)
    if not (main and low and others):
        return None
    return {
        "kind": "name_inversion",
        "column": main.group(1),
        "other_columns": sorted(set(others)),
        "thresholds": [float(main.group(2)), float(low.group(2))],
    }


# --------------------------------------------------------------------------
# Blocking rules
# --------------------------------------------------------------------------

_EQ_TERM = re.compile(r"^\s*l\.(\w+)\s*=\s*r\.(\w+)\s*$")


def _split_single_eq(term: str) -> tuple[str, str] | None:
    """Split a term on its single top-level '=' (not <=, >=, !=, <>, ==),
    paren- and quote-aware. None when there is no clean single '='."""
    positions = []
    depth, i = 0, 0
    while i < len(term):
        ch = term[i]
        if ch == "'":
            end = term.find("'", i + 1)
            i = len(term) if end < 0 else end + 1
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "=" and depth == 0:
            prev = term[i - 1] if i else ""
            nxt = term[i + 1] if i + 1 < len(term) else ""
            if prev not in "<>!=" and nxt != "=":
                positions.append(i)
        i += 1
    if len(positions) != 1:
        return None
    p = positions[0]
    return term[:p].strip(), term[p + 1 :].strip()


def _try_derived_eq(term: str) -> tuple[str, str] | None:
    """Recognise a function-of-column equality join term: ``EXPR_L = EXPR_R``
    where one side references only l.* columns and the other only r.*
    columns, both within the derived-key evaluator's function surface
    (splink_tpu/derived_keys.py). Returns the side-stripped canonical
    (left_key, right_key) — the reference runs such predicates as ordinary
    Spark joins (/root/reference/splink/blocking.py:141-158); here they
    become ordinary hash-join keys on precomputed derived columns."""
    from .derived_keys import (
        DerivedKeyError,
        canonical,
        expr_sides,
        parse_key_expr,
        strip_side,
    )

    parts = _split_single_eq(term)
    if parts is None:
        return None
    try:
        na, nb = parse_key_expr(parts[0]), parse_key_expr(parts[1])
    except DerivedKeyError:
        return None
    sa, sb = expr_sides(na), expr_sides(nb)
    if sa == {"l"} and sb == {"r"}:
        pass
    elif sa == {"r"} and sb == {"l"}:
        na, nb = nb, na
    else:
        return None
    return canonical(strip_side(na)), canonical(strip_side(nb))


def parse_blocking_rule(rule: str):
    """Parse a blocking rule into (equality_pairs, residual_predicate).

    equality_pairs: list of (left_key, right_key) from top-level AND-ed
    equality terms; these become hash-join keys (SQL inner-join equality
    semantics: rows with a null key never match). Each key is either a bare
    column name (``l.col = r.col``) or a side-stripped derived-key
    expression (``substr(l.surname,1,3) = substr(r.surname,1,3)`` ->
    ``substr(surname,1,3)`` on both sides) evaluated host-side by
    splink_tpu/derived_keys.py. Cross-column / cross-expression equalities
    (l.a = r.b) keep distinct left and right keys and hash-join over a
    shared vocabulary.

    residual_predicate: a compiled python expression (numpy semantics) for any
    remaining AND-ed terms, or None. Evaluated against dicts ``l``/``r`` of
    column arrays after the hash join.

    ``dmetaphone(l.col)`` terms resolve to the host-precomputed derived
    column ``__dm_col`` (splink_tpu/data.py), so phonetic blocking keys are
    ordinary hash-join keys.
    """
    s = _normalise(rule)
    s = re.sub(r"(?i)\bdmetaphone\(\s*(l|r)\.(\w+)\s*\)", r"\1.__dm_\2", s)
    if not s:
        raise SqlTranslationError("Empty blocking rule")
    # Split on top-level AND only — quote- and paren-aware, so literals like
    # 'rock and roll' or nested (a AND b) groups don't steer the split.
    terms = [t for t in (p.strip() for p in _split_top_level(s, "and")) if t]

    eq_pairs = []
    residual_terms = []
    for t in terms:
        m = _EQ_TERM.match(t)
        if m:
            eq_pairs.append((m.group(1), m.group(2)))
            continue
        derived = _try_derived_eq(t)
        if derived is not None:
            eq_pairs.append(derived)
        else:
            residual_terms.append(t)

    residual = None
    if residual_terms:
        residual = sql_predicate_to_python(" and ".join(f"({t})" for t in residual_terms))
    return eq_pairs, residual


def sql_predicate_to_python(pred: str) -> str:
    """Convert a simple SQL boolean predicate to a numpy-evaluable expression.

    Supports: l./r. column refs, = != <> < <= > >=, AND/OR/NOT, abs(),
    numeric and single-quoted string literals, IS [NOT] NULL via an ``_isna``
    helper. The returned source expects ``l`` and ``r`` dict-of-array
    namespaces.

    AND/OR/NOT become the numpy element-wise operators ``& | ~``, which bind
    *tighter* than comparisons in Python — so every comparison atom is
    parenthesised during translation to preserve SQL precedence.
    """
    s = _normalise(pred)
    # Substitute IS [NOT] NULL before parsing — its NOT must not be taken
    # as a boolean operator.
    s = re.sub(r"(?i)\bis\s+not\s+null\b", " __ISNOTNULL__", s)
    s = re.sub(r"(?i)\bis\s+null\b", " __ISNULL__", s)
    # Recursive descent over the boolean structure. Parens are only grouping
    # when they wrap a sub-expression containing top-level boolean operators;
    # otherwise they belong to the atom (function calls like abs(...),
    # parenthesised arithmetic) and must not be split apart.
    return _bool_expr(s)


def _split_top_level(s: str, word: str) -> list[str]:
    """Split s on the boolean keyword at paren depth 0, outside single-quoted
    string literals (case-insensitive) — a literal like 'rock and roll' or
    'Ft. (Worth' must not steer the parse."""
    parts, depth, last = [], 0, 0
    pat = re.compile(rf"(?i)\b{word}\b")
    pos = 0
    while pos < len(s):
        ch = s[pos]
        if ch == "'":
            end = s.find("'", pos + 1)
            pos = len(s) if end < 0 else end + 1
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif depth == 0:
            m = pat.match(s, pos)
            if m and (pos == 0 or not s[pos - 1].isalnum()):
                parts.append(s[last:pos])
                last = m.end()
                pos = m.end()
                continue
        pos += 1
    parts.append(s[last:])
    return parts


def _bool_expr(s: str) -> str:
    s = s.strip()
    ors = _split_top_level(s, "or")
    if len(ors) > 1:
        return " | ".join(f"({_bool_expr(p)})" for p in ors)
    ands = _split_top_level(s, "and")
    if len(ands) > 1:
        return " & ".join(f"({_bool_expr(p)})" for p in ands)
    m = re.match(r"(?i)^\s*not\b(.*)$", s)
    if m:
        return f"~({_bool_expr(m.group(1))})"
    # fully-wrapped group whose parens match end-to-end -> recurse inside
    if s.startswith("(") and s.endswith(")") and _parens_match_whole(s):
        inner = s[1:-1]
        if (
            len(_split_top_level(inner, "or")) > 1
            or len(_split_top_level(inner, "and")) > 1
            or re.match(r"(?i)^\s*not\b", inner.strip())
            or (inner.strip().startswith("(") and _parens_match_whole(inner.strip()))
        ):
            return f"({_bool_expr(inner)})"
    return f"({_translate_atom(s)})"


def _parens_match_whole(s: str) -> bool:
    """True when s[0] == '(' pairs with s[-1] == ')' (quote-aware)."""
    depth = 0
    i = 0
    while i < len(s):
        ch = s[i]
        if ch == "'":
            end = s.find("'", i + 1)
            i = len(s) if end < 0 else end + 1
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return i == len(s) - 1
        i += 1
    return False


def _rewrite_concat_and_cast(s: str) -> str:
    """Quote-aware lexical rewrites for the atom translation:
      * SQL's ``||`` string-concat operator becomes ``@`` (Python's MatMult
        — unused otherwise, so the residual evaluators can give it concat
        semantics WITHOUT conflating it with SQL's numeric ``+``, which on
        strings means add-after-cast, not concatenation);
      * ``cast(x AS t)`` becomes ``cast(x, 't')`` so the expression stays
        parseable Python (``as`` is a keyword)."""
    out, i = [], 0
    while i < len(s):
        ch = s[i]
        if ch == "'":
            end = s.find("'", i + 1)
            end = len(s) if end < 0 else end + 1
            out.append(s[i:end])
            i = end
            continue
        if s.startswith("||", i):
            out.append("@")
            i += 2
            continue
        m = re.match(r"(?i)\bas\s+(\w+)\s*\)", s[i:])
        if m and i and (s[i - 1].isspace()):
            out.append(f", '{m.group(1)}')")
            i += m.end()
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _translate_atom(atom: str) -> str:
    """Translate one comparison atom (no boolean operators) to Python."""
    s = _rewrite_concat_and_cast(atom)
    s = re.sub(r"\bl\.(\w+)", r'l["\1"]', s)
    s = re.sub(r"\br\.(\w+)", r'r["\1"]', s)
    s = re.sub(r"(?<![<>!=])=(?!=)", "==", s)
    s = s.replace("<>", "!=")
    s = re.sub(r'((?:l|r)\["\w+"\])\s*__ISNOTNULL__', r"~_isna(\1)", s)
    s = re.sub(r'((?:l|r)\["\w+"\])\s*__ISNULL__', r"_isna(\1)", s)
    return s.strip()
