"""Streaming EM: sufficient statistics accumulated across micro-batches.

For pair sets too large for HBM the reference gets global aggregation for
free from Spark's shuffle (/root/reference/splink/maximisation_step.py:54-57).
The TPU equivalent: stream gamma batches host->device (double-buffered via
jax's async dispatch), accumulate ``SufficientStats`` on device per batch,
and apply the parameter update once per pass over the data. The per-batch
kernel is a single jit; with a mesh, batches are sharded over the pair axis
and the stats reduction rides ICI psum.
"""

from __future__ import annotations

import functools
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from ..models.fellegi_sunter import (
    FSParams,
    SufficientStats,
    log_likelihood,
    match_probability,
    sufficient_stats,
    update_params,
)
from .mesh import pair_sharding, shard_pairs


@functools.partial(jax.jit, static_argnames=("max_levels", "compute_ll"))
def _batch_stats(G, params: FSParams, max_levels: int, weights=None, compute_ll=False):
    p = match_probability(G, params)
    stats = sufficient_stats(G, p, max_levels, weights)
    ll = log_likelihood(G, params, weights) if compute_ll else jnp.zeros((), p.dtype)
    return stats, ll


@jax.jit
def _update_and_delta(acc: SufficientStats, params: FSParams):
    """M-step update fused with the convergence delta, one compiled program:
    the driver loop then needs a single scalar read per pass instead of one
    sync per jnp reduction (jaxlint JL011)."""
    new = update_params(acc)
    delta = jnp.maximum(
        jnp.max(jnp.abs(new.m - params.m)),
        jnp.max(jnp.abs(new.u - params.u)),
    )
    return new, delta


def run_em_streamed(
    batch_iter_factory: Callable[[], Iterable],
    init: FSParams,
    *,
    max_iterations: int,
    max_levels: int,
    em_convergence: float,
    mesh=None,
    compute_ll: bool = False,
    on_iteration=None,
    stats_reduce=None,
    start_iteration: int = 0,
    retry_policy=None,
    fault_plan=None,
    telemetry=None,
):
    """EM over a re-iterable stream of gamma batches.

    Args:
        batch_iter_factory: zero-arg callable returning an iterable of either
            ``G`` arrays or ``(G, weights)`` tuples, each (b, C) int8. Called
            once per EM iteration (the stream is re-read every pass, like the
            reference re-scans the persisted df_gammas).
        init: starting parameters.
        mesh: optional Mesh; batches are padded + sharded over the pair axis.
        stats_reduce: optional callable applied to the pass's accumulated
            SufficientStats before the parameter update. Multi-controller
            runs pass ``parallel.distributed.all_sum_stats`` here so every
            process updates from the GLOBAL aggregate while streaming only
            its own ``global_pair_slice`` (the reference gets this from
            Spark's global shuffle, maximisation_step.py:54-57).
        on_iteration: optional callback(iteration_index, FSParams, ll,
            converged) run after each update — the save_state_fn hook's
            internal analogue (and where resilience.EMCheckpointer plugs
            in); ``converged`` is True on the update that met
            em_convergence.
        start_iteration: resume support — the number of EM updates ``init``
            already embodies (from a checkpoint); iteration indices
            reported to on_iteration continue from here, and at most
            ``max_iterations - start_iteration`` further updates run.
            Histories still start at index 0 = ``init`` (the caller merges
            with pre-resume history).
        retry_policy: optional resilience.RetryPolicy. A transient failure
            anywhere in a pass (batch fetch, device put, execute) restarts
            that WHOLE pass with bounded exponential backoff — partial
            sufficient statistics are never reused, so a retried pass is
            bit-identical to an undisturbed one. Deterministic failures
            propagate immediately. None disables retry.
        fault_plan: optional resilience.FaultPlan consulted at the
            ``batch_fetch`` (per batch) and ``em_iteration`` (per update)
            injection sites; None resolves the process's active plan
            (SPLINK_TPU_FAULTS).
        telemetry: optional ``obs.runtime.RunContext`` — emits one EM
            convergence record per pass (the streamed loop is host-driven,
            so this adds no host callback to any compiled program) plus a
            pass counter.

    Returns (params, histories, n_updates, converged) mirroring run_em.
    """
    from ..resilience import faults as _faults
    from ..resilience.retry import retry_call

    if fault_plan is None:
        fault_plan = _faults.active_plan()

    params = init
    C, L = init.m.shape
    lam_hist = [float(init.lam)]
    m_hist = [np.asarray(init.m)]
    u_hist = [np.asarray(init.u)]
    ll_hist = []
    converged = False
    it = start_iteration

    def one_pass(it, params):
        """One full pass over the stream: (accumulated stats, ll parts)."""
        acc = SufficientStats.zeros(C, L, dtype=init.m.dtype)
        # Per-batch log-likelihoods stay on device (a host-side float(ll)
        # here would sync every micro-batch and serialise the stream) and
        # reduce pairwise at the end of the pass, which keeps f32 error
        # O(log n_batches) instead of O(n_batches) for sequential adds.
        ll_parts = []
        for bi, batch in enumerate(batch_iter_factory()):
            fault_plan.fire("batch_fetch", iter=it, batch=bi)
            if isinstance(batch, tuple):
                G, w = batch
            else:
                G, w = batch, None
            if mesh is not None:
                if w is None:
                    G, w = shard_pairs(mesh, np.asarray(G))
                else:
                    # pad user weights alongside G (padding weight 0)
                    G, w, _auto_w = shard_pairs(
                        mesh, np.asarray(G), np.asarray(w, np.float32)
                    )
            stats, ll = _batch_stats(
                jnp.asarray(G), params, max_levels, w, compute_ll
            )
            acc = acc + stats
            if compute_ll:
                ll_parts.append(ll)
        return acc, ll_parts

    for it in range(start_iteration + 1, max_iterations + 1):
        if retry_policy is not None:
            acc, ll_parts = retry_call(
                lambda: one_pass(it, params),
                policy=retry_policy,
                label=f"EM pass {it}",
            )
        else:
            acc, ll_parts = one_pass(it, params)
        ll_dev = (
            jnp.sum(jnp.stack(ll_parts))
            if ll_parts
            else jnp.zeros((), init.m.dtype)
        )

        if stats_reduce is not None:
            # reduce the log-likelihood with the SAME collective as the
            # stats (one pytree, one allgather): each process streams only
            # its slice, so the local ll is partial too
            if compute_ll:
                acc, ll_dev = stats_reduce((acc, ll_dev))
            else:
                acc = stats_reduce(acc)
        new, delta_dev = _update_and_delta(acc, params)
        params = new
        # The ONE sanctioned sync point per pass: the convergence decision
        # and the histories need these scalars on host, and everything
        # upstream (per-batch stats, ll parts, the update+delta) stayed on
        # device.
        delta = float(delta_dev)  # jaxlint: disable=JL011 — sanctioned
        lam_f = float(params.lam)  # jaxlint: disable=JL011 — same sync point
        ll_total = (  # jaxlint: disable=JL011 — same sync point
            float(ll_dev) if compute_ll else 0.0
        )
        lam_hist.append(lam_f)
        m_hist.append(np.asarray(params.m))
        u_hist.append(np.asarray(params.u))
        if compute_ll:
            ll_hist.append(ll_total)
        converged_now = delta < em_convergence
        if telemetry is not None:
            telemetry.em_update(
                it, lam_f, params.m, params.u,
                ll_total if compute_ll else None, converged_now,
            )
            telemetry.count("em_stream_passes")
        if on_iteration is not None:
            # the convergence flag rides along so a checkpoint written at
            # the converging iteration records converged=True — a resume
            # must not append a spurious extra update
            on_iteration(
                it, params, ll_total if compute_ll else None, converged_now
            )
        # after on_iteration so a checkpoint hook persists this update
        # before an injected process death (the kill-and-resume tests)
        fault_plan.fire("em_iteration", iter=it)
        if converged_now:
            converged = True
            break

    histories = {
        "lam": np.asarray(lam_hist),
        "m": np.stack(m_hist),
        "u": np.stack(u_hist),
        "ll": np.asarray(ll_hist) if compute_ll else None,
    }
    return params, histories, it, converged


def score_stream(batch_iter, params: FSParams):
    """Yield match probabilities for each gamma batch in the stream."""
    from ..em import score_pairs

    for batch in batch_iter:
        G = batch[0] if isinstance(batch, tuple) else batch
        yield np.asarray(score_pairs(jnp.asarray(G), params))
