from .distributed import global_pair_slice, initialize_multihost
from .mesh import (
    DATA_AXIS,
    make_mesh,
    mesh_from_settings,
    pair_sharding,
    replicated,
    shard_pairs,
)
from .streaming import run_em_streamed, score_stream

__all__ = [
    "DATA_AXIS",
    "make_mesh",
    "mesh_from_settings",
    "pair_sharding",
    "replicated",
    "shard_pairs",
    "run_em_streamed",
    "score_stream",
    "initialize_multihost",
    "global_pair_slice",
]
