"""Multi-host (multi-slice) initialisation and work partitioning.

The reference's multi-machine story is "submit to a Spark cluster". The
splink_tpu analogue is JAX multi-controller: each host runs the same program,
``jax.distributed.initialize`` wires the hosts together, and the global mesh
spans every chip; XLA routes the M-step psum over ICI within a slice and DCN
across slices. EM's collective traffic is tiny (the SufficientStats pytree,
a few KB), so DCN latency is irrelevant — the design scales to any slice
count the pair stream can feed.

Support status: the single-process path and the partitioning arithmetic are
tested (tests/test_distributed.py); sharded EM correctness is proven on an
8-virtual-device mesh (tests/test_sharding.py); and the REAL multi-controller
path — two OS processes wired by ``jax.distributed.initialize`` over local
TCP (Gloo CPU collectives), each streaming its ``global_pair_slice`` through
``run_em_streamed`` with ``all_sum_stats`` as the cross-process reduction —
runs in CI with bit-parity against the single-process trajectory
(tests/test_multiprocess_em.py). Physical-pod bring-up uses the identical
code path with auto-detected coordinator arguments.
"""

from __future__ import annotations

import hashlib
import logging

import jax

logger = logging.getLogger("splink_tpu")


def distributed_is_initialized() -> bool:
    """Whether the multi-controller runtime is up. jax < 0.5 has no
    ``jax.distributed.is_initialized``; fall back to the client object the
    initialize call installs (reading it does NOT initialise the XLA
    backend, unlike jax.process_count())."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 - conservative: assume not initialised
        return False


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialise JAX's multi-controller runtime.

    On TPU pods the arguments are auto-detected from the environment; pass
    them explicitly for manual bring-up. With no arguments and no cluster
    environment this is a logged no-op (single-process run); explicit
    arguments that fail to connect raise — a misconfigured cluster must not
    silently degrade to one host.
    """
    # NOTE: do not probe jax.process_count() here — it INITIALISES the XLA
    # backend, after which jax.distributed.initialize refuses to run (it
    # must precede any backend use). is_initialized() only inspects the
    # distributed-runtime state.
    if distributed_is_initialized():
        return  # already initialised
    explicit = coordinator_address is not None
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        if explicit:
            raise RuntimeError(
                f"jax.distributed.initialize failed for coordinator "
                f"{coordinator_address!r}: {e}"
            ) from e
        logger.info(
            "no multi-host environment detected (%s); running single-process",
            e,
        )


def all_sum_stats(stats):
    """Sum a SufficientStats pytree (or any small pytree of arrays) across
    controller processes — the multi-host analogue of the in-mesh psum. The
    payload is a few KB, so one allgather per EM pass is negligible next to
    the pair stream.

    Single-process: identity (so the same code runs everywhere). Pass as
    ``run_em_streamed(..., stats_reduce=all_sum_stats)``.
    """
    if jax.process_count() == 1:
        return stats
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    # ONE allgather for the whole pytree (process_allgather maps over
    # leaves inside a single collective round), then sum the process axis
    gathered = multihost_utils.process_allgather(
        jax.tree.map(jnp.asarray, stats)
    )
    return jax.tree.map(lambda leaf: jnp.sum(leaf, axis=0), gathered)


def validate_resume_presence(found: bool) -> bool:
    """All processes must agree whether the checkpoint exists BEFORE any
    loader-only work happens: validate_resume_topology is a collective,
    and a resumed process also starts from a different iteration than a
    fresh one — either divergence deadlocks or corrupts the run. Mixed
    found-flags mean checkpoint_dir is per-host storage (only process 0
    writes); raise with that diagnosis instead of hanging. Every process
    must call this when resuming under multi-controller. Returns
    ``found`` unchanged for the single-process case and for agreement."""
    if jax.process_count() == 1:
        return found
    import numpy as np
    from jax.experimental import multihost_utils

    local = np.array([1 if found else 0], np.int64)
    gathered = np.asarray(multihost_utils.process_allgather(local)).ravel()
    if gathered.min() != gathered.max():
        raise RuntimeError(
            "processes disagree on checkpoint presence (found flags "
            f"{gathered.tolist()}): only process 0 writes checkpoints, so "
            "checkpoint_dir must be on storage shared by every controller "
            "process."
        )
    return found


def validate_resume_topology(
    checkpoint_process_count: int, state_hash: str, iteration: int
) -> None:
    """Gate a multi-controller checkpoint resume on topology agreement.

    A resumed run must (a) have the SAME process count the checkpoint was
    written under — global_pair_slice partitions by process count, so a
    different topology would stream different slices than the histories
    assume — and (b) agree ACROSS processes on which checkpoint it is
    resuming (same settings hash, same iteration). Disagreement raises
    before any training continues; the single-process case checks only (a).
    """
    if jax.process_count() != checkpoint_process_count:
        raise RuntimeError(
            f"checkpoint was written by {checkpoint_process_count} "
            f"process(es) but this run has {jax.process_count()}: the "
            "global pair slices would not line up. Resume with the same "
            "topology, or train fresh with resume=False."
        )
    if jax.process_count() == 1:
        return
    import numpy as np
    from jax.experimental import multihost_utils

    digest = np.frombuffer(
        hashlib.sha256(state_hash.encode()).digest()[:8], np.int64
    )[0]
    local = np.array([digest, iteration], np.int64)
    gathered = np.asarray(multihost_utils.process_allgather(local))
    if not (gathered == local[None, :]).all():
        raise RuntimeError(
            "processes disagree on the checkpoint being resumed "
            f"(hash-digest/iteration rows: {gathered.tolist()}); refusing "
            "to continue from inconsistent state."
        )


def host_tags() -> dict:
    """Per-host identity tags stamped on every telemetry event
    (``process_index`` / ``process_count``), so a multi-controller run's
    merged JSONL records attribute each event to its controller.

    Deliberately does NOT call ``jax.process_count()`` unless the
    multi-controller runtime is already up: that call initialises the XLA
    backend, and telemetry sinks are created at linker construction —
    before ``initialize_multihost`` callers may have wired the cluster.
    A single-process run IS process 0 of 1, so the fallback is exact.
    """
    if not distributed_is_initialized():
        return {"process_index": 0, "process_count": 1}
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
    }


def spill_shard_dir(base: str) -> str:
    """Per-controller root for the durable spill write path: under
    multi-controller each process emits ITS shard subset of the pair
    stream into its own ``<base>/proc<k>`` store (single-writer manifests
    — the same discipline as the checkpoint writer), while a
    single-process run uses ``base`` directly so the common case has no
    extra directory level. ``base`` must be shared storage when the
    consuming EM later runs with a different controller layout."""
    import os

    if not distributed_is_initialized():
        return base
    return os.path.join(base, f"proc{jax.process_index()}")


def global_pair_slice(n_pairs_global: int) -> slice:
    """The half-open range of global pair indices this host is responsible
    for feeding. Hosts stream disjoint slices; the psum in the EM stats makes
    the union behave like one global aggregate."""
    per = -(-n_pairs_global // jax.process_count())
    start = min(jax.process_index() * per, n_pairs_global)
    return slice(start, min(start + per, n_pairs_global))
