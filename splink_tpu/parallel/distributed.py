"""Multi-host (multi-slice) initialisation and work partitioning.

The reference's multi-machine story is "submit to a Spark cluster". The
splink_tpu analogue is JAX multi-controller: each host runs the same program,
``jax.distributed.initialize`` wires the hosts together, and the global mesh
spans every chip; XLA routes the M-step psum over ICI within a slice and DCN
across slices. EM's collective traffic is tiny (the SufficientStats pytree,
a few KB), so DCN latency is irrelevant — the design scales to any slice
count the pair stream can feed.

Support status (honest): the single-process path and the partitioning
arithmetic are tested (tests/test_distributed.py); sharded EM correctness is
proven on an 8-virtual-device mesh (tests/test_sharding.py). Real multi-host
bring-up follows the standard jax.distributed.initialize pattern but has not
run on a physical pod from this repo.
"""

from __future__ import annotations

import logging

import jax

logger = logging.getLogger("splink_tpu")


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialise JAX's multi-controller runtime.

    On TPU pods the arguments are auto-detected from the environment; pass
    them explicitly for manual bring-up. With no arguments and no cluster
    environment this is a logged no-op (single-process run); explicit
    arguments that fail to connect raise — a misconfigured cluster must not
    silently degrade to one host.
    """
    if jax.process_count() > 1:
        return  # already initialised
    explicit = coordinator_address is not None
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        if explicit:
            raise RuntimeError(
                f"jax.distributed.initialize failed for coordinator "
                f"{coordinator_address!r}: {e}"
            ) from e
        logger.info(
            "no multi-host environment detected (%s); running single-process",
            e,
        )


def global_pair_slice(n_pairs_global: int) -> slice:
    """The half-open range of global pair indices this host is responsible
    for feeding. Hosts stream disjoint slices; the psum in the EM stats makes
    the union behave like one global aggregate."""
    per = -(-n_pairs_global // jax.process_count())
    start = min(jax.process_index() * per, n_pairs_global)
    return slice(start, min(start + per, n_pairs_global))
