"""Multi-host (multi-slice) initialisation.

The reference's multi-machine story is "submit to a Spark cluster". The
splink_tpu analogue is JAX multi-controller: each host runs the same program,
``jax.distributed.initialize`` wires the hosts together, and the global mesh
spans every chip; XLA routes the M-step psum over ICI within a slice and DCN
across slices. EM's collective traffic is tiny (the SufficientStats pytree,
a few KB), so DCN latency is irrelevant — the design scales to any slice
count the pair stream can feed.
"""

from __future__ import annotations

import jax


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialise JAX's multi-controller runtime (no-op if single-process).

    On TPU pods the arguments are auto-detected from the environment; pass
    them explicitly for manual bring-up.
    """
    if jax.process_count() > 1:
        return  # already initialised
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        # Single-process environment (no coordinator): run locally.
        pass


def global_pair_slice(n_pairs_global: int) -> slice:
    """The half-open range of global pair indices this host is responsible
    for feeding. Hosts stream disjoint slices; the psum in the EM stats makes
    the union behave like one global aggregate."""
    per = -(-n_pairs_global // jax.process_count())
    start = jax.process_index() * per
    return slice(start, min(start + per, n_pairs_global))
