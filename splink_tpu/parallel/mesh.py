"""Device-mesh helpers: the TPU replacement for Spark's partitioning layer.

The reference delegates all distribution to Spark (SURVEY.md section 2:
"Parallelism & distributed-communication components"). Here the single
distributed axis is the candidate-pair axis — this framework's "sequence
length" — sharded over a 1-D ``data`` mesh axis. M-step reductions then lower
to psum collectives over ICI; parameters are replicated.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, axis_name: str = DATA_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def mesh_from_settings(settings: dict) -> Mesh | None:
    """Build the mesh described by the settings ``mesh`` dict, or None.

    ``{"data": N}`` means: shard the pair axis over N devices. ``{"data":
    1}`` is an EXPLICIT single-device mesh — the sharded code path with one
    shard (useful for exercising mesh plumbing anywhere), not the same as
    the empty dict / absent key, which selects the unsharded single-device
    path.
    """
    spec = settings.get("mesh") or {}
    if not spec:
        return None
    supported = (
        f"the supported form is {{{DATA_AXIS!r}: N}} — a 1-D mesh over the "
        f"pair axis with 1 <= N <= jax.device_count()"
    )
    if list(spec.keys()) != [DATA_AXIS]:
        raise ValueError(f"unsupported mesh spec {spec!r}; {supported}")
    n = spec[DATA_AXIS]
    if isinstance(n, bool) or not isinstance(n, int) or n < 1:
        raise ValueError(
            f"unsupported mesh size {n!r} in {spec!r}; {supported}"
        )
    available = len(jax.devices())
    if n > available:
        raise ValueError(
            f"mesh spec {spec!r} requests {n} devices but only {available} "
            f"are visible; {supported}"
        )
    return make_mesh(n)


def pair_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (n_pairs, ...) arrays: split the leading pair axis."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def shard_pairs(mesh: Mesh, *arrays):
    """Pad the leading axis to a multiple of the mesh size and device_put with
    pair sharding. Returns (padded_arrays..., weights) where weights is 1.0
    for real rows and 0.0 for padding — thread it into EM so padding rows
    contribute nothing (gamma padding value -1 + weight 0; shard_audit
    SA-PAD statically pins that the stats kernels consume the weights)."""
    n = arrays[0].shape[0]
    n_dev = mesh.devices.size
    n_pad = pad_to_multiple(max(n, n_dev), n_dev)
    sharding = pair_sharding(mesh)

    out = []
    for a in arrays:
        if n_pad != n:
            pad_shape = (n_pad - n,) + a.shape[1:]
            fill = -1 if np.issubdtype(a.dtype, np.signedinteger) else 0
            a = np.concatenate([a, np.full(pad_shape, fill, a.dtype)])
        out.append(jax.device_put(a, sharding))
    weights = np.zeros(n_pad, np.float32)
    weights[:n] = 1.0
    out.append(jax.device_put(weights, sharding))
    return tuple(out)
