"""Device-mesh helpers: the TPU replacement for Spark's partitioning layer.

The reference delegates all distribution to Spark (SURVEY.md section 2:
"Parallelism & distributed-communication components"). Here the single
distributed axis is the candidate-pair axis — this framework's "sequence
length" — sharded over a 1-D ``data`` mesh axis. M-step reductions then lower
to psum collectives over ICI; parameters are replicated.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, axis_name: str = DATA_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def mesh_from_settings(settings: dict) -> Mesh | None:
    """Build the mesh described by the settings ``mesh`` dict, or None.

    ``{"data": 8}`` means: shard the pair axis over 8 devices. An empty dict
    (the default) means single-device execution.
    """
    spec = settings.get("mesh") or {}
    if not spec:
        return None
    if list(spec.keys()) != [DATA_AXIS]:
        raise ValueError(
            f"Only a 1-D {{'data': N}} mesh is supported for EM; got {spec!r}"
        )
    return make_mesh(spec[DATA_AXIS])


def pair_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (n_pairs, ...) arrays: split the leading pair axis."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def shard_pairs(mesh: Mesh, *arrays):
    """Pad the leading axis to a multiple of the mesh size and device_put with
    pair sharding. Returns (padded_arrays..., weights) where weights is 1.0
    for real rows and 0.0 for padding — thread it into EM so padding rows
    contribute nothing (gamma padding value -1 + weight 0)."""
    import numpy as np

    n = arrays[0].shape[0]
    n_dev = mesh.devices.size
    n_pad = pad_to_multiple(max(n, n_dev), n_dev)
    sharding = pair_sharding(mesh)

    out = []
    for a in arrays:
        if n_pad != n:
            pad_shape = (n_pad - n,) + a.shape[1:]
            fill = -1 if np.issubdtype(a.dtype, np.signedinteger) else 0
            a = np.concatenate([a, np.full(pad_shape, fill, a.dtype)])
        out.append(jax.device_put(a, sharding))
    weights = np.zeros(n_pad, np.float32)
    weights[:n] = 1.0
    out.append(jax.device_put(weights, sharding))
    return tuple(out)
