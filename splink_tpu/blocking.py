"""Candidate-pair generation (blocking) — host-side hash joins.

The reference implements blocking as Spark SQL inner joins, one per rule,
UNION ALLed with each rule ANDed against NOT(any previous rule)
(/root/reference/splink/blocking.py:95-160). The TPU design keeps blocking on
the host — it is an irregular, data-dependent join that would fight XLA's
static shapes — and produces *pair index arrays* into the encoded table; the
quadratic pair data itself never materialises on the host beyond two int
arrays, and device gathers do the rest.

Round 7 moved the join itself onto the device for the common shapes:
``block_using_rules`` dispatches to the device-native sort-join tier
(splink_tpu/blocking_device.py — segmented sort, run-length segment
detection, budgeted on-device pair expansion) on accelerator backends or
when ``device_blocking: "on"``; the host joins below remain the fallback
for unsupported shapes AND the parity oracle the device tier is tested
against (docs/blocking.md).

Pair-set semantics are preserved exactly:
  * equality-conjunction rules (``l.a = r.a AND l.b = r.b``) become hash
    joins on combined key codes; rows with a null key never match (SQL
    equality semantics),
  * function-of-column equalities (``substr(l.surname,1,3) =
    substr(r.surname,1,3)``, a dmetaphone key) hash-join on host-derived
    key columns (splink_tpu/derived_keys.py), and cross-column /
    cross-expression equalities (``l.a = r.b``) hash-join through per-side
    code arrays over a shared vocabulary — the reference ran all of these
    as ordinary Spark joins (/root/reference/splink/blocking.py:141-158),
  * each rule's pairs exclude pairs produced by ANY earlier rule. The
    reference expresses this as ``AND NOT ifnull(previous_rule, false)``
    (/root/reference/splink/blocking.py:59-68) and that is literally what
    runs here: earlier rules' predicates (join-key equality + residual) are
    evaluated on each new rule's candidates, with a null/UNKNOWN outcome
    counting as not-produced (the ifnull). No accumulated pair set is kept,
  * link types order/orient pairs like the reference
    (/root/reference/splink/blocking.py:133-139): dedupe_only keeps
    ``uid_l < uid_r``; link_only crosses the two tables with the left input
    on the l side; link_and_dedupe orders by (source_table, uid),
  * empty rules -> cartesian join (with the documented quadratic warning).

Rules that are not pure equality conjunctions keep their equality part as the
join key and evaluate the residual predicate on the joined candidates (or,
with no equality part at all, against cartesian chunks).
"""

from __future__ import annotations

import logging
import warnings
from dataclasses import dataclass

import numpy as np

from . import native
from .check_types import check_types
from .compat_sql import parse_blocking_rule
from .data import EncodedTable

logger = logging.getLogger("splink_tpu")

_CARTESIAN_CHUNK = 1 << 22


@dataclass
class PairIndex:
    """Candidate pairs as row indices into one EncodedTable.

    Indices are int32 whenever the table allows (n_rows < 2^31 — i.e.
    always, in practice): at billions of candidate pairs the narrow dtype
    halves both the resident footprint and the spill size. The int64 path
    survives behind the ``_idx_dtype`` size check only."""

    idx_l: np.ndarray  # (n_pairs,) int32 (int64 iff n_rows >= 2^31)
    idx_r: np.ndarray  # (n_pairs,) int32 (int64 iff n_rows >= 2^31)
    # When blocking streamed the pairs straight to disk (spill_dir set),
    # idx_l/idx_r are memmaps living in this directory; the linker adopts it
    # for lifetime management.
    spill_tmp: str | None = None
    # When the pairs came through the DURABLE spill store (build_spill_dir:
    # sharded emission with a resume manifest), this is the owning
    # spill.PairSpillStore — caller-owned, never auto-deleted, and what the
    # spill-fed streamed EM consumes directly.
    spill_store: object | None = None

    @property
    def n_pairs(self) -> int:
        return len(self.idx_l)

    def release(self) -> None:
        """Deterministically release the spill backing: close the memmaps
        FIRST, then reclaim the transient spill directory. The weakref
        finalizer does the same reclaim at GC time on POSIX, but Windows
        refuses to unlink a file with a live mapping — callers that need
        portable, immediate reclamation use this instead of relying on
        collection order. Idempotent; leaves a durable spill_store's files
        untouched (those are caller-owned)."""
        import shutil

        for name in ("idx_l", "idx_r"):
            arr = getattr(self, name)
            mm = getattr(arr, "_mmap", None)
            if mm is not None:
                setattr(self, name, np.zeros(0, arr.dtype))
                try:
                    mm.close()
                except (BufferError, OSError):
                    pass  # an external view still holds the map
        fin = self.__dict__.pop("_finalizer", None)
        if fin is not None:
            fin.detach()
        if self.spill_tmp is not None:
            shutil.rmtree(self.spill_tmp, ignore_errors=True)
            self.spill_tmp = None
        if self.spill_store is not None:
            self.spill_store.release_maps()


def _proc_start_time(pid: int) -> int | None:
    """The process's kernel start time (clock ticks since boot) from
    /proc/<pid>/stat, or None where /proc is unavailable. Distinguishes a
    live owner from an unrelated process that recycled its pid."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as fh:
            data = fh.read().decode("ascii", "replace")
        # field 22 (starttime); the comm field can contain spaces/parens so
        # split after the LAST ')'
        return int(data.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


def _owner_token(pid: int) -> str:
    start = _proc_start_time(pid)
    return f"{pid} {start}" if start is not None else str(pid)


def _sweep_stale_spill_dirs(spill_dir: str) -> None:
    """Reclaim splink_pairs_* dirs whose owning process is gone.

    The weakref finalizer on a spilled PairIndex never runs on
    SIGKILL/OOM-kill — the most likely death for a job big enough to spill —
    so each spill dir records its owner pid (plus the pid's kernel start
    time, so a recycled pid belonging to an unrelated live process doesn't
    pin a multi-GB orphan forever) and the next spilling run sweeps dirs
    whose owner is gone, BEFORE it starts writing its own pair set. Dirs
    without a pid file (mid-creation, or foreign) are left alone.
    """
    import os
    import shutil

    try:
        entries = os.listdir(spill_dir)
    except OSError:
        return
    for name in entries:
        if not name.startswith("splink_pairs_"):
            continue
        path = os.path.join(spill_dir, name)
        pid_file = os.path.join(path, "owner.pid")
        try:
            with open(pid_file) as fh:
                fields = fh.read().split()
            pid = int(fields[0])
            recorded_start = int(fields[1]) if len(fields) > 1 else None
        except (OSError, IndexError, ValueError):
            continue
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)  # signal 0: existence check only
        except ProcessLookupError:
            logger.info("reclaiming stale spill dir %s (pid %d dead)", path, pid)
            shutil.rmtree(path, ignore_errors=True)
            continue
        except OSError:
            pass  # e.g. EPERM: pid exists under another user — but
            # /proc/<pid>/stat is world-readable, so the start-time
            # comparison below still detects a recycled pid
        # pid is alive — but is it the same process that wrote the dir?
        current_start = _proc_start_time(pid)
        if (
            recorded_start is not None
            and current_start is not None
            and current_start != recorded_start
        ):
            logger.info(
                "reclaiming stale spill dir %s (pid %d recycled: start %d "
                "!= recorded %d)", path, pid, current_start, recorded_start,
            )
            shutil.rmtree(path, ignore_errors=True)


class _PairSink:
    """Accumulates per-rule pair chunks; either in RAM (concatenate at the
    end) or streamed to spill files as they are produced, so the pair set
    never exists twice in memory (chunks + concatenated copy).

    A context manager: an exception anywhere inside the ``with`` body
    aborts the sink — handles closed, the partial spill directory
    reclaimed — so segments written before a mid-emission failure are
    never left for the stale-dir sweep to (not) find: the owning process
    is still alive, which is exactly the case the pid-based sweep
    correctly refuses to touch."""

    def __enter__(self) -> "_PairSink":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.abort()

    def __init__(self, spill_dir: str | None, idx_dtype):
        self.idx_dtype = idx_dtype
        self.total = 0
        self.spill_tmp = None
        if spill_dir:
            import os
            import tempfile

            os.makedirs(spill_dir, exist_ok=True)
            # reclaim orphans before writing tens of GB next to them
            _sweep_stale_spill_dirs(spill_dir)
            self.spill_tmp = tempfile.mkdtemp(
                prefix="splink_pairs_", dir=spill_dir
            )
            with open(os.path.join(self.spill_tmp, "owner.pid"), "w") as fh:
                fh.write(_owner_token(os.getpid()))
            self._files = [
                open(os.path.join(self.spill_tmp, f"{name}.bin"), "wb")
                for name in ("idx_l", "idx_r")
            ]
        else:
            self._chunks_l: list[np.ndarray] = []
            self._chunks_r: list[np.ndarray] = []

    def append(self, i: np.ndarray, j: np.ndarray) -> None:
        i = i.astype(self.idx_dtype, copy=False)
        j = j.astype(self.idx_dtype, copy=False)
        self.total += len(i)
        if self.spill_tmp is not None:
            i.tofile(self._files[0])
            j.tofile(self._files[1])
        else:
            self._chunks_l.append(i)
            self._chunks_r.append(j)

    def abort(self) -> None:
        """Close handles and reclaim the partial spill dir after a failure
        mid-blocking — the owning process is still alive, so the stale-dir
        sweep would (correctly) not touch it."""
        if self.spill_tmp is None:
            return
        import shutil

        for fh in self._files:
            try:
                fh.close()
            except OSError:
                pass
        shutil.rmtree(self.spill_tmp, ignore_errors=True)
        self.spill_tmp = None

    def finish(self) -> PairIndex:
        if self.spill_tmp is None:
            if not self._chunks_l:  # chunked emission may sink nothing
                return PairIndex(
                    np.zeros(0, self.idx_dtype), np.zeros(0, self.idx_dtype)
                )
            if len(self._chunks_l) == 1:
                # np.concatenate on a one-element list still copies
                return PairIndex(self._chunks_l[0], self._chunks_r[0])
            return PairIndex(
                np.concatenate(self._chunks_l), np.concatenate(self._chunks_r)
            )
        import os
        import shutil
        import weakref

        for fh in self._files:
            fh.close()
        arrs = []
        for name in ("idx_l", "idx_r"):
            path = os.path.join(self.spill_tmp, f"{name}.bin")
            if self.total:
                arrs.append(
                    np.memmap(
                        path, dtype=self.idx_dtype, mode="r", shape=(self.total,)
                    )
                )
            else:
                arrs.append(np.empty(0, self.idx_dtype))
        out = PairIndex(arrs[0], arrs[1], spill_tmp=self.spill_tmp)
        # reclaim the files when the pair index goes away (unlink while the
        # memmaps are open is safe on POSIX; space frees on close). The
        # handle is kept so PairIndex.release() can close the maps first
        # and detach — the Windows-safe deterministic path.
        out._finalizer = weakref.finalize(out, shutil.rmtree, self.spill_tmp, True)
        return out


# ----------------------------------------------------------------------
# Small vectorised helpers
# ----------------------------------------------------------------------


def _ranges(counts: np.ndarray) -> np.ndarray:
    """Concatenated [0..c) ranges: _ranges([2,3]) -> [0,1,0,1,2]."""
    counts = counts.astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    offsets = np.cumsum(counts) - counts  # output offset of each group
    return np.arange(total, dtype=np.int64) - np.repeat(offsets, counts)


def _key_codes(table: EncodedTable, cols: list[str]) -> np.ndarray:
    """Combined int64 key codes for a list of columns; -1 where any is null.

    Each entry is either a plain column name or a side-stripped derived-key
    expression (``substr(surname,1,3)``) evaluated host-side by
    splink_tpu/derived_keys.py — from here on a derived key is just codes.

    Cached per column tuple on the table instance (the `_uid_ranks`
    pattern): the overlap regime estimator and the blocking joins use the
    same keys, and refactorising billion-row columns twice would put
    minutes of duplicate work on the critical path."""
    cache = getattr(table, "_key_code_cache", None)
    if cache is None:
        cache = table._key_code_cache = {}
    key = tuple(cols)
    if key in cache:
        return cache[key]
    out = _key_codes_uncached(table, cols)
    cache[key] = out
    return out


def clear_key_code_cache(table: EncodedTable) -> None:
    """Drop the per-table key-code caches once their consumers (estimator,
    plan build, blocking joins) are done — at billions of rows each cached
    tuple is an 8-bytes-per-row array that must not outlive blocking."""
    if getattr(table, "_key_code_cache", None):
        table._key_code_cache = {}
    if getattr(table, "_asym_code_cache", None):
        table._asym_code_cache = {}
    from .derived_keys import clear_derived_key_cache

    clear_derived_key_cache(table)


def _pack_codes(combined: np.ndarray | None, codes: np.ndarray) -> np.ndarray:
    """Fold one more key's codes into the running combination, refactorising
    to keep codes < n_rows; -1 (null) anywhere makes the whole key null."""
    if combined is None:
        return codes.astype(np.int64)
    card = int(codes.max()) + 1 if len(codes) else 1
    null = (combined < 0) | (codes < 0)
    packed = combined * card + codes
    packed[null] = -1
    uniq, inv = np.unique(packed[~null], return_inverse=True)
    out = np.full(len(packed), -1, np.int64)
    out[~null] = inv
    return out


def _key_codes_uncached(table: EncodedTable, cols: list[str]) -> np.ndarray:
    combined: np.ndarray | None = None
    for col in cols:
        combined = _pack_codes(combined, _single_col_codes(table, col))
    assert combined is not None
    return combined


def _single_col_codes(table: EncodedTable, col: str) -> np.ndarray:
    if col in table.strings:
        return table.strings[col].token_ids.astype(np.int64)
    if col in table.numerics:
        nc = table.numerics[col]
        uniq, inv = np.unique(nc.values_f64[~nc.null_mask], return_inverse=True)
        out = np.full(table.n_rows, -1, np.int64)
        out[~nc.null_mask] = inv
        return out
    if col in table.raw:
        import pandas as pd

        codes, _ = pd.factorize(pd.Series(table.raw[col]))
        return codes.astype(np.int64)
    from .derived_keys import is_plain_column, key_values_object

    if is_plain_column(col):
        # a bare column name that is in no column family: unknown column
        raise KeyError(col)
    # derived-key expression: evaluate host-side, factorise
    import pandas as pd

    vals, null = key_values_object(table, col)
    codes, _ = pd.factorize(pd.Series(vals))
    codes = codes.astype(np.int64)
    codes[null] = -1
    return codes


def _key_codes_asym(
    table: EncodedTable,
    sym_cols: list[str],
    asym_pairs: list[tuple[str, str]],
) -> tuple[np.ndarray, np.ndarray]:
    """(codes_l, codes_r) for a rule whose equality terms include
    cross-column / cross-expression keys (``l.a = r.b``): each asymmetric
    key pair factorises BOTH sides over one shared vocabulary so equal
    values share a code across sides; symmetric keys contribute the same
    code array to both sides. Cached per (sym, asym) signature."""
    cache = getattr(table, "_asym_code_cache", None)
    if cache is None:
        cache = table._asym_code_cache = {}
    key = (tuple(sym_cols), tuple(asym_pairs))
    if key in cache:
        return cache[key]

    import pandas as pd

    from .derived_keys import key_values_object

    n = table.n_rows
    combined_l: np.ndarray | None = None
    combined_r: np.ndarray | None = None
    # every key folds through the PAIR packer (symmetric keys contribute the
    # same codes to both sides): refactorisation always runs over the union
    # of both sides, so the running combined codes stay comparable across
    # sides no matter how sym/asym keys interleave
    for col in sym_cols:
        codes = _single_col_codes(table, col)
        combined_l, combined_r = _pack_codes_pair(
            combined_l, codes, combined_r, codes
        )
    for lexpr, rexpr in asym_pairs:
        vl, nl_ = key_values_object(table, lexpr)
        vr, nr_ = key_values_object(table, rexpr)
        joint, _ = pd.factorize(pd.Series(np.concatenate([vl, vr])))
        joint = joint.astype(np.int64)
        cl, cr = joint[:n].copy(), joint[n:].copy()
        cl[nl_] = -1
        cr[nr_] = -1
        combined_l, combined_r = _pack_codes_pair(
            combined_l, cl, combined_r, cr
        )
    assert combined_l is not None and combined_r is not None
    cache[key] = (combined_l, combined_r)
    return cache[key]


def _pack_codes_pair(
    comb_l: np.ndarray | None,
    cl: np.ndarray,
    comb_r: np.ndarray | None,
    cr: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """Fold one key's (cl, cr) codes into the running (combined_l,
    combined_r), refactorising over the UNION of both sides so codes stay
    comparable across sides. -1 (null) anywhere nulls the whole key."""
    if comb_l is None:
        return cl.astype(np.int64), cr.astype(np.int64)
    card = max(int(max(cl.max(initial=-1), cr.max(initial=-1))) + 1, 1)
    packed_all = []
    for comb, c in ((comb_l, cl), (comb_r, cr)):
        null = (comb < 0) | (c < 0)
        packed = comb * card + c
        packed[null] = -1
        packed_all.append(packed)
    both = np.concatenate(packed_all)
    valid = both >= 0
    uniq, inv = np.unique(both[valid], return_inverse=True)
    res = np.full(len(both), -1, np.int64)
    res[valid] = inv
    n = len(comb_l)
    return res[:n], res[n:]


def _sort_groups(codes: np.ndarray, rows: np.ndarray):
    """Sort rows by code; return (sorted_rows, unique_codes, starts, sizes)."""
    order = np.argsort(codes[rows], kind="stable")
    rows_sorted = rows[order]
    codes_sorted = codes[rows][order]
    if len(codes_sorted) == 0:
        return rows_sorted, codes_sorted[:0], np.zeros(0, np.int64), np.zeros(0, np.int64)
    boundary = np.r_[True, codes_sorted[1:] != codes_sorted[:-1]]
    starts = np.flatnonzero(boundary).astype(np.int64)
    sizes = np.diff(np.r_[starts, len(codes_sorted)]).astype(np.int64)
    return rows_sorted, codes_sorted[starts], starts, sizes


def _idx_dtype(n_rows: int):
    return np.int32 if n_rows < 2**31 else np.int64


def _iter_self_join_chunks(
    codes: np.ndarray, order: np.ndarray | None = None,
    chunk: int | None = None,
):
    """Yield (i, j) chunks of at most ~``chunk`` pairs for the within-group
    self-join, in :func:`_self_join`'s emission order.

    With ``order`` (per-row ranks), group members are pre-sorted by rank so
    each emitted pair already satisfies rank_i < rank_j — orientation comes
    out of the join for free instead of costing a full-size gather + where
    pass over billions of pairs. Emits int32 indices when the table allows.

    The expansion intermediates (``np.repeat`` over sizes, :func:`_ranges`)
    are built PER CHUNK, so peak host RAM is O(chunk) no matter how many
    pairs the rule produces — previously a budget/spill run still built the
    full-pair-count repeat arrays in one shot.
    """
    rows = np.flatnonzero(codes >= 0).astype(_idx_dtype(len(codes)))
    if order is not None:
        rows = rows[np.argsort(order[rows], kind="stable")]
    rows_sorted, _, starts, sizes = _sort_groups(codes, rows)
    counts = (sizes * (sizes - 1)) // 2
    cap = chunk if chunk else max(int(counts.sum()), 1)
    g, n_groups = 0, len(sizes)
    while g < n_groups:
        if counts[g] > cap:
            # giant group: split its triangle by a-rows so each slice
            # emits at most ~cap pairs; a single a-row wider than the cap
            # (near-constant key) further splits its contiguous b-range,
            # so the O(cap) bound holds for ANY group shape
            s0, s = int(starts[g]), int(sizes[g])
            rem = (s - 1) - np.arange(s - 1, dtype=np.int64)
            cum = np.cumsum(rem)
            k = 0
            while k < s - 1:
                if rem[k] > cap:
                    i_row = rows_sorted[s0 + k]
                    for b0 in range(k + 1, s, cap):
                        q = rows_sorted[s0 + b0 : s0 + min(b0 + cap, s)]
                        yield np.full(len(q), i_row, rows_sorted.dtype), q
                    k += 1
                    continue
                base = int(cum[k - 1]) if k else 0
                # last k2 with cum[k2-1] <= base + cap: the packed rows'
                # pairs stay within the cap (rows wider than the cap were
                # peeled off above)
                k2 = int(np.searchsorted(cum, base + cap, side="right"))
                k2 = min(max(k2, k + 1), s - 1)
                sub = np.arange(k, k2, dtype=np.int64)
                rep = (s - 1) - sub
                p = np.repeat(sub, rep) + s0
                q = p + 1 + _ranges(rep)
                yield rows_sorted[p], rows_sorted[q]
                k = k2
            g += 1
            continue
        # greedy span of whole groups with total pairs <= cap
        g2, tot = g, 0
        while g2 < n_groups and tot + counts[g2] <= cap:
            tot += counts[g2]
            g2 += 1
        g2 = max(g2, g + 1)
        st, sz = starts[g:g2], sizes[g:g2]
        native_out = native.self_join_pairs(rows_sorted, st, sz)
        if native_out is not None:
            yield native_out
            g = g2
            continue
        # numpy fallback: position k within its group pairs with the
        # (s-1-k) following positions; span rows are contiguous in
        # rows_sorted so global positions are span-offset + local
        pos_in_group = _ranges(sz)
        rep = np.repeat(sz, sz) - pos_in_group - 1
        span_len = int(sz.sum())
        p = np.repeat(np.arange(span_len, dtype=np.int64), rep) + int(
            st[0] if len(st) else 0
        )
        q = p + 1 + _ranges(rep)
        yield rows_sorted[p], rows_sorted[q]
        g = g2


def _self_join(
    codes: np.ndarray, order: np.ndarray | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """All unordered within-group pairs for non-null codes, in one array
    pair (see :func:`_iter_self_join_chunks` for the chunked form)."""
    out = list(_iter_self_join_chunks(codes, order))
    if not out:
        dt = _idx_dtype(len(codes))
        return np.zeros(0, dt), np.zeros(0, dt)
    if len(out) == 1:
        return out[0]
    return (
        np.concatenate([c[0] for c in out]),
        np.concatenate([c[1] for c in out]),
    )


def _iter_cross_join_chunks(
    codes_l: np.ndarray,
    left_rows: np.ndarray,
    right_rows: np.ndarray,
    codes_r: np.ndarray | None = None,
    chunk: int | None = None,
):
    """Yield (i, j) chunks of at most ~``chunk`` pairs for the cross join,
    in :func:`_cross_join`'s emission order. With ``codes_r`` the two sides
    read different code arrays (an asymmetric key like ``l.a = r.b`` — both
    factorised over one shared vocabulary by _key_codes_asym); otherwise
    one array serves both. Expansion intermediates are per chunk, like
    :func:`_iter_self_join_chunks`."""
    if codes_r is None:
        codes_r = codes_l
    lrows, lcodes, lstarts, lsizes = _sort_groups(
        codes_l, left_rows[codes_l[left_rows] >= 0]
    )
    rrows, rcodes, rstarts, rsizes = _sort_groups(
        codes_r, right_rows[codes_r[right_rows] >= 0]
    )
    # intersect group keys
    common, li, ri = np.intersect1d(lcodes, rcodes, return_indices=True)
    if len(common) == 0:
        return
    ls, lz = lstarts[li], lsizes[li]
    rs, rz = rstarts[ri], rsizes[ri]
    counts = lz * rz
    cap = chunk if chunk else max(int(counts.sum()), 1)
    g, n_groups = 0, len(common)
    while g < n_groups:
        if counts[g] > cap:
            # giant group: split its rectangle by l-rows; an r-side wider
            # than the cap further splits each l-row's contiguous r-range,
            # so the O(cap) bound holds for ANY group shape
            l0, lzg = int(ls[g]), int(lz[g])
            r0, rzg = int(rs[g]), int(rz[g])
            if rzg > cap:
                for a in range(lzg):
                    i_row = lrows[l0 + a]
                    for b0 in range(0, rzg, cap):
                        q = rrows[r0 + b0 : r0 + min(b0 + cap, rzg)]
                        yield np.full(len(q), i_row, lrows.dtype), q
                g += 1
                continue
            rows_per = max(cap // rzg, 1)
            right_span = np.arange(r0, r0 + rzg, dtype=np.int64)
            for a0 in range(0, lzg, rows_per):
                a1 = min(a0 + rows_per, lzg)
                p = np.repeat(
                    np.arange(a0, a1, dtype=np.int64) + l0, rzg
                )
                q = np.tile(right_span, a1 - a0)
                yield lrows[p], rrows[q]
            g += 1
            continue
        g2, tot = g, 0
        while g2 < n_groups and tot + counts[g2] <= cap:
            tot += counts[g2]
            g2 += 1
        g2 = max(g2, g + 1)
        span = slice(g, g2)
        native_out = native.cross_join_pairs(
            lrows, ls[span], lz[span], rrows, rs[span], rz[span]
        )
        if native_out is not None:
            yield native_out
            g = g2
            continue
        cnt = counts[span]
        gi = np.repeat(np.arange(g2 - g, dtype=np.int64), cnt)
        t = _ranges(cnt)
        a = t // rz[span][gi] + ls[span][gi]
        b = t % rz[span][gi] + rs[span][gi]
        yield lrows[a], rrows[b]
        g = g2


def _cross_join(
    codes_l: np.ndarray,
    left_rows: np.ndarray,
    right_rows: np.ndarray,
    codes_r: np.ndarray | None = None,
):
    """All cross pairs whose key codes match, in one array pair (see
    :func:`_iter_cross_join_chunks` for the chunked form)."""
    out = list(
        _iter_cross_join_chunks(codes_l, left_rows, right_rows, codes_r)
    )
    if not out:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    if len(out) == 1:
        return out[0]
    return (
        np.concatenate([c[0] for c in out]),
        np.concatenate([c[1] for c in out]),
    )


# ----------------------------------------------------------------------
# Pair orientation / where-condition per link type
# ----------------------------------------------------------------------


def _uid_ranks(table: EncodedTable, link_type: str):
    """(ranks, keys_unique): int32 rank of each row in the reference's
    ordering — uid for dedupe_only, (source_table, uid) for link_and_dedupe —
    plus whether the ordering keys are unique (they almost always are, which
    lets orientation skip the drop-equal-key pass entirely). Rank comparisons
    replace per-pair gathers of arbitrary-dtype uid arrays: at billions of
    candidate pairs the int32 rank gather halves the transient footprint and
    avoids object-dtype comparisons for string uids. Cached per table."""
    cache = getattr(table, "_uid_rank_cache", None)
    if cache is None:
        cache = table._uid_rank_cache = {}
    if link_type not in cache:
        uid = np.asarray(table.unique_id)
        if link_type == "link_and_dedupe":
            order = np.lexsort((uid, table.source_table))
        else:
            order = np.argsort(uid, kind="stable")
        ranks = np.empty(len(uid), np.int32)
        ranks[order] = np.arange(len(uid), dtype=np.int32)
        sorted_uid = uid[order]
        if len(uid) < 2:
            keys_unique = True
        elif link_type == "link_and_dedupe":
            sorted_src = table.source_table[order]
            keys_unique = bool(
                (
                    (sorted_uid[1:] != sorted_uid[:-1])
                    | (sorted_src[1:] != sorted_src[:-1])
                ).all()
            )
        else:
            keys_unique = bool((sorted_uid[1:] != sorted_uid[:-1]).all())
        cache[link_type] = (ranks, keys_unique)
    return cache[link_type]


def _drop_equal_key_pairs(
    table: EncodedTable, link_type: str, i: np.ndarray, j: np.ndarray
):
    """Drop pairs whose ordering keys collide (duplicate uids in the input):
    the reference's strict l.uid < r.uid / (source, uid) ordering excludes
    them. Only reached when the input really contains duplicates."""
    uid = table.unique_id
    if link_type == "link_and_dedupe":
        st = table.source_table
        keep = ~((st[i] == st[j]) & (uid[i] == uid[j]))
    else:
        keep = uid[i] != uid[j]
    return i[keep], j[keep]


def _orient_pairs(table: EncodedTable, link_type: str, i: np.ndarray, j: np.ndarray):
    """Apply the reference's where-condition semantics to unordered pairs."""
    if link_type == "dedupe_only":
        ranks, uids_unique = _uid_ranks(table, link_type)
        ri, rj = ranks[i], ranks[j]
        if not uids_unique:
            # duplicated uids: drop equal-uid pairs (the reference's
            # l.uid < r.uid keeps them out)
            uid = table.unique_id
            keep = uid[i] != uid[j]
            i, j, ri, rj = i[keep], j[keep], ri[keep], rj[keep]
        swap = rj < ri
        return np.where(swap, j, i), np.where(swap, i, j)
    if link_type == "link_and_dedupe":
        ranks, combos_unique = _uid_ranks(table, link_type)
        ri, rj = ranks[i], ranks[j]
        if combos_unique:
            keep = ri != rj  # drops same-source same-uid self matches
        else:
            st = table.source_table
            uid = table.unique_id
            keep = ~((st[i] == st[j]) & (uid[i] == uid[j]))
        i, j, ri, rj = i[keep], j[keep], ri[keep], rj[keep]
        swap = rj < ri
        return np.where(swap, j, i), np.where(swap, i, j)
    return i, j  # link_only: orientation fixed by construction


# ----------------------------------------------------------------------
# Residual (non-equality) predicate evaluation
# ----------------------------------------------------------------------


def _eval_residual(table: EncodedTable, residual: str, i: np.ndarray, j: np.ndarray):
    """Evaluate a translated residual predicate on candidate pairs via the
    typed AST interpreter (splink_tpu/residual_eval.py): string columns
    compare through lexicographic rank arrays, comparisons follow SQL null
    semantics, and no ``eval`` is involved."""
    from .residual_eval import evaluate_residual

    mask = evaluate_residual(table, residual, i, j)
    return i[mask], j[mask]


# ----------------------------------------------------------------------
# Public entry points
# ----------------------------------------------------------------------


@check_types
def estimate_pair_upper_bound(
    settings: dict,
    table: EncodedTable,
    n_left: int | None = None,
    include_approx: bool = True,
) -> int:
    """Cheap O(n) upper bound on the candidate-pair count: per-rule join
    sizes from key-group histograms, ignoring sequential-rule dedup and
    residual filters (both only remove pairs). The linker uses it to pick
    the overlap consumer BEFORE blocking runs — resident-size jobs stream
    the gamma matrix (keeping it device-resident for EM), larger ones
    stream 3-byte pattern ids."""
    link_type = settings["link_type"]
    rules = settings.get("blocking_rules") or []
    n = table.n_rows
    if not rules:
        if link_type == "link_only":
            assert n_left is not None
            return n_left * (n - n_left)
        return n * (n - 1) // 2
    bound = sum(
        _rule_group_stats(link_type, table, rule, n_left)[1] for rule in rules
    )
    if include_approx and settings.get("approx_blocking"):
        # the approximate tier appends at most its explicit pair budget —
        # but only when it can actually run (a job with no sketchable
        # string column skips the tier and contributes zero), and never
        # more than the job's total possible pair count (the default 4M
        # budget must not push a 500-row job past the resident gate or
        # inflate its single gamma batch).
        # ``include_approx=False`` gives the EXACT-rules-only bound, which
        # is what the device-blocking auto gate sizes its jit-warmup
        # decision on (the approx tier has its own kernels either way).
        from .approx.lsh import DEFAULT_BUDGET, approx_columns

        if approx_columns(settings, table):
            budget = int(settings.get("approx_pair_budget") or DEFAULT_BUDGET)
            if link_type == "link_only" and n_left is not None:
                total = n_left * (n - n_left)
            else:
                total = n * (n - 1) // 2
            bound += min(budget, total)
    return bound


def _rule_group_stats(
    link_type: str, table: EncodedTable, rule: str, n_left: int | None
) -> tuple[np.ndarray | None, int]:
    """One rule's (key-group row histogram, upper-bound pair count) — the
    single definition behind :func:`estimate_pair_upper_bound` (which sums
    the bounds) and :func:`block_size_stats` (which reads the histogram).
    The histogram is None for a keyless (cartesian) rule; for link_only
    and asymmetric keys it is the combined l+r per-group row count."""
    eq_pairs, residual = parse_blocking_rule(rule)
    sym_cols, asym, residual = _split_join_keys(eq_pairs, residual)
    if not sym_cols and not asym:
        return None, table.n_rows * table.n_rows
    if asym:
        codes_l, codes_r = _key_codes_asym(table, sym_cols, asym)
    else:
        codes_l = codes_r = _key_codes(table, sym_cols)
    m = (
        int(max(codes_l.max(initial=-1), codes_r.max(initial=-1))) + 1
        if len(codes_l)
        else 0
    )
    if m <= 0:
        return np.zeros(0, np.int64), 0
    if link_type == "link_only":
        assert n_left is not None
        cl, cr = codes_l[:n_left], codes_r[n_left:]
        hl = np.bincount(cl[cl >= 0], minlength=m).astype(np.int64)
        hr = np.bincount(cr[cr >= 0], minlength=m).astype(np.int64)
        return hl + hr, int(hl @ hr)
    if asym:
        # self-join on an asymmetric key: l-side histogram against
        # r-side histogram over-counts by the rank filter and the
        # diagonal — it stays an upper bound, which is the contract
        hl = np.bincount(codes_l[codes_l >= 0], minlength=m).astype(np.int64)
        hr = np.bincount(codes_r[codes_r >= 0], minlength=m).astype(np.int64)
        return hl + hr, int(hl @ hr)
    valid = codes_l[codes_l >= 0]
    if not len(valid):
        return np.zeros(0, np.int64), 0
    cnt = np.bincount(valid, minlength=m).astype(np.int64)
    return cnt, int((cnt * (cnt - 1) // 2).sum())


def block_size_stats(
    settings: dict, table: EncodedTable, n_left: int | None = None, top: int = 5
) -> list[dict]:
    """Per-rule block-size telemetry from the same O(n) key-group
    histograms as :func:`estimate_pair_upper_bound` (the key-code cache
    makes the second walk nearly free). Skewed blocks are the central
    scalability risk of rule-based blocking (arxiv 1905.06167) and what
    progressive blocking manages dynamically (arxiv 2005.14326) — this is
    the machine-readable record of which blocks dominated a run, the
    replacement for eyeballing the Spark UI's task-skew view.

    Returns one dict per rule: number of non-null key groups, the
    ``top``-largest group row counts (descending), and that rule's
    upper-bound pair contribution.
    """
    link_type = settings["link_type"]
    rules = settings.get("blocking_rules") or []
    stats: list[dict] = []
    for rule in rules:
        entry = {"rule": rule, "n_groups": 0, "top_group_rows": [],
                 "pair_bound": 0}
        try:
            h, entry["pair_bound"] = _rule_group_stats(
                link_type, table, rule, n_left
            )
            if h is not None:
                nz = h[h > 0]
                entry["n_groups"] = int(len(nz))
                if len(nz):
                    largest = np.sort(nz)[::-1][:top]
                    entry["top_group_rows"] = [int(v) for v in largest]
        except Exception as e:  # noqa: BLE001 - telemetry is best-effort
            entry["error"] = f"{type(e).__name__}: {e}"[:200]
        stats.append(entry)
    return stats


def block_using_rules(
    settings: dict,
    table: EncodedTable,
    n_left: int | None = None,
    pair_consumer=None,
) -> PairIndex:
    """Generate candidate pairs for the given settings.

    Args:
        settings: completed settings dict.
        table: the encoded input table. For link_only / link_and_dedupe this
            is the vertical concatenation of both inputs (rows [0, n_left)
            from the left input).
        n_left: number of left-input rows (link types only).
        pair_consumer: optional callable(i, j) invoked with every pair chunk
            in emission order, right after it is sunk. The linker passes a
            device-scoring stream here so gamma/pattern computation OVERLAPS
            blocking (jax dispatch is async: the accelerator crunches rule
            k's pairs while the host joins rule k+1) instead of a second
            sweep over the finished — possibly disk-spilled — pair index.
            Spark got this overlap for free from lazy evaluation
            (/root/reference/splink/blocking.py:210).
    """
    link_type = settings["link_type"]
    rules = settings.get("blocking_rules") or []
    if not rules:
        return cartesian_block(settings, table, n_left, pair_consumer)

    # Pair indices are stored int32 when the table allows (they always do —
    # int32 row indices cover 2^31 rows); at billions of candidate pairs this
    # halves the resident footprint of the pair set.
    idx_dtype = _idx_dtype(table.n_rows)
    all_rows = np.arange(table.n_rows, dtype=idx_dtype)

    # Sequential-rule dedup by PREDICATE, the literal semantics of the
    # reference's ``AND NOT ifnull(previous_rule, false)``
    # (/root/reference/splink/blocking.py:59-68): a candidate of rule k is
    # kept iff NO earlier rule's predicate holds for it. Evaluating earlier
    # predicates on rule k's candidates costs O(pairs_k) per earlier rule and
    # needs no sorted pair-set accumulation (the round-1 design re-sorted a
    # packed pair-id set per rule — minutes of host time and two extra
    # full-size copies at the 10M-row configs).
    prior_rules: list[tuple[np.ndarray | None, str | None]] = []
    sink = _PairSink(settings.get("spill_dir"), idx_dtype)
    # The approximate tier (splink_tpu/approx/: minhash-LSH band joins +
    # q-gram verification + progressive pair budgeting) runs AFTER the
    # exact rules when opted in — it composes through the same sequential
    # dedup semantics (a pair any exact rule produced is never re-emitted)
    # and appends its budget-ordered chunks to the same sink.
    approx_on = bool(settings.get("approx_blocking"))
    with sink:
        # Device-native tier first (blocking_device.py): the sort-based
        # hash join runs as jitted kernels and streams budgeted chunks into
        # the same sink. Falls through to the host join for unsupported
        # shapes (cartesian rules, uncompilable residuals, monster groups)
        # or "auto"-mode jobs too small to pay the jit warmup — the host
        # path below stays the fallback AND the parity oracle.
        mode = settings.get("device_blocking", "auto")
        exact_done = False
        if mode in ("auto", "on"):
            from .blocking_device import device_block_rules

            out = device_block_rules(
                settings, table, n_left, sink, pair_consumer, mode,
                finish=not approx_on,
            )
            if out is not None:
                if not approx_on:
                    return out
                exact_done = True
        if not exact_done:
            out = _block_rules_into(
                sink, rules, settings, table, link_type, all_rows, n_left,
                prior_rules, pair_consumer, finish=not approx_on,
            )
            if not approx_on:
                return out
        from .approx import approx_block_into

        approx_block_into(settings, table, n_left, sink, pair_consumer)
        return sink.finish()


def _block_rules_into(
    sink, rules, settings, table, link_type, all_rows, n_left, prior_rules,
    pair_consumer=None, finish: bool = True,
) -> PairIndex | None:
    # Per-rule pairs are generated and CONSUMED in bounded chunks: the
    # residual/dedup filters are elementwise, so running them chunk-wise is
    # semantics-preserving and keeps peak host RAM at O(chunk) — the
    # expansion intermediates (np.repeat / _ranges) no longer materialise
    # over a rule's full pair count when a budget or spill cap applies.
    chunk_cap = int(settings.get("blocking_chunk_pairs") or 0) or None
    if link_type == "link_only":
        assert n_left is not None
        left_rows, right_rows = all_rows[:n_left], all_rows[n_left:]
    for rule in rules:
        eq_pairs, residual = parse_blocking_rule(rule)
        sym_cols, asym, residual = _split_join_keys(eq_pairs, residual)

        rank_filter = False
        if asym:
            # asymmetric equality keys (l.a = r.b): hash join over the
            # shared-vocabulary code pair
            codes_l, codes_r = _key_codes_asym(table, sym_cols, asym)
            if link_type == "link_only":
                chunks = _iter_cross_join_chunks(
                    codes_l, left_rows, right_rows, codes_r, chunk_cap
                )
            else:
                # f(l) = g(r) was written with the l side first; the
                # reference's join enumerates ordered (l, r) pairs and its
                # where-condition keeps rank_l < rank_r — so cross-join the
                # table against itself and keep that orientation (no swap:
                # swapping would change which side each expression applies
                # to)
                chunks = _iter_cross_join_chunks(
                    codes_l, all_rows, all_rows, codes_r, chunk_cap
                )
                rank_filter = True
        elif sym_cols:
            codes_l = codes_r = _key_codes(table, sym_cols)
            if link_type == "link_only":
                # oriented by construction: left input on the l side
                chunks = _iter_cross_join_chunks(
                    codes_l, left_rows, right_rows, chunk=chunk_cap
                )
            else:
                # group members pre-sorted by uid rank -> pairs come out
                # already oriented; only duplicate-key inputs need the
                # drop-equal pass
                ranks, keys_unique = _uid_ranks(table, link_type)
                chunks = _iter_self_join_chunks(
                    codes_l, order=ranks, chunk=chunk_cap
                )
        else:
            codes_l = codes_r = None
            warnings.warn(
                f"Blocking rule {rule!r} has no equality condition; evaluating "
                "it against all row pairs (quadratic)."
            )
            chunks = (
                _iter_all_pairs_chunks(
                    table, link_type, n_left, chunk_cap or _CARTESIAN_CHUNK
                )
            )
        n_new = 0
        for i, j in chunks:
            if codes_l is None:
                i, j = _orient_pairs(table, link_type, i, j)
            elif rank_filter:
                ranks, keys_unique = _uid_ranks(table, link_type)
                keep = ranks[i] < ranks[j]
                i, j = i[keep], j[keep]
                if not keys_unique:
                    i, j = _drop_equal_key_pairs(table, link_type, i, j)
            elif sym_cols and link_type != "link_only" and not keys_unique:
                i, j = _drop_equal_key_pairs(table, link_type, i, j)
            if residual is not None:
                i, j = _eval_residual(table, residual, i, j)
            for prev_l, prev_r, prev_residual in prior_rules:
                holds = _rule_holds(
                    table, prev_l, prev_r, prev_residual, i, j
                )
                keep = ~holds
                i, j = i[keep], j[keep]
            n_new += len(i)
            sink.append(i, j)
            if pair_consumer is not None:
                pair_consumer(
                    i.astype(sink.idx_dtype, copy=False),
                    j.astype(sink.idx_dtype, copy=False),
                )
            del i, j

        prior_rules.append((codes_l, codes_r, residual))
        logger.debug("blocking rule %r -> %d new pairs", rule, n_new)

    return sink.finish() if finish else None


def _rule_holds(
    table: EncodedTable,
    codes_l: np.ndarray | None,
    codes_r: np.ndarray | None,
    residual: str | None,
    i: np.ndarray,
    j: np.ndarray,
) -> np.ndarray:
    """Whether an (earlier) rule's predicate holds for each candidate pair:
    combined join-key equality (null keys never match) AND the residual
    (UNKNOWN counts as not-holding — ifnull(..., false)). Candidates are
    already oriented with i on the l side, so an asymmetric earlier rule
    reads codes_l[i] against codes_r[j]."""
    if codes_l is not None:
        ci, cj = codes_l[i], codes_r[j]
        holds = (ci == cj) & (ci >= 0)
    else:
        holds = np.ones(len(i), bool)
    if residual is not None:
        sub = np.flatnonzero(holds)
        if len(sub):
            from .residual_eval import evaluate_residual

            holds[sub] = evaluate_residual(table, residual, i[sub], j[sub])
    return holds


def _split_join_keys(
    eq_pairs, residual: str | None
) -> tuple[list[str], list[tuple[str, str]], str | None]:
    """-> (sym_cols, asym_pairs, residual). Same-expression equalities
    (``l.x = r.x``, ``substr(l.x,1,3) = substr(r.x,1,3)``) become symmetric
    hash-join keys; cross-column / cross-expression equalities (``l.a =
    r.b`` — a name-swap block, say) keep distinct left/right keys and
    hash-join through a shared vocabulary (_key_codes_asym) instead of the
    round-3 behaviour of filtering them as residuals after a join on the
    remaining keys (quadratic when they were the ONLY equality)."""
    sym: list[str] = []
    asym: list[tuple[str, str]] = []
    for lc, rc in eq_pairs:
        if lc == rc:
            sym.append(lc)
        else:
            asym.append((lc, rc))
    return sym, asym, residual


def _all_pairs(table: EncodedTable, link_type: str, n_left: int | None):
    n = table.n_rows
    if link_type == "link_only":
        assert n_left is not None
        n_right = n - n_left
        i = np.repeat(np.arange(n_left, dtype=np.int64), n_right)
        j = np.tile(np.arange(n_left, n, dtype=np.int64), n_left)
        return i, j
    tri = np.triu_indices(n, k=1)
    return tri[0].astype(np.int64), tri[1].astype(np.int64)


def _iter_all_pairs_chunks(table: EncodedTable, link_type: str, n_left, chunk):
    """Yield the cartesian pair set in bounded-memory (i, j) chunks of at
    most ~``chunk`` pairs, in the same order _all_pairs produces."""
    n = table.n_rows
    if link_type == "link_only":
        assert n_left is not None
        n_right = n - n_left
        rows_per = max(1, chunk // max(n_right, 1))
        right = np.arange(n_left, n, dtype=np.int64)
        for a in range(0, n_left, rows_per):
            b = min(a + rows_per, n_left)
            i = np.repeat(np.arange(a, b, dtype=np.int64), n_right)
            j = np.tile(right, b - a)
            yield i, j
        return
    # dedupe-style upper triangle (i < j), emitted row-block by row-block
    a = 0
    while a < n - 1:
        b = a + 1
        total = n - 1 - a
        while b < n - 1 and total + (n - 1 - b) <= chunk:
            total += n - 1 - b
            b += 1
        counts = (n - 1) - np.arange(a, b, dtype=np.int64)
        i = np.repeat(np.arange(a, b, dtype=np.int64), counts)
        starts = np.repeat(np.arange(a, b, dtype=np.int64) + 1, counts)
        within = np.arange(len(i), dtype=np.int64) - np.repeat(
            np.cumsum(counts) - counts, counts
        )
        j = starts + within
        yield i, j
        a = b


def cartesian_block(
    settings: dict,
    table: EncodedTable,
    n_left: int | None = None,
    pair_consumer=None,
) -> PairIndex:
    """All pairwise comparisons (the fallback when no rules are given,
    /root/reference/splink/blocking.py:183-184, 219-318). With spill_dir the
    pair set is generated and streamed to disk in bounded-memory chunks."""
    link_type = settings["link_type"]
    spill_dir = settings.get("spill_dir")
    idx_dtype = _idx_dtype(table.n_rows)
    if not spill_dir:
        i, j = _all_pairs(table, link_type, n_left)
        i, j = _orient_pairs(table, link_type, i, j)
        i = i.astype(idx_dtype, copy=False)
        j = j.astype(idx_dtype, copy=False)
        if pair_consumer is not None:
            pair_consumer(i, j)
        return PairIndex(i, j)
    with _PairSink(spill_dir, idx_dtype) as sink:
        for i, j in _iter_all_pairs_chunks(
            table, link_type, n_left, _CARTESIAN_CHUNK
        ):
            i, j = _orient_pairs(table, link_type, i, j)
            sink.append(i, j)
            if pair_consumer is not None:
                pair_consumer(
                    i.astype(idx_dtype, copy=False),
                    j.astype(idx_dtype, copy=False),
                )
        return sink.finish()
