.PHONY: test tpu-smoke bench bench-blocking all

# CPU oracle/golden tier: 8 virtual devices, runs anywhere.
test:
	python -m pytest tests/ -x -q

# Hardware smoke tier: real TPU lowering of Pallas kernels + pipeline.
# Separate invocation because tests/conftest.py pins its process to CPU.
# Skips cleanly when no TPU backend is present; exits 5 (nothing collected)
# when the accelerator backend is unreachable — treated as a skip.
tpu-smoke:
	python -m pytest tests_tpu/ -q || [ $$? -eq 5 ]

bench:
	python bench.py

# Host-side blocking throughput at 10M rows (no device work; ~15 min).
bench-blocking:
	python benchmarks/blocking_bench.py

all: test tpu-smoke bench
