.PHONY: test lint shard-baselines perf-baselines num-baselines tpu-smoke obs-smoke serve-smoke chaos-smoke wire-smoke thread-smoke blocking-smoke approx-smoke trace-smoke warmup-smoke drift-smoke perf-smoke tf-smoke scale-smoke fleet-smoke num-smoke bench bench-blocking all

# CPU oracle/golden tier: 8 virtual devices, runs anywhere.
test:
	python -m pytest tests/ -x -q

# Static analysis gate — all six layers (splink_tpu/analysis/):
#   1  jaxlint      AST pass over the package (JL001-JL012)
#   2  trace audit  jaxpr audit of the kernel registry
#   3  shard audit  SPMD partition-safety + cost budgets on the 8-device mesh
#   4  perf audit   measured runtime/memory budgets (--list-perf-kernels here;
#                   the measured gate runs in perf-smoke)
#   5  threadlint   concurrency-safety audit of the serve/obs thread fleet
#                   (TL001-TL005; dynamic half: thread-smoke)
#   6  numlint      numerical-hygiene AST pass (NL001-NL008, rides the same
#                   paths invocation; measured half --num-audit runs in
#                   num-smoke against num_baselines.json)
# Exit 1 on any unsuppressed finding, undeclared collective, cost-budget
# drift, or thread-safety hazard; tests/test_codebase_clean.py enforces the
# same gates in tier-1. (The CLI pins JAX_PLATFORMS/XLA_FLAGS itself for
# --shard-audit; set here too so the whole invocation runs the same config.)
lint:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m splink_tpu.analysis splink_tpu/ --audit --shard-audit --thread-audit
	JAX_PLATFORMS=cpu python -m splink_tpu.analysis --list-perf-kernels

# Intentional refresh of the committed per-kernel cost/collective budgets
# (splink_tpu/analysis/shard_baselines.json) after an accepted perf change
# or a new shard kernel. Review the JSON diff like a benchmark result.
shard-baselines:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m splink_tpu.analysis --shard-audit --update-baselines

# Intentional refresh of the committed MEASURED per-(tier, kernel, shape)
# runtime/memory budgets (splink_tpu/analysis/perf_baselines.json, layer 4)
# after an accepted perf change or a new kernel. Only this tier's block is
# rewritten (hardware tiers add their own); review the diff like a bench.
perf-baselines:
	JAX_PLATFORMS=cpu \
		python -m splink_tpu.analysis --perf-audit --update-perf-baselines

# Intentional refresh of the committed per-(tier, kernel) f32/f64 ulp
# budgets (splink_tpu/analysis/num_baselines.json, layer 6) after an
# accepted numerics change or a new kernel. Only this tier's block is
# rewritten (hardware tiers add their own); review the diff like a bench —
# a wider budget means the f32 error bar grew.
num-baselines:
	JAX_PLATFORMS=cpu \
		python -m splink_tpu.analysis --num-audit --update-num-baselines

# Hardware smoke tier: real TPU lowering of Pallas kernels + pipeline.
# Separate invocation because tests/conftest.py pins its process to CPU.
# Skips cleanly when no TPU backend is present; exits 5 (nothing collected)
# when the accelerator backend is unreachable — treated as a skip.
tpu-smoke:
	python -m pytest tests_tpu/ -q || [ $$? -eq 5 ]

# Telemetry smoke: fixture linker run with the JSONL sink enabled (fault
# injection included), then the summarize + export-trace CLI over the
# record (docs/observability.md).
obs-smoke:
	python scripts/obs_smoke.py

# Serving smoke: build a LinkageIndex from the fixture corpus, serve 100
# queries through the micro-batching service, assert serve<->offline score
# parity (bit-identical) and zero steady-state recompiles (docs/serving.md).
serve-smoke:
	python scripts/serve_smoke.py

# Chaos smoke: run the service under EVERY registered serve fault site
# (worker death, batch exception, slow batch, breaker storm, index
# corruption, swap-validation failure — resilience/faults.py SERVE_SITES)
# and assert the resilience contract: no future hangs past its timeout, no
# exception escapes to a caller, fault/degradation events land in the
# JSONL sink, throughput recovers after each fault, and a hot-swap +
# brown-out episode stay recompile-free (docs/serving.md#resilience).
chaos-smoke:
	python scripts/chaos_smoke.py

# Wire chaos smoke: two real services behind loopback WireServers, a
# ReplicaRouter over RemoteReplica clients, driven through every wire
# fault site (resilience/faults.py WIRE_SITES — host kill mid-request,
# partition + heal, slow link tripping the hedger, torn frames, per-
# remote breaker storm) and assert the multi-host contract: no future
# hangs, no exception escapes, sheds are machine-readable, wire events
# land in the JSONL sink, remote answers stay bit-identical to local,
# and post-recovery steady state performs ZERO recompiles
# (docs/serving.md#multi-host).
wire-smoke:
	python scripts/wire_chaos_smoke.py

# Thread-safety smoke: the dynamic half of analysis layer 5. Every fleet
# lock is created through the lockwatch instrumented factories
# (SPLINK_TPU_LOCKWATCH=1), sys.setswitchinterval is lowered ~1000x, and
# a real engine + service + wire server + hedged router fleet is driven
# by concurrent submit threads, stats/health pollers and injected
# connection drops. Gates: a seeded A->B/B->A inversion IS detected
# (lock_inversion event + flight dump + lock_order_graph.json artifact),
# the real fleet shows ZERO inversions, the observed-union-declared lock
# graph stays acyclic, every future resolves, counters stay consistent,
# and steady state performs ZERO recompiles (docs/static_analysis.md#layer-5).
thread-smoke:
	python scripts/thread_smoke.py

# Device-blocking smoke: device<->host pair-set parity (the host join is
# the oracle) over sequential/null/asymmetric rules with budgeted chunked
# emission, plus zero steady-state recompiles across chunk shapes
# (docs/blocking.md).
blocking-smoke:
	python scripts/blocking_smoke.py

# Approximate-blocking smoke: minhash-LSH candidate-set determinism across
# two runs, approx_pair_budget held, zero steady-state recompiles across
# chunk shapes, and serve fallback parity with a host-side oracle —
# garbled queries return approx-tagged candidates whose scores are
# bit-identical to offline scoring of the same pairs
# (docs/blocking.md#approximate-tier).
approx-smoke:
	python scripts/approx_smoke.py

# Request-tracing smoke: the serving tier under an injected slow batch +
# breaker storm with tracing at full sample rate, asserting the
# attribution contract — per-request phase durations sum to the measured
# wall latency within 5%, every request closes exactly one span tree with
# a machine-readable outcome, the breaker storm dumps the flight recorder
# to a JSONL that round-trips through `obs summarize`, and steady-state
# recompiles stay at ZERO with tracing enabled
# (docs/observability.md#serve-tracing).
trace-smoke:
	python scripts/trace_smoke.py

# Cold-start smoke: process A builds an index + compiles the serve menu +
# commits the AOT executable sidecar; a FRESH process B restores the whole
# menu and the gate asserts zero backend compiles (jax.monitoring split
# accounting), zero persistent-cache reads, first-query scores bit-identical
# to process A, and the fused-kernel audits clean in the restored process
# (docs/serving.md#cold-start).
warmup-smoke:
	python scripts/warmup_smoke.py

# Drift smoke: build a profiled index, serve a clean query stream (quiet
# windows, zero recompiles with sketching on), then inject a skewed stream
# and assert the two-window drift alert fires, the flight recorder dumps,
# and `obs drift` + the Prometheus exposition render the captured record
# (docs/observability.md#drift).
drift-smoke:
	python scripts/drift_smoke.py

# Performance-observatory smoke: the layer-4 measured audit passes against
# the committed perf_baselines.json on this tier, steady-state traffic with
# the serve-time KernelWatch on performs zero compile requests, a
# monkeypatched slow engine trips the two-window perf alert (flight dump
# with the window snapshot inside, edge-triggered clear on recovery), and
# `obs summarize` + the Prometheus exposition render the perf series
# (docs/observability.md#perf).
perf-smoke:
	python scripts/perf_smoke.py

# Term-frequency smoke: serve<->offline TF-adjusted parity bit-identical
# (fused + unfused) on a TF-flagged model, a legacy TF-less artifact
# round-trips and serves unchanged, and a FRESH process restores the TF
# serve menu from the AOT sidecar with zero backend compiles and
# bit-identical first-query answers (docs/serving.md#term-frequency).
tf-smoke:
	python scripts/tf_smoke.py

# Offline-scale smoke: the billion-row write path's contracts — an
# out-of-core index build over a corpus larger than the configured
# working set is content-fingerprint-identical to the resident build,
# the sharded spill emission's pair set equals the ordinary path's with
# zero steady-state recompiles across chunk shapes and spill segments,
# and a build SIGKILLed mid-segment resumes from its manifest to a
# bit-identical fingerprint (docs/blocking.md#offline-scale).
scale-smoke:
	python scripts/scale_smoke.py

# Fleet observability smoke: two wire hosts + a tracing router on
# loopback under net_delay/net_partition faults — stitched cross-host
# waterfalls telescope inside the client wall, metric federation is
# bit-exact against the raw per-host exports, a partition burst
# produces one correlated incident bundle, and steady state with
# stitching on performs zero recompiles (docs/observability.md#fleet-observability).
fleet-smoke:
	python scripts/fleet_smoke.py

# Numerics smoke: the measured half of analysis layer 6. The corner-batch
# audit (NA-FIN finite outputs, NA-ULP f32/f64 divergence inside committed
# budgets, NA-MONO monotone match probabilities, NA-ORD pinned fold order)
# passes against num_baselines.json on this tier, a doctored ulp budget
# provably trips the gate, and the audit summary lands on the obs timeline
# as a num_audit flight transition (docs/static_analysis.md#layer-6).
num-smoke:
	python scripts/num_smoke.py

bench:
	python bench.py

# Host-side blocking throughput at 10M rows (no device work; ~15 min).
bench-blocking:
	python benchmarks/blocking_bench.py

all: lint test tpu-smoke blocking-smoke approx-smoke serve-smoke chaos-smoke wire-smoke thread-smoke trace-smoke warmup-smoke drift-smoke perf-smoke tf-smoke scale-smoke fleet-smoke num-smoke bench
