"""Device-native blocking (splink_tpu/blocking_device.py).

The host join in blocking.py is the parity ORACLE: on every supported rule
shape the device tier's pair set must be bit-equal AS A SET — across
exact/multi-column/sequential rules, null keys, asymmetric keys (dedupe
name-swap and link tables), duplicate uids, residual predicates, uneven
chunk boundaries and budget-capped runs. Plus: the serving bucket CSR from
the device kernel is bit-equal to the host construction, steady-state
emission never recompiles, int32 pair indices hold on both tiers (spill
included), and the new audit registrations are falsifiable (a broken twin
trips TA-DTYPE / SA-COLL).
"""

import warnings

import numpy as np
import pandas as pd
import pytest

from splink_tpu.blocking import block_using_rules
from splink_tpu.blocking_device import (
    build_bucket_csr,
    build_device_plan,
    iter_device_pairs,
)
from splink_tpu.data import concat_tables, encode_table
from splink_tpu.settings import complete_settings_dict


def _settings(rules, link_type="dedupe_only", **extra):
    s = {
        "link_type": link_type,
        "comparison_columns": [
            {"col_name": "first_name"},
            {"col_name": "surname"},
            {"col_name": "amount", "data_type": "numeric"},
        ],
        "blocking_rules": list(rules),
    }
    s.update(extra)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return complete_settings_dict(s)


# names deliberately OVERLAP across first_name/surname so asymmetric
# (name-swap) joins produce pairs
_NAMES = ["john", "mary", "jones", "smith", None, "lee", "ann"]


def _df(n, seed, dup_uids=False):
    r = np.random.default_rng(seed)
    uid = np.arange(n) // 2 if dup_uids else np.arange(n)
    return pd.DataFrame(
        {
            "unique_id": uid,
            "first_name": r.choice(_NAMES, n),
            "surname": r.choice(_NAMES, n),
            "amount": r.choice([1.0, 2.5, 3.0, np.nan], n),
        }
    )


def _block_both(settings, table, n_left=None, chunk=None):
    """(host_pairs, device_pairs) as sets; asserts the device tier actually
    ran (plan not rejected) unless the caller expects fallback."""
    sh = dict(settings)
    sh["device_blocking"] = "off"
    sd = dict(settings)
    sd["device_blocking"] = "on"
    if chunk:
        sd["blocking_chunk_pairs"] = chunk
    ph = block_using_rules(sh, table, n_left)
    pdv = block_using_rules(sd, table, n_left)
    host = set(zip(ph.idx_l.tolist(), ph.idx_r.tolist()))
    dev = set(zip(pdv.idx_l.tolist(), pdv.idx_r.tolist()))
    return host, dev, ph, pdv


DEDUPE_RULESETS = [
    ["l.first_name = r.first_name"],
    ["l.first_name = r.first_name and l.surname = r.surname"],
    # sequential rules: rule 2 excludes every rule-1 pair (null-safe NOT)
    ["l.first_name = r.first_name", "l.surname = r.surname"],
    # asymmetric name-swap key over one table
    ["l.first_name = r.surname"],
    # asym + symmetric key in one rule, after a plain rule
    ["l.surname = r.surname", "l.first_name = r.surname and l.amount = r.amount"],
    # derived-key expression
    ["substr(l.surname,1,2) = substr(r.surname,1,2)"],
    # residual predicates (compiled to device masks)
    ["l.first_name = r.first_name and l.amount + 1 > r.amount"],
    ["l.surname = r.surname and l.amount <= r.amount", "l.first_name = r.first_name"],
]


@pytest.mark.parametrize("chunk", [None, 7])
@pytest.mark.parametrize("rules", DEDUPE_RULESETS)
def test_device_parity_dedupe(rules, chunk):
    s = _settings(rules)
    t = encode_table(_df(120, 3), s)
    assert build_device_plan(s, t) is not None, "plan unexpectedly rejected"
    host, dev, _, _ = _block_both(s, t, chunk=chunk)
    assert dev == host
    assert host, f"degenerate fixture: no pairs for {rules}"


@pytest.mark.parametrize("chunk", [None, 13])
@pytest.mark.parametrize(
    "rules",
    [
        ["l.first_name = r.first_name"],
        ["l.first_name = r.surname"],  # asymmetric link key
        ["l.first_name = r.first_name", "l.surname = r.surname"],
    ],
)
def test_device_parity_link_only(rules, chunk):
    s = _settings(rules, link_type="link_only")
    t = concat_tables(_df(70, 5), _df(90, 6), s)
    host, dev, _, _ = _block_both(s, t, n_left=70, chunk=chunk)
    assert dev == host
    assert host


@pytest.mark.parametrize(
    "rules",
    [
        ["l.first_name = r.first_name", "l.surname = r.surname"],
        ["l.first_name = r.surname"],
    ],
)
def test_device_parity_link_and_dedupe(rules):
    s = _settings(rules, link_type="link_and_dedupe")
    t = concat_tables(_df(60, 7), _df(50, 8), s)
    host, dev, _, _ = _block_both(s, t, n_left=60, chunk=11)
    assert dev == host
    assert host


@pytest.mark.parametrize("link_type", ["dedupe_only", "link_and_dedupe"])
def test_device_parity_duplicate_uids(link_type):
    """Duplicate ordering keys: the strict l.key < r.key ordering drops
    equal-key pairs — the device uid mask must reproduce it exactly."""
    rules = ["l.first_name = r.first_name", "l.first_name = r.surname"]
    s = _settings(rules, link_type=link_type)
    if link_type == "dedupe_only":
        t = encode_table(_df(100, 9, dup_uids=True), s)
        n_left = None
    else:
        t = concat_tables(
            _df(50, 10, dup_uids=True), _df(60, 11, dup_uids=True), s
        )
        n_left = 50
    host, dev, _, _ = _block_both(s, t, n_left=n_left, chunk=17)
    assert dev == host
    assert host


def test_device_parity_null_only_rule():
    """A rule whose key is null on every row joins nothing, on both tiers."""
    s = _settings(["l.first_name = r.first_name"])
    df = _df(30, 12)
    df["first_name"] = None
    t = encode_table(df, s)
    host, dev, _, _ = _block_both(s, t)
    assert host == dev == set()


def test_budget_capped_run_parity_and_chunk_shapes():
    """An explicit pair budget streams fixed-shape chunks: every emitted
    chunk respects the cap, uneven tails included, and the union equals
    the host set."""
    s = _settings(
        ["l.first_name = r.first_name", "l.surname = r.surname"],
        device_blocking="on",
    )
    t = encode_table(_df(300, 13), s)
    plan = build_device_plan(s, t)
    assert plan is not None and plan.n_candidates > 64
    budget = 64
    chunks = list(iter_device_pairs(plan, budget))
    assert chunks
    for _r, i, j in chunks:
        assert len(i) == len(j) <= budget
    got = {
        (int(a), int(b)) for _r, i, j in chunks for a, b in zip(i, j)
    }
    sh = dict(s)
    sh["device_blocking"] = "off"
    ph = block_using_rules(sh, t)
    assert got == set(zip(ph.idx_l.tolist(), ph.idx_r.tolist()))


def test_host_chunk_iterators_bound_monster_groups():
    """The per-chunk cap holds for ANY group shape: a single a-row (or an
    r-side) wider than the cap splits its contiguous range, so no chunk —
    and no expansion intermediate — ever exceeds ~cap pairs."""
    from splink_tpu.blocking import (
        _cross_join,
        _iter_cross_join_chunks,
        _iter_self_join_chunks,
        _self_join,
    )

    cap = 50
    codes = np.zeros(200, np.int64)  # ONE giant group: 19900 pairs
    chunks = list(_iter_self_join_chunks(codes, None, cap))
    assert len(chunks) > 1
    assert all(len(i) <= cap for i, _ in chunks)
    got = {(a, b) for i, j in chunks for a, b in zip(i.tolist(), j.tolist())}
    fi, fj = _self_join(codes)
    assert got == set(zip(fi.tolist(), fj.tolist()))

    codes = np.zeros(203, np.int64)
    left = np.arange(3, dtype=np.int64)
    right = np.arange(3, 203, dtype=np.int64)  # r-side 200 >> cap
    chunks = list(_iter_cross_join_chunks(codes, left, right, None, cap))
    assert all(len(i) <= cap for i, _ in chunks)
    got = {(a, b) for i, j in chunks for a, b in zip(i.tolist(), j.tolist())}
    fi, fj = _cross_join(codes, left, right)
    assert got == set(zip(fi.tolist(), fj.tolist()))


def test_mesh_emission_parity():
    """The sharded emission driver (positions sharded over the virtual
    8-device mesh, host compacting per shard) yields the same pair set as
    the host oracle."""
    from splink_tpu.parallel.mesh import make_mesh

    s = _settings(
        ["l.first_name = r.first_name", "l.surname = r.surname"],
    )
    t = encode_table(_df(150, 23), s)
    plan = build_device_plan(s, t)
    assert plan is not None
    mesh = make_mesh(8)
    got = {
        (int(a), int(b))
        for _r, i, j in iter_device_pairs(plan, 256, mesh=mesh)
        for a, b in zip(i, j)
    }
    sh = dict(s)
    sh["device_blocking"] = "off"
    ph = block_using_rules(sh, t)
    assert got == set(zip(ph.idx_l.tolist(), ph.idx_r.tolist()))


def test_zero_steady_state_recompiles():
    """After the first emission warms the per-rule kernels, re-driving the
    SAME plan — uneven tail chunks and all — compiles nothing."""
    from splink_tpu.obs.metrics import compile_requests, install_compile_monitor

    install_compile_monitor()
    s = _settings(["l.first_name = r.first_name", "l.surname = r.surname"])
    t = encode_table(_df(250, 14), s)
    plan = build_device_plan(s, t)
    assert plan is not None
    first = [c for c in iter_device_pairs(plan, 128)]
    c0 = compile_requests()
    second = [c for c in iter_device_pairs(plan, 128)]
    c1 = compile_requests()
    assert c1 == c0, f"{c1 - c0} steady-state recompiles"
    flat = lambda cs: [(r, i.tolist(), j.tolist()) for r, i, j in cs]  # noqa: E731
    assert flat(first) == flat(second)


def test_pair_index_int32_both_tiers(tmp_path):
    """Satellite: PairIndex emits int32 indices when n_rows < 2^31 on BOTH
    tiers, spill path included (the memmap inherits the narrow dtype, so
    spill files halve too)."""
    s = _settings(["l.first_name = r.first_name"])
    t = encode_table(_df(80, 15), s)
    for mode in ("off", "on"):
        cfg = dict(s)
        cfg["device_blocking"] = mode
        pairs = block_using_rules(cfg, t)
        assert pairs.idx_l.dtype == np.int32, mode
        assert pairs.idx_r.dtype == np.int32, mode
        cfg_spill = dict(cfg)
        cfg_spill["spill_dir"] = str(tmp_path / f"spill_{mode}")
        spilled = block_using_rules(cfg_spill, t)
        assert spilled.idx_l.dtype == np.int32, mode
        assert spilled.spill_tmp is not None
        assert set(zip(spilled.idx_l.tolist(), spilled.idx_r.tolist())) == set(
            zip(pairs.idx_l.tolist(), pairs.idx_r.tolist())
        )


def test_host_chunked_emission_matches_unchunked():
    """Satellite: the host join consumes per-chunk expansion intermediates
    under blocking_chunk_pairs — the emitted pair index is bit-identical
    to the unchunked run (same enumeration order, not just same set)."""
    s = _settings(
        ["l.first_name = r.first_name", "l.first_name = r.surname"],
        device_blocking="off",
    )
    t = encode_table(_df(150, 16), s)
    base = block_using_rules(s, t)
    for cap in (5, 64, 1001):
        cfg = dict(s)
        cfg["blocking_chunk_pairs"] = cap
        got = block_using_rules(cfg, t)
        assert np.array_equal(got.idx_l, base.idx_l), cap
        assert np.array_equal(got.idx_r, base.idx_r), cap


def test_pair_consumer_chunks_cover_stream():
    """The overlap consumer sees every device chunk, in order, with the
    sink's dtype."""
    s = _settings(["l.first_name = r.first_name"], device_blocking="on",
                  blocking_chunk_pairs=64)
    t = encode_table(_df(200, 17), s)
    seen = []
    pairs = block_using_rules(
        s, t, pair_consumer=lambda i, j: seen.append((i.copy(), j.copy()))
    )
    assert seen and all(i.dtype == np.int32 for i, _ in seen)
    got_l = np.concatenate([i for i, _ in seen])
    got_r = np.concatenate([j for _, j in seen])
    assert np.array_equal(got_l, pairs.idx_l)
    assert np.array_equal(got_r, pairs.idx_r)


def test_unsupported_shapes_fall_back():
    """Cartesian rules and monster groups reject the device plan; the host
    path serves them (block_using_rules still answers)."""
    # a rule with no equality condition anywhere in the list
    s = _settings(["l.amount < r.amount"])
    t = encode_table(_df(25, 18), s)
    assert build_device_plan(s, t) is None
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        host, dev, _, _ = _block_both(s, t)
    assert dev == host


def test_monster_group_falls_back(monkeypatch):
    import splink_tpu.pairgen as pairgen

    monkeypatch.setattr(pairgen, "MAX_UNITS_PER_GROUP", 2)
    s = _settings(["l.first_name = r.first_name"])
    df = _df(120, 19)
    df["first_name"] = "same"  # one giant group
    t = encode_table(df, s)
    assert build_device_plan(s, t, chunk=4) is None


def test_auto_gate_uses_host_below_threshold(monkeypatch):
    """mode='auto' must not pay the jit warmup on a job whose estimated
    pair bound is tiny — device_block_rules returns None untouched."""
    from splink_tpu import blocking_device

    s = _settings(["l.first_name = r.first_name"])
    t = encode_table(_df(40, 20), s)

    def boom(*a, **k):  # the plan build must never run
        raise AssertionError("plan built for a tiny auto-mode job")

    monkeypatch.setattr(blocking_device, "build_device_plan", boom)
    assert (
        blocking_device.device_block_rules(s, t, None, None, None, "auto")
        is None
    )


# ----------------------------------------------------------------------
# Sharded spill emission (the billion-row write path)
# ----------------------------------------------------------------------


def _emit_to_store(plan, tmp_path, name, n_shards, batch=128, mesh=None):
    from splink_tpu.spill import PairSpillStore

    store = PairSpillStore.attach(str(tmp_path / name), np.int32, {})
    from splink_tpu.blocking_device import emit_pairs_sharded

    with store:
        emit_pairs_sharded(plan, store, batch, n_shards=n_shards, mesh=mesh)
    store.finalize()
    pi = store.as_pair_index()
    return set(zip(pi.idx_l.tolist(), pi.idx_r.tolist()))


@pytest.mark.parametrize("rules", DEDUPE_RULESETS)
def test_sharded_emission_pair_set_parity(rules, tmp_path):
    """ACCEPTANCE: the sharded spill emission's pair set exactly equals
    the single-shard device tier's (and the host oracle's) on every rule
    shape — shards partition units, never pairs."""
    s = _settings(rules)
    t = encode_table(_df(120, 3), s)
    plan = build_device_plan(s, t)
    assert plan is not None
    single = {
        (int(a), int(b))
        for _r, i, j in iter_device_pairs(plan, 128)
        for a, b in zip(i, j)
    }
    sharded = _emit_to_store(plan, tmp_path, "sharded", n_shards=3)
    assert sharded == single
    sh = dict(s)
    sh["device_blocking"] = "off"
    ph = block_using_rules(sh, t)
    assert sharded == set(zip(ph.idx_l.tolist(), ph.idx_r.tolist()))
    assert sharded, f"degenerate fixture: no pairs for {rules}"


@pytest.mark.parametrize(
    "rules",
    [
        ["l.first_name = r.first_name"],
        ["l.first_name = r.surname"],
        ["l.first_name = r.first_name", "l.surname = r.surname"],
    ],
)
def test_sharded_emission_parity_link_only(rules, tmp_path):
    s = _settings(rules, link_type="link_only")
    t = concat_tables(_df(70, 5), _df(90, 6), s)
    plan = build_device_plan(s, t, n_left=70)
    assert plan is not None
    sharded = _emit_to_store(plan, tmp_path, "link", n_shards=4, batch=64)
    sh = dict(s)
    sh["device_blocking"] = "off"
    ph = block_using_rules(sh, t, 70)
    assert sharded == set(zip(ph.idx_l.tolist(), ph.idx_r.tolist()))
    assert sharded


def test_sharded_emission_mesh_parity(tmp_path):
    """Shard scheduling composes with the mesh decode: units partition
    across shards AND each chunk's positions shard over the virtual
    8-device mesh (block_pair_decode_sharded)."""
    from splink_tpu.parallel.mesh import make_mesh

    s = _settings(
        ["l.first_name = r.first_name", "l.surname = r.surname"]
    )
    t = encode_table(_df(150, 23), s)
    plan = build_device_plan(s, t)
    sharded = _emit_to_store(
        plan, tmp_path, "mesh", n_shards=4, batch=256, mesh=make_mesh(8)
    )
    sh = dict(s)
    sh["device_blocking"] = "off"
    ph = block_using_rules(sh, t)
    assert sharded == set(zip(ph.idx_l.tolist(), ph.idx_r.tolist()))


def test_sharded_emission_zero_steady_state_recompiles(tmp_path):
    """ACCEPTANCE: across chunk shapes, shard switches AND spill segments,
    a second drive of the same plan compiles nothing — shard metadata
    rows are floored to the rule-wide kpad so every (rule, shard, seq)
    shares one specialisation."""
    from splink_tpu.blocking_device import emit_pairs_sharded
    from splink_tpu.obs.metrics import (
        compile_requests,
        install_compile_monitor,
    )
    from splink_tpu.spill import PairSpillStore

    install_compile_monitor()
    s = _settings(["l.first_name = r.first_name", "l.surname = r.surname"])
    t = encode_table(_df(250, 14), s)
    plan = build_device_plan(s, t)
    store1 = PairSpillStore.attach(str(tmp_path / "one"), np.int32, {})
    with store1:
        emit_pairs_sharded(plan, store1, 128, n_shards=3)
    store1.finalize()
    c0 = compile_requests()
    store2 = PairSpillStore.attach(str(tmp_path / "two"), np.int32, {})
    with store2:
        emit_pairs_sharded(plan, store2, 128, n_shards=3)
    store2.finalize()
    c1 = compile_requests()
    assert c1 == c0, f"{c1 - c0} steady-state recompiles across segments"
    a = store1.as_pair_index()
    b = store2.as_pair_index()
    assert np.array_equal(a.idx_l, b.idx_l)
    assert np.array_equal(a.idx_r, b.idx_r)


def test_spill_block_rules_settings_shapes(tmp_path):
    """emit_shard_chunks resolves the shard count; the host-only rule
    shapes fall back (None) instead of half-building a store."""
    from splink_tpu.blocking_device import spill_block_rules

    s = _settings(
        ["l.first_name = r.first_name"], emit_shard_chunks=2,
        blocking_chunk_pairs=256,
    )
    t = encode_table(_df(120, 19), s)
    pi = spill_block_rules(s, t, None, str(tmp_path / "ok"))
    assert pi is not None
    import json as _json
    import os as _os

    m = _json.load(
        open(_os.path.join(str(tmp_path / "ok"), "pairs", "pair_manifest.json"))
    )
    assert m["meta"]["n_shards"] == 2
    assert {seg["shard"] for seg in m["segments"]} <= {0, 1}
    # cartesian rule: no device plan, caller falls back
    s2 = _settings(["l.amount < r.amount"])
    t2 = encode_table(
        _df(25, 18).assign(amount=np.arange(25.0)), s2
    )
    assert spill_block_rules(s2, t2, None, str(tmp_path / "no")) is None


# ----------------------------------------------------------------------
# Serving bucket CSR
# ----------------------------------------------------------------------


def test_bucket_csr_matches_host_construction():
    from splink_tpu.blocking import _key_codes, _sort_groups

    s = _settings(["l.first_name = r.first_name"])
    t = encode_table(_df(173, 21), s)  # non-power-of-two row count
    codes = _key_codes(t, ["first_name"])
    csr = build_bucket_csr(codes)
    assert csr is not None
    rows_sorted, starts, sizes, row_bucket = csr
    rows = np.flatnonzero(codes >= 0).astype(np.int32)
    h_rows, _, h_starts, h_sizes = _sort_groups(codes, rows)
    assert np.array_equal(rows_sorted, h_rows)
    assert np.array_equal(starts, h_starts.astype(np.int32))
    assert np.array_equal(sizes, h_sizes.astype(np.int32))
    h_bucket = np.full(t.n_rows, -1, np.int32)
    h_bucket[h_rows] = np.repeat(
        np.arange(len(h_sizes), dtype=np.int32), h_sizes
    )
    assert np.array_equal(row_bucket, h_bucket)


def test_serve_rule_device_and_host_builds_agree():
    from splink_tpu.serve.index import _build_serve_rule

    s = _settings(["l.first_name = r.first_name"])
    t = encode_table(_df(140, 22), s)
    dev = _build_serve_rule(t, "l.first_name = r.first_name", device=True)
    host = _build_serve_rule(t, "l.first_name = r.first_name", device=False)
    assert np.array_equal(dev.rows_sorted, host.rows_sorted)
    assert np.array_equal(dev.starts, host.starts)
    assert np.array_equal(dev.sizes, host.sizes)
    assert np.array_equal(dev.row_bucket, host.row_bucket)
    assert dev.bucket_of == host.bucket_of


# ----------------------------------------------------------------------
# Settings keys
# ----------------------------------------------------------------------


def test_blocking_settings_keys_complete_and_validate():
    from splink_tpu.validate import ValidationError, validate_settings

    s = _settings(["l.first_name = r.first_name"])
    assert s["device_blocking"] == "auto"
    assert s["blocking_chunk_pairs"] == 4194304
    for bad in (
        {"device_blocking": "sometimes"},
        {"device_blocking": 1},
        {"blocking_chunk_pairs": 0},
        {"blocking_chunk_pairs": "big"},
    ):
        with pytest.raises(ValidationError):
            validate_settings(_settings(["l.first_name = r.first_name"], **bad))
    validate_settings(
        _settings(
            ["l.first_name = r.first_name"],
            device_blocking="on",
            blocking_chunk_pairs=1024,
        )
    )


# ----------------------------------------------------------------------
# Audit registrations: clean AND falsifiable
# ----------------------------------------------------------------------


def test_blocking_kernels_registered_and_clean():
    from splink_tpu.analysis.trace_audit import run_audit

    findings, audited = run_audit(
        ["block_segment_sort", "block_bucket_csr", "block_pair_emit"]
    )
    assert audited == 3
    assert not findings, "\n".join(f.format() for f in findings)


def test_blocking_shard_kernel_registered_and_clean():
    from splink_tpu.analysis.shard_audit import run_shard_audit

    findings, audited = run_shard_audit(["block_pair_decode_sharded"])
    assert audited == 1
    assert not findings, "\n".join(f.format() for f in findings)


def test_bad_emit_twin_trips_ta_dtype():
    """An unpinned arange in the emission compaction goes int64 under the
    forced-x64 trace — the dtype leak TA-DTYPE exists to catch."""
    from splink_tpu.analysis.trace_audit import KernelSpec, audit_kernel

    def build():
        import jax.numpy as jnp

        def bad(keep, i):
            slots = jnp.arange(keep.shape[0])  # unpinned: int64 under x64
            kcum = jnp.cumsum(keep.astype(jnp.int32), dtype=jnp.int32)
            dest = jnp.where(keep, kcum - 1, keep.shape[0])
            return jnp.zeros(keep.shape[0], jnp.int32).at[dest].set(
                i + slots.astype(jnp.int32) * 0, mode="drop"
            )

        keep = jnp.zeros(16, bool)
        i = jnp.zeros(16, jnp.int32)
        return bad, (keep, i), {}

    findings = audit_kernel(KernelSpec(name="bad_block_emit_dtype", build=build))
    assert any(f.rule == "TA-DTYPE" for f in findings), [
        f.format() for f in findings
    ]


def test_spill_digest_kernels_registered_and_clean():
    from splink_tpu.analysis.shard_audit import run_shard_audit
    from splink_tpu.analysis.trace_audit import run_audit

    findings, audited = run_audit(
        ["spill_chunk_digest", "spill_chunk_digest_compact"]
    )
    assert audited == 2
    assert not findings, "\n".join(f.format() for f in findings)
    findings, audited = run_shard_audit(["spill_chunk_digest_sharded"])
    assert audited == 1
    assert not findings, "\n".join(f.format() for f in findings)


def test_bad_digest_shard_twin_trips_sa_coll():
    """FALSIFIABILITY (acceptance): the digest's cross-shard sum is its
    ONE declared collective — a twin registered WITHOUT the declaration
    must trip SA-COLL, proving the audit would catch a kernel that grew
    undeclared cross-device traffic."""
    from splink_tpu.analysis.shard_audit import (
        register_shard_kernel,
        run_shard_audit,
    )

    registry: dict = {}

    @register_shard_kernel(
        "bad_spill_digest_sharded", n_pairs=64, registry=registry
    )  # no allow_collectives: the psum is undeclared
    def _build():
        import jax

        from splink_tpu.analysis.shard_audit import audit_mesh
        from splink_tpu.blocking_device import make_chunk_digest_fn
        from splink_tpu.parallel.mesh import pair_sharding

        mesh = audit_mesh()
        fn = make_chunk_digest_fn(mesh)
        shard = pair_sharding(mesh)
        i = jax.device_put(np.zeros(64, np.int32), shard)
        j = jax.device_put(np.zeros(64, np.int32), shard)
        keep = jax.device_put(np.ones(64, bool), shard)
        return fn, (i, j, keep), {}

    findings, audited = run_shard_audit(registry=registry, baselines={})
    assert audited == 1
    assert any(f.rule == "SA-COLL" for f in findings), [
        f.format() for f in findings
    ]


def test_doctored_digest_mem_baseline_trips_pa_mem():
    """FALSIFIABILITY (acceptance): a perf baseline claiming the digest
    executable used to move fewer bytes makes PA-MEM fire — the measured
    layer would catch a memory regression in the new kernel."""
    import copy

    from splink_tpu.analysis import perf_audit as pa

    kernels = {}
    for cell in pa.perf_plan(["spill_chunk_digest"]):
        kernels.setdefault(cell.kernel, {})[cell.label] = pa.measure_cell(
            cell, best_of=2
        )
    base = {"tiers": {pa.current_tier(): {"kernels": kernels}}}
    doctored = copy.deepcopy(base)
    cell0 = doctored["tiers"][pa.current_tier()]["kernels"][
        "spill_chunk_digest"
    ]
    label = next(iter(cell0))
    cell0[label]["argument_bytes"] = cell0[label]["argument_bytes"] / 10.0
    findings, _ = pa.run_perf_audit(
        ["spill_chunk_digest"], doctored, best_of=2, remeasure=2
    )
    mem = [f for f in findings if f.rule == "PA-MEM"]
    assert mem and "argument_bytes" in mem[0].message
    # the honest measurement stays clean
    findings, _ = pa.run_perf_audit(
        ["spill_chunk_digest"], base, best_of=2, remeasure=2
    )
    assert not [f for f in findings if f.rule == "PA-MEM"]


def test_bad_shard_twin_trips_sa_coll():
    """Sorting INSIDE the sharded decode — the unpartitionable op the
    design keeps out of the mesh kernel — forces GSPMD to gather the
    sharded position axis: SA-COLL fires."""
    from splink_tpu.analysis.shard_audit import (
        register_shard_kernel,
        run_shard_audit,
    )

    registry: dict = {}

    @register_shard_kernel(
        "bad_block_sort_sharded", n_pairs=64, registry=registry
    )
    def _build():
        import jax

        from splink_tpu.analysis.shard_audit import audit_mesh
        from splink_tpu.parallel.mesh import pair_sharding

        mesh = audit_mesh()
        codes = jax.device_put(
            np.zeros(64, np.int32), pair_sharding(mesh)
        )

        def bad(codes):
            return jax.lax.sort((codes,), num_keys=1)[0]

        return bad, (codes,), {}

    findings, audited = run_shard_audit(registry=registry, baselines={})
    assert audited == 1
    assert any(f.rule == "SA-COLL" for f in findings), [
        f.format() for f in findings
    ]
