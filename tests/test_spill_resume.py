"""Kill-and-resume of the spill-backed offline build (ISSUE 15 satellite).

A REAL SIGKILL (fault-plan kind=kill: no atexit, no finally blocks) lands
mid-segment in the emission driver — after the segment's bytes hit disk,
before its manifest commit — and, separately, mid-chunk in the
out-of-core packed-matrix writer. The relaunched build must resume from
the last committed state and produce an index whose CONTENT FINGERPRINT
is bit-identical to an uninterrupted run's, on both mesh widths (the
explicit single-device mesh and the virtual 8-device one). Anything
weaker would let a resume that re-emits, drops or reorders a segment
hide behind EM's tolerance of pair order.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "spill_build_worker.py")


def _run_worker(tmp_path, tag, mesh_n, faults=None, build=None):
    out = str(tmp_path / f"{tag}.json")
    if build is None:
        build = str(tmp_path / f"build_{tag}")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("SPLINK_TPU_FAULTS", None)
    if faults:
        env["SPLINK_TPU_FAULTS"] = faults
    proc = subprocess.run(
        [sys.executable, WORKER, out, build, str(mesh_n)],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=600,
    )
    return proc, out, build


@pytest.mark.parametrize(
    "mesh_n,fault",
    [
        # kill between a spill segment's byte append and its manifest
        # commit — the widest window — on the single-device mesh
        (1, "emit_segment@seq=2:kind=kill"),
        # kill between an out-of-core packed chunk's append and its
        # watermark commit, with the emission mesh-sharded 8 wide
        (8, "build_chunk@chunk=1:kind=kill"),
    ],
)
def test_killed_build_resumes_bit_identical(tmp_path, mesh_n, fault):
    # uninterrupted oracle (its own build dir)
    ref, ref_out, _ = _run_worker(tmp_path, f"ref-{mesh_n}", mesh_n)
    assert ref.returncode == 0, ref.stderr[-2000:]
    want = json.load(open(ref_out))

    # killed run: a REAL SIGKILL mid-commit-window
    killed, _, build = _run_worker(
        tmp_path, f"killed-{mesh_n}", mesh_n, faults=fault
    )
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, killed.stdout[-1000:], killed.stderr[-1000:],
    )
    # something durable was committed before death (a resume has state)
    assert os.path.isdir(build)

    # resumed run over the SAME build dir, no faults
    resumed, res_out, _ = _run_worker(
        tmp_path, f"resumed-{mesh_n}", mesh_n, build=build
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    got = json.load(open(res_out))
    assert got["fingerprint"] == want["fingerprint"], (
        "resumed build fingerprint diverged from the uninterrupted run"
    )
    assert got["n_pairs"] == want["n_pairs"]
    log = resumed.stderr + resumed.stdout
    assert "resumed" in log.lower() or got["segments"] > 0
