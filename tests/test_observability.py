"""Observability layer: intuition report, charts, block diagnostics.

Parity targets: intuition narrative (/root/reference/splink/intuition.py:32-92),
chart methods + combined HTML (/root/reference/splink/params.py:358-484,
chart_definitions.py:248-277), get_largest_blocks
(/root/reference/splink/comparison_evaluation.py:12-34).
"""

import json

import numpy as np
import pandas as pd
import pytest

from splink_tpu import Splink
from splink_tpu.comparison_evaluation import get_largest_blocks
from splink_tpu.intuition import adjustment_factor_chart, intuition_report


@pytest.fixture
def trained_linker():
    rng = np.random.default_rng(11)
    firsts = np.array(["amelia", "oliver", "isla", "george"])
    n = 120
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "first_name": firsts[rng.integers(0, 4, n)],
            "surname": np.array(["smith", "jones", "taylor"])[rng.integers(0, 3, n)],
            "city": [f"c{i % 3}" for i in range(n)],
        }
    )
    settings = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.city = r.city"],
        "comparison_columns": [
            {"col_name": "first_name", "num_levels": 2, "comparison": {"kind": "exact"}},
            {"col_name": "surname", "num_levels": 2, "comparison": {"kind": "exact"}},
        ],
        "retain_intermediate_calculation_columns": True,
        "retain_matching_columns": True,
        "max_iterations": 5,
    }
    linker = Splink(settings, df=df)
    df_e = linker.get_scored_comparisons(compute_ll=True)
    return linker, df_e


def test_intuition_report_narrative(trained_linker):
    linker, df_e = trained_linker
    row = df_e.iloc[0]
    text = intuition_report(row, linker.params)
    assert "Initial probability of match (prior)" in text
    assert "Comparison of first_name" in text
    assert "Comparison of surname" in text
    assert "Adjustment factor = m/(m + u)" in text
    # the narrative's final probability equals the scored probability
    final = float(text.strip().rsplit("=", 1)[1])
    assert final == pytest.approx(float(row["match_probability"]), abs=1e-4)


def test_intuition_report_requires_intermediates(trained_linker):
    linker, df_e = trained_linker
    row = df_e.iloc[0].drop(labels=["prob_gamma_first_name_match"])
    with pytest.raises(KeyError, match="retain_intermediate_calculation_columns"):
        intuition_report(row, linker.params)


def test_adjustment_factor_chart(trained_linker):
    linker, df_e = trained_linker
    spec = adjustment_factor_chart(df_e.iloc[0], linker.params)
    rows = spec["data"]["values"]
    assert {r["col_name"] for r in rows} == {"first_name", "surname"}
    for r in rows:
        assert abs(r["normalised"]) <= 0.5
        assert r["value"] == pytest.approx(r["normalised"] + 0.5)


def test_params_charts_and_html(tmp_path, trained_linker):
    linker, _ = trained_linker
    p = linker.params
    for method in (
        "pi_iteration_chart",
        "lambda_iteration_chart",
        "ll_iteration_chart",
        "probability_distribution_chart",
        "adjustment_factor_chart",
    ):
        spec = getattr(p, method)()
        assert isinstance(spec, dict) and "data" in spec
        json.dumps(spec)  # must be JSON-serialisable

    out = tmp_path / "charts.html"
    p.all_charts_write_html_file(str(out))
    html = out.read_text()
    assert "vega" in html.lower()
    with pytest.raises(ValueError):  # overwrite guard
        p.all_charts_write_html_file(str(out))
    p.all_charts_write_html_file(str(out), overwrite=True)


def test_get_largest_blocks():
    df = pd.DataFrame(
        {
            "first_name": ["a", "a", "a", "b", "b", None, "c"],
            "surname": ["x"] * 7,
        }
    )
    top = get_largest_blocks("l.first_name = r.first_name", df, limit=2)
    assert top.iloc[0]["first_name"] == "a"
    assert top.iloc[0]["count"] == 3
    assert len(top) == 2

    two_col = get_largest_blocks(
        "l.first_name = r.first_name and l.surname = r.surname", df
    )
    assert list(two_col.columns) == ["first_name", "surname", "count"]

    with pytest.raises(ValueError):
        get_largest_blocks("something invalid", df)


def test_intuition_report_with_case_sql_column():
    """The per-row intuition narrative and waterfall work when a comparison
    is a compiled hand-written CASE expression (kind case_sql)."""
    import numpy as np
    import pandas as pd

    from splink_tpu import Splink
    from splink_tpu.intuition import intuition_report

    rng = np.random.default_rng(2)
    n = 120
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["ann", "bob", "cat", "dan"], n),
            "city": rng.choice(["x", "y"], n),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.city = r.city"],
        "comparison_columns": [
            {
                "col_name": "name",
                "num_levels": 3,
                "case_expression": """case
                    when name_l is null or name_r is null then -1
                    when name_l = name_r then 2
                    when jaro_winkler_sim(name_l, name_r) > 0.7 then 1
                    else 0 end""",
            }
        ],
        "retain_intermediate_calculation_columns": True,
        "max_iterations": 4,
    }
    linker = Splink(s, df=df)
    df_e = linker.get_scored_comparisons()
    row = df_e.iloc[0]
    report = intuition_report(row, linker.params)
    assert "Initial probability of match" in report
    assert "gamma_name" in report


def test_stage_timings_recorded_through_pipeline():
    """StageTimer records encode/blocking/gammas/em wall times during a
    linker run — the structured-profiling analogue of the reference logging
    each stage's generated SQL."""
    import numpy as np
    import pandas as pd

    from splink_tpu import Splink
    from splink_tpu.utils.profiling import reset_timings, stage_timings

    rng = np.random.default_rng(4)
    n = 100
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["a", "b", "c"], n),
            "city": rng.choice(["x", "y"], n),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.city = r.city"],
        "comparison_columns": [
            {"col_name": "name", "comparison": {"kind": "exact"}}
        ],
        "max_iterations": 3,
    }
    reset_timings()
    Splink(s, df=df).get_scored_comparisons()
    t = stage_timings()
    for stage in ("encode", "blocking", "gammas", "em"):
        assert stage in t and t[stage][0] >= 0, (stage, t.keys())


def test_stage_timer_trace_hook_writes_profile(tmp_path):
    """StageTimer(trace_dir=...) wraps the stage in a jax.profiler.trace and
    leaves a TensorBoard-format profile artifact behind — the observability
    hook is exercised, not just wired."""
    import os

    import jax.numpy as jnp

    from splink_tpu.utils.profiling import StageTimer, stage_timings

    trace_dir = str(tmp_path / "trace")
    with StageTimer("traced_stage", trace_dir=trace_dir):
        jnp.dot(jnp.ones((32, 32)), jnp.ones((32, 32))).block_until_ready()

    produced = [
        os.path.join(root, f)
        for root, _, files in os.walk(trace_dir)
        for f in files
    ]
    assert any("xplane" in f or f.endswith(".json.gz") for f in produced), (
        f"no profile artifact under {trace_dir}: {produced}"
    )
    assert "traced_stage" in stage_timings()


def test_spill_sweep_reclaims_recycled_pid_dirs(tmp_path):
    """A stale splink_pairs_* dir whose recorded pid was recycled by an
    unrelated live process is reclaimed (the start-time token detects the
    reuse); a dir owned by a genuinely live process is kept."""
    import os

    from splink_tpu.blocking import (
        _owner_token,
        _proc_start_time,
        _sweep_stale_spill_dirs,
    )

    spill = tmp_path / "spill"
    spill.mkdir()

    # pid 1 is always alive; recording a WRONG start time simulates a dir
    # written by a dead process whose pid was later recycled
    recycled = spill / "splink_pairs_recycled"
    recycled.mkdir()
    live_start = _proc_start_time(1)
    assert live_start is not None  # linux /proc available in CI
    (recycled / "owner.pid").write_text(f"1 {live_start + 12345}")

    # same pid with the CORRECT start time: a live owner, must be kept
    kept = spill / "splink_pairs_live"
    kept.mkdir()
    (kept / "owner.pid").write_text(_owner_token(1))

    # dead pid: reclaimed regardless of token format (legacy single-field)
    dead = spill / "splink_pairs_dead"
    dead.mkdir()
    dead_pid = 1
    for cand in range(300000, 400000):
        if not os.path.exists(f"/proc/{cand}"):
            dead_pid = cand
            break
    (dead / "owner.pid").write_text(str(dead_pid))

    _sweep_stale_spill_dirs(str(spill))
    assert not recycled.exists(), "recycled-pid orphan not reclaimed"
    assert kept.exists(), "live owner's dir must not be touched"
    assert not dead.exists(), "dead-pid orphan not reclaimed"


def test_profile_dir_captures_traces(tmp_path):
    """settings["profile_dir"] -> device-heavy stages emit jax profiler
    traces (one flag turns an EM pass into utilisation data)."""
    import os

    import numpy as np
    import pandas as pd

    from splink_tpu import Splink
    from splink_tpu.utils.profiling import set_trace_dir

    rng = np.random.default_rng(0)
    df = pd.DataFrame(
        {
            "unique_id": range(200),
            "name": rng.choice(["ann", "bob", "cat"], 200),
            "dob": rng.choice([f"d{k}" for k in range(10)], 200),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": [{"col_name": "name", "num_levels": 2}],
        "blocking_rules": ["l.dob = r.dob"],
        "max_iterations": 2,
        "profile_dir": str(tmp_path),
    }
    try:
        Splink(s, df=df).get_scored_comparisons()
        found = [
            os.path.join(root, f)
            for root, _dirs, files in os.walk(tmp_path)
            for f in files
        ]
        assert found, "no trace files captured"
    finally:
        set_trace_dir(None)  # process-wide flag: do not leak into other tests


def test_cpu_cache_keyed_by_target_fingerprint(tmp_path, monkeypatch):
    """On the CPU backend the persistent compilation cache is ON (no more
    accelerator-only gate) and its directory is keyed by the host's
    target-feature fingerprint: XLA:CPU executables embed exact machine
    features, so the ``cpu-<fp16>`` subdirectory is what keeps a shared
    cache volume from serving SIGILL-prone foreign code. Completion still
    never auto-fills the settings key."""
    import os

    import jax
    import pandas as pd

    import splink_tpu.linker as linker_mod
    from splink_tpu import Splink
    from splink_tpu.settings import complete_settings_dict
    from splink_tpu.utils.envfp import cpu_target_fingerprint

    if jax.default_backend() != "cpu":
        pytest.skip("CPU-only keying: not exercisable on an accelerator")
    # the conftest-pinned env var must not short-circuit the settings path
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    prev_applied = linker_mod._compilation_cache_applied
    prev_dir = jax.config.jax_compilation_cache_dir
    try:
        linker_mod._compilation_cache_applied = None
        base = tmp_path / "xla"
        linker_mod._enable_compilation_cache(str(base), explicit=False)
        applied = linker_mod._compilation_cache_applied
        expect = os.path.join(
            str(base), f"cpu-{cpu_target_fingerprint()[:16]}"
        )
        assert applied == expect
        assert jax.config.jax_compilation_cache_dir == expect
        # two hosts with different feature sets never share entries: the
        # fingerprint is a pure function of machine + flags
        assert cpu_target_fingerprint() == cpu_target_fingerprint()
        # completion never fills the key (the linker resolves the schema
        # default lazily; a reused dict must not look explicitly set)
        s = complete_settings_dict(
            {
                "link_type": "dedupe_only",
                "comparison_columns": [
                    {"col_name": "name", "num_levels": 2}
                ],
                "blocking_rules": ["l.name = r.name"],
            }
        )
        assert "compilation_cache_dir" not in s
        # first linker wins holds for the fingerprinted path too
        linker_mod._enable_compilation_cache(
            str(tmp_path / "other"), explicit=False
        )
        assert linker_mod._compilation_cache_applied == expect
        # and a default-config linker construction leaves it untouched
        df = pd.DataFrame({"unique_id": [0, 1], "name": ["a", "b"]})
        Splink(s, df=df)
        assert linker_mod._compilation_cache_applied == expect
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        linker_mod._compilation_cache_applied = prev_applied
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()


def test_compilation_cache_dir_applies(tmp_path, monkeypatch):
    """settings["compilation_cache_dir"] -> jax persistent compilation
    cache enabled at that path (process-wide, first linker wins; on the
    CPU backend under the target-fingerprint subdirectory); entries
    actually land once a compile exceeds the time threshold (forced to 0
    here so the CPU tier's sub-second compiles qualify)."""
    import os

    import jax
    import numpy as np
    import pandas as pd

    import splink_tpu.linker as linker_mod
    from splink_tpu import Splink
    from splink_tpu.utils.envfp import cpu_target_fingerprint

    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    expect = str(tmp_path / "xla")
    if jax.default_backend() == "cpu":
        expect = os.path.join(
            expect, f"cpu-{cpu_target_fingerprint()[:16]}"
        )
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_applied = linker_mod._compilation_cache_applied
    prev_min_time = jax.config.jax_persistent_cache_min_compile_time_secs
    prev_min_size = jax.config.jax_persistent_cache_min_entry_size_bytes
    cache = tmp_path / "xla"
    df = pd.DataFrame(
        {
            "unique_id": range(100),
            "name": ["ann", "bob"] * 50,
            "dob": [f"d{k % 7}" for k in range(100)],
        }
    )
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": [{"col_name": "name", "num_levels": 2}],
        "blocking_rules": ["l.dob = r.dob"],
        "max_iterations": 1,
        "compilation_cache_dir": str(cache),
    }
    try:
        linker_mod._compilation_cache_applied = None
        Splink(s, df=df)
        assert jax.config.jax_compilation_cache_dir == expect
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # drop in-process executable caches: earlier tests may have
        # compiled these same shapes, and only a real compile persists.
        # jax also binds its persistent-cache object to the FIRST dir it
        # initialised with (an earlier linker in this process), so reset
        # it to pick up this test's dir
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
        jax.clear_caches()
        Splink(s, df=df).get_scored_comparisons()
        entries = [
            f for _root, _dirs, files in os.walk(cache) for f in files
        ]
        assert entries, "no compiled executables persisted"
        # empty value disables for a fresh process but must NOT clear the
        # already-applied process-wide dir (first linker wins)
        Splink({**s, "compilation_cache_dir": ""}, df=df)
        assert jax.config.jax_compilation_cache_dir == expect
        # a later linker with a DIFFERENT dir must also be ignored
        Splink({**s, "compilation_cache_dir": str(tmp_path / "b")}, df=df)
        assert jax.config.jax_compilation_cache_dir == expect
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min_time
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", prev_min_size
        )
        linker_mod._compilation_cache_applied = prev_applied
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()


# ----------------------------------------------------------------------
# Run-scoped profiling (utils/profiling.py): timings and trace dirs are
# keyed by run id — two linkers in one process no longer interleave
# timings or clobber each other's profile_dir.
# ----------------------------------------------------------------------


def _tiny_df(n=60, seed=0):
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["a", "b", "c"], n),
            "city": rng.choice(["x", "y"], n),
        }
    )


def _tiny_settings(**over):
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {"col_name": "name", "comparison": {"kind": "exact"}}
        ],
        "blocking_rules": ["l.city = r.city"],
        "max_iterations": 2,
    }
    s.update(over)
    return s


def test_timings_scoped_per_linker_run():
    """Two linkers record into separate run scopes; stage_timings() reads
    the CURRENT run and stage_timings(run=...) a specific linker's."""
    from splink_tpu import Splink
    from splink_tpu.utils.profiling import stage_timings

    a = Splink(_tiny_settings(), df=_tiny_df(seed=1))
    a.get_scored_comparisons()
    t_a = stage_timings(run=a.run_id)
    assert "em" in t_a and len(t_a["em"]) == 1

    # constructing linker B opens (and makes current) a FRESH scope
    b = Splink(_tiny_settings(), df=_tiny_df(seed=2))
    assert stage_timings() == {}
    b.get_scored_comparisons()
    assert len(stage_timings(run=b.run_id)["em"]) == 1
    # A's record is untouched by B's run (the old process-global _TIMINGS
    # would have interleaved them)
    assert stage_timings(run=a.run_id) == t_a

    # interleaved construction: A2 built BEFORE B2 runs still records into
    # its own scope when driven afterwards
    a2 = Splink(_tiny_settings(), df=_tiny_df(seed=3))
    b2 = Splink(_tiny_settings(), df=_tiny_df(seed=4))
    b2.get_scored_comparisons()
    a2.get_scored_comparisons()
    assert len(stage_timings(run=a2.run_id)["em"]) == 1
    assert len(stage_timings(run=b2.run_id)["em"]) == 1


def test_later_linker_does_not_clear_earlier_trace_dir(tmp_path):
    """A later linker WITHOUT profile_dir must not disable an earlier
    linker's trace capture (the old process-global _TRACE_DIR did:
    linker.py cleared it unconditionally on every construction)."""
    import os

    from splink_tpu import Splink

    a = Splink(_tiny_settings(profile_dir=str(tmp_path)), df=_tiny_df(seed=5))
    Splink(_tiny_settings(), df=_tiny_df(seed=6))  # no profile_dir
    a.get_scored_comparisons()
    found = [
        os.path.join(root, f)
        for root, _dirs, files in os.walk(tmp_path)
        for f in files
    ]
    assert found, "later linker clobbered the first linker's profile_dir"


def test_stage_timer_does_not_nest_profiler_traces(tmp_path):
    """jax.profiler.trace cannot nest: an inner StageTimer with a trace
    dir must skip tracing while an outer trace is active (and trace again
    once it is released)."""
    from splink_tpu.utils import profiling
    from splink_tpu.utils.profiling import StageTimer

    outer_dir = str(tmp_path / "outer")
    inner_dir = str(tmp_path / "inner")
    with StageTimer("outer", trace_dir=outer_dir) as outer:
        assert outer._trace is not None and profiling._TRACE_ACTIVE
        with StageTimer("inner", trace_dir=inner_dir) as inner:
            assert inner._trace is None  # skipped: a trace is active
        assert profiling._TRACE_ACTIVE  # inner exit didn't release the flag
    assert not profiling._TRACE_ACTIVE
    with StageTimer("after", trace_dir=str(tmp_path / "after")) as after:
        assert after._trace is not None
    assert not profiling._TRACE_ACTIVE


def test_stage_timer_trace_active_exception_safety(tmp_path):
    """_TRACE_ACTIVE is released when the stage body raises, and even when
    the profiler's own __exit__ raises — otherwise no later stage could
    ever trace again."""
    import pytest

    from splink_tpu.utils import profiling
    from splink_tpu.utils.profiling import StageTimer

    with pytest.raises(RuntimeError, match="boom"):
        with StageTimer("failing", trace_dir=str(tmp_path / "t1")):
            raise RuntimeError("boom")
    assert not profiling._TRACE_ACTIVE

    class _ExplodingTrace:
        def __exit__(self, *exc):
            raise OSError("profiler write failed")

    # simulate a profiler whose own __exit__ raises WITHOUT opening a real
    # jax trace (overwriting a live trace object would leak the singleton
    # profiler session into later tests)
    timer = StageTimer("bad_exit")
    with pytest.raises(OSError, match="profiler write failed"):
        with timer:
            profiling._TRACE_ACTIVE = True
            timer._trace = _ExplodingTrace()
    assert not profiling._TRACE_ACTIVE
    # timing was still recorded for the failing stage
    from splink_tpu.utils.profiling import stage_timings

    assert "bad_exit" in stage_timings()
