"""Multi-device semantics on the virtual 8-device CPU mesh: sharded EM and
streamed EM must agree exactly with the single-device in-memory path — the
JAX analogue of the reference running one scenario through both sqlite and
Spark."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from splink_tpu.em import run_em
from splink_tpu.models.fellegi_sunter import FSParams
from splink_tpu.parallel import (
    make_mesh,
    mesh_from_settings,
    run_em_streamed,
    shard_pairs,
)


def _dgp(n=20_000, seed=0):
    rng = np.random.default_rng(seed)
    lam = 0.3
    m = np.array([[0.1, 0.9], [0.2, 0.8], [0.3, 0.7]])
    u = np.array([[0.85, 0.15], [0.7, 0.3], [0.6, 0.4]])
    is_match = rng.random(n) < lam
    G = np.zeros((n, 3), np.int8)
    for c in range(3):
        probs = np.where(is_match[:, None], m[c], u[c])
        G[:, c] = (rng.random(n)[:, None] > probs.cumsum(1)).sum(1)
    init = FSParams(
        lam=jnp.asarray(0.5),
        m=jnp.asarray(np.full((3, 2), 0.5)),
        u=jnp.asarray(np.full((3, 2), 0.5)),
    )
    # symmetric init won't move; use slightly asymmetric
    m0 = np.tile([0.4, 0.6], (3, 1))
    u0 = np.tile([0.6, 0.4], (3, 1))
    init = FSParams(lam=jnp.asarray(0.5), m=jnp.asarray(m0), u=jnp.asarray(u0))
    return G, init


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_em_matches_single_device():
    G, init = _dgp(n=20_000)
    ref = run_em(jnp.asarray(G), init, max_iterations=10, max_levels=2, em_convergence=0.0)

    mesh = make_mesh()
    # deliberately use a size not divisible by 8 to exercise padding
    G_odd = G[:-3]
    ref_odd = run_em(
        jnp.asarray(G_odd), init, max_iterations=10, max_levels=2, em_convergence=0.0
    )
    G_dev, weights = shard_pairs(mesh, G_odd)
    sharded = run_em(
        G_dev,
        init,
        max_iterations=10,
        max_levels=2,
        em_convergence=0.0,
        weights=weights.astype(init.m.dtype),
    )
    # tolerances allow cross-shard reduction-order float drift only
    assert float(sharded.params.lam) == pytest.approx(float(ref_odd.params.lam), rel=1e-9)
    np.testing.assert_allclose(
        np.asarray(sharded.params.m), np.asarray(ref_odd.params.m), rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(sharded.params.u), np.asarray(ref_odd.params.u), rtol=1e-9
    )
    del ref


def test_streamed_em_matches_in_memory():
    G, init = _dgp(n=10_000)
    ref = run_em(jnp.asarray(G), init, max_iterations=8, max_levels=2, em_convergence=0.0)

    def batches():
        for start in range(0, len(G), 1024):
            yield G[start : start + 1024]

    params, hist, n_updates, converged = run_em_streamed(
        batches,
        init,
        max_iterations=8,
        max_levels=2,
        em_convergence=0.0,
    )
    assert n_updates == 8
    assert float(params.lam) == pytest.approx(float(ref.params.lam), rel=1e-10)
    np.testing.assert_allclose(np.asarray(params.m), np.asarray(ref.params.m), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(params.u), np.asarray(ref.params.u), rtol=1e-9)
    # histories align: entry 0 is the init
    assert hist["lam"][0] == pytest.approx(0.5)


def test_streamed_em_sharded_batches():
    G, init = _dgp(n=8_192)
    ref = run_em(jnp.asarray(G), init, max_iterations=5, max_levels=2, em_convergence=0.0)
    mesh = make_mesh()

    def batches():
        for start in range(0, len(G), 1000):  # ragged: exercises padding
            yield G[start : start + 1000]

    params, _, _, _ = run_em_streamed(
        batches,
        init,
        max_iterations=5,
        max_levels=2,
        em_convergence=0.0,
        mesh=mesh,
    )
    assert float(params.lam) == pytest.approx(float(ref.params.lam), rel=1e-10)


def test_mesh_from_settings():
    assert mesh_from_settings({"mesh": {}}) is None
    assert mesh_from_settings({}) is None
    mesh = mesh_from_settings({"mesh": {"data": 8}})
    assert mesh.devices.size == 8
    with pytest.raises(ValueError):
        mesh_from_settings({"mesh": {"model": 2}})


def test_mesh_from_settings_explicit_single_device():
    # {"data": 1} is a REAL one-device mesh (the sharded code path with one
    # shard), distinct from the empty dict's unsharded path
    mesh = mesh_from_settings({"mesh": {"data": 1}})
    assert mesh is not None
    assert mesh.devices.size == 1


def test_mesh_from_settings_error_reports_supported_form():
    for bad in (
        {"mesh": {"model": 2}},
        {"mesh": {"data": 0}},
        {"mesh": {"data": -3}},
        {"mesh": {"data": "eight"}},
        {"mesh": {"data": True}},
        {"mesh": {"data": 9}},  # more than the 8 visible devices
    ):
        with pytest.raises(ValueError, match="supported form"):
            mesh_from_settings(bad)


def test_linker_explicit_single_device_mesh_matches_unsharded():
    import pandas as pd

    from splink_tpu import Splink

    rng = np.random.default_rng(11)
    df = pd.DataFrame(
        {
            "unique_id": range(80),
            "name": rng.choice(["ann", "bob", "cat"], 80),
            "dob": rng.choice(["x", "y"], 80),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {"col_name": "name", "comparison": {"kind": "exact"}},
        ],
        "blocking_rules": ["l.dob = r.dob"],
        "max_iterations": 4,
        "float64": True,
    }
    plain = Splink(dict(s), df=df).get_scored_comparisons()
    meshed = Splink(dict(s, mesh={"data": 1}), df=df).get_scored_comparisons()
    np.testing.assert_allclose(
        plain.match_probability.to_numpy(),
        meshed.match_probability.to_numpy(),
        rtol=1e-12,
    )


@pytest.mark.parametrize("ndev", [2, 4, 8])
def test_shard_pairs_padding_semantics(ndev):
    """Uneven n_pairs across 2/4/8-way meshes: the shard_pairs padding rows
    contribute EXACTLY nothing to the EM sufficient statistics.

    Bit-identity is asserted where it is mathematically owed — the stats
    must not change by one ulp when the padding rows' CONTENT changes
    (weight 0 annihilates them exactly) — and the sharded aggregate matches
    the unsharded path up to cross-shard reduction-order drift only (a
    different summation tree legitimately rounds differently; under f64
    that drift is bounded far below 1e-12)."""
    from splink_tpu.models.fellegi_sunter import FSParams as FS
    from splink_tpu.parallel.mesh import pair_sharding
    from splink_tpu.parallel.streaming import _batch_stats

    rng = np.random.default_rng(31)
    n = 10_007  # never a multiple of 2/4/8
    G = rng.integers(-1, 3, size=(n, 3)).astype(np.int8)
    params = FS(
        lam=jnp.asarray(0.3),
        m=jnp.asarray(np.tile([0.2, 0.5, 0.3], (3, 1))),
        u=jnp.asarray(np.tile([0.5, 0.3, 0.2], (3, 1))),
    )
    mesh = make_mesh(ndev)
    G_dev, w = shard_pairs(mesh, G)
    n_pad = G_dev.shape[0]
    assert n_pad % ndev == 0 and n_pad >= n
    w_host = np.asarray(w)
    assert (w_host[:n] == 1.0).all() and (w_host[n:] == 0.0).all()
    wf = w.astype(params.m.dtype)

    stats, ll = _batch_stats(G_dev, params, 3, wf, True)

    # (a) bit-identity under padding-content change: refill the padding
    # rows with every distinct gamma value; not one output bit may move
    for fill in (0, 1, 2):
        G_alt = np.concatenate(
            [G, np.full((n_pad - n, 3), fill, np.int8)]
        )
        G_alt_dev = jax.device_put(G_alt, pair_sharding(mesh))
        stats_alt, ll_alt = _batch_stats(G_alt_dev, params, 3, wf, True)
        for a, b in zip(stats, stats_alt):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert float(ll) == float(ll_alt)

    # (b) an all-padding batch (weights identically 0) produces exact-zero
    # statistics — nothing for the M-step to absorb
    zero_w = jnp.zeros(n_pad, params.m.dtype)
    stats_zero, _ = _batch_stats(G_dev, params, 3, zero_w, True)
    for leaf in stats_zero:
        np.testing.assert_array_equal(
            np.asarray(leaf), np.zeros_like(np.asarray(leaf))
        )

    # (c) the sharded aggregate equals the unsharded one up to reduction
    # order; the EM trajectories then agree to the same precision
    ref_stats, ref_ll = _batch_stats(jnp.asarray(G), params, 3, None, True)
    for a, b in zip(stats, ref_stats):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-12, atol=0
        )
    np.testing.assert_allclose(float(ll), float(ref_ll), rtol=1e-12)

    ref_em = run_em(
        jnp.asarray(G), params, max_iterations=6, max_levels=3,
        em_convergence=0.0,
    )
    shard_em = run_em(
        G_dev, params, max_iterations=6, max_levels=3, em_convergence=0.0,
        weights=wf,
    )
    np.testing.assert_allclose(
        np.asarray(shard_em.params.m), np.asarray(ref_em.params.m),
        rtol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(shard_em.params.u), np.asarray(ref_em.params.u),
        rtol=1e-12,
    )
    assert float(shard_em.params.lam) == pytest.approx(
        float(ref_em.params.lam), rel=1e-12
    )


def test_linker_with_mesh_setting():
    import pandas as pd

    from splink_tpu import Splink

    rng = np.random.default_rng(4)
    df = pd.DataFrame(
        {
            "unique_id": range(100),
            "name": rng.choice(["ann", "bob", "cat", "dan"], 100),
            "dob": rng.choice(["x", "y", "z"], 100),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {"col_name": "name", "comparison": {"kind": "exact"}},
            {"col_name": "dob", "comparison": {"kind": "exact"}},
        ],
        "blocking_rules": ["l.dob = r.dob"],
        "max_iterations": 5,
        "mesh": {"data": 8},
        "float64": True,  # keeps the mesh-vs-single comparison exact on CPU
    }
    linker = Splink(s, df=df)
    df_e = linker.get_scored_comparisons()
    assert df_e.match_probability.between(0, 1).all()

    s2 = {**s, "mesh": {}}
    linker2 = Splink(s2, df=df)
    df_e2 = linker2.get_scored_comparisons()
    np.testing.assert_allclose(
        df_e.match_probability.to_numpy(), df_e2.match_probability.to_numpy(), rtol=1e-9
    )


def test_mesh_linker_with_case_sql_matches_single_device():
    """Sharded EM over the 8-device mesh with a compiled CASE comparison
    must score like the single-device path."""
    import pandas as pd

    from splink_tpu import Splink

    rng = np.random.default_rng(9)
    n = 240
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["ann", "bob", "cat", "dan", "eve"], n),
            "city": rng.choice(["x", "y"], n),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.city = r.city"],
        "comparison_columns": [
            {
                "col_name": "name",
                "num_levels": 2,
                "case_expression": "case when name_l is null or name_r is "
                "null then -1 when lower(name_l) = lower(name_r) then 1 "
                "else 0 end",
            }
        ],
        "max_iterations": 5,
        "float64": True,
    }
    single = Splink(s, df=df).get_scored_comparisons()
    meshed = Splink({**s, "mesh": {"data": 8}}, df=df).get_scored_comparisons()
    m = single.merge(
        meshed, on=["unique_id_l", "unique_id_r"], suffixes=("_a", "_b")
    )
    assert len(m) == len(single) == len(meshed)
    np.testing.assert_allclose(
        m.match_probability_a, m.match_probability_b, rtol=1e-9
    )


def test_materialised_pattern_pass_mesh_bit_parity():
    """compute_pattern_ids with a mesh shards the pair axis and must be
    bit-identical to the single-device pass (round 4: materialised
    pattern jobs compose with multi-chip EM like virtual ones)."""
    import numpy as np
    import pandas as pd

    from splink_tpu.blocking import block_using_rules
    from splink_tpu.data import encode_table
    from splink_tpu.gammas import GammaProgram
    from splink_tpu.parallel.mesh import make_mesh
    from splink_tpu.settings import complete_settings_dict

    rng = np.random.default_rng(51)
    n = 500
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["ann", "bob", "cat", None], n),
            "dob": rng.choice([f"d{k}" for k in range(8)], n),
        }
    )
    s = complete_settings_dict(
        {
            "link_type": "dedupe_only",
            "comparison_columns": [{"col_name": "name", "num_levels": 3}],
            "blocking_rules": ["l.dob = r.dob"],
        }
    )
    t = encode_table(df, s)
    pairs = block_using_rules(s, t)
    prog = GammaProgram(s, t)
    p1, c1 = prog.compute_pattern_ids(pairs.idx_l, pairs.idx_r, 4096)
    p2, c2 = prog.compute_pattern_ids(
        pairs.idx_l, pairs.idx_r, 4096, mesh=make_mesh(8)
    )
    np.testing.assert_array_equal(p1, p2)
    np.testing.assert_array_equal(c1, c2)


def test_linker_mesh_materialised_pattern_pipeline_e2e():
    """Mesh + device_pair_generation=off + pairs above max_resident:
    the PatternStream/compute_pattern_ids mesh path end to end, scores
    identical to the single-device run."""
    import numpy as np
    import pandas as pd

    from splink_tpu import Splink

    rng = np.random.default_rng(53)
    n = 900
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["ann", "bob", "cat", "dan", None], n),
            "dob": rng.choice([f"d{k}" for k in range(10)], n),
            "city": rng.choice(["x", "y", "z"], n),
        }
    )
    base = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {"col_name": "name", "num_levels": 3},
            {"col_name": "city", "num_levels": 2},
        ],
        "blocking_rules": ["l.dob = r.dob"],
        "max_resident_pairs": 1024,
        "device_pair_generation": "off",
        "max_iterations": 6,
    }
    key = ["unique_id_l", "unique_id_r"]
    a = (
        Splink(dict(base), df=df)
        .get_scored_comparisons()
        .sort_values(key)
        .reset_index(drop=True)
    )
    b = (
        Splink(dict(base, mesh={"data": 8}), df=df)
        .get_scored_comparisons()
        .sort_values(key)
        .reset_index(drop=True)
    )
    assert len(a) == len(b) and len(a) > 2000
    np.testing.assert_array_equal(a[key].to_numpy(), b[key].to_numpy())
    np.testing.assert_allclose(
        a.match_probability, b.match_probability, rtol=1e-12
    )
