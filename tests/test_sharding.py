"""Multi-device semantics on the virtual 8-device CPU mesh: sharded EM and
streamed EM must agree exactly with the single-device in-memory path — the
JAX analogue of the reference running one scenario through both sqlite and
Spark."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from splink_tpu.em import run_em
from splink_tpu.models.fellegi_sunter import FSParams
from splink_tpu.parallel import (
    make_mesh,
    mesh_from_settings,
    run_em_streamed,
    shard_pairs,
)


def _dgp(n=20_000, seed=0):
    rng = np.random.default_rng(seed)
    lam = 0.3
    m = np.array([[0.1, 0.9], [0.2, 0.8], [0.3, 0.7]])
    u = np.array([[0.85, 0.15], [0.7, 0.3], [0.6, 0.4]])
    is_match = rng.random(n) < lam
    G = np.zeros((n, 3), np.int8)
    for c in range(3):
        probs = np.where(is_match[:, None], m[c], u[c])
        G[:, c] = (rng.random(n)[:, None] > probs.cumsum(1)).sum(1)
    init = FSParams(
        lam=jnp.asarray(0.5),
        m=jnp.asarray(np.full((3, 2), 0.5)),
        u=jnp.asarray(np.full((3, 2), 0.5)),
    )
    # symmetric init won't move; use slightly asymmetric
    m0 = np.tile([0.4, 0.6], (3, 1))
    u0 = np.tile([0.6, 0.4], (3, 1))
    init = FSParams(lam=jnp.asarray(0.5), m=jnp.asarray(m0), u=jnp.asarray(u0))
    return G, init


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_em_matches_single_device():
    G, init = _dgp(n=20_000)
    ref = run_em(jnp.asarray(G), init, max_iterations=10, max_levels=2, em_convergence=0.0)

    mesh = make_mesh()
    # deliberately use a size not divisible by 8 to exercise padding
    G_odd = G[:-3]
    ref_odd = run_em(
        jnp.asarray(G_odd), init, max_iterations=10, max_levels=2, em_convergence=0.0
    )
    G_dev, weights = shard_pairs(mesh, G_odd)
    sharded = run_em(
        G_dev,
        init,
        max_iterations=10,
        max_levels=2,
        em_convergence=0.0,
        weights=weights.astype(init.m.dtype),
    )
    # tolerances allow cross-shard reduction-order float drift only
    assert float(sharded.params.lam) == pytest.approx(float(ref_odd.params.lam), rel=1e-9)
    np.testing.assert_allclose(
        np.asarray(sharded.params.m), np.asarray(ref_odd.params.m), rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(sharded.params.u), np.asarray(ref_odd.params.u), rtol=1e-9
    )
    del ref


def test_streamed_em_matches_in_memory():
    G, init = _dgp(n=10_000)
    ref = run_em(jnp.asarray(G), init, max_iterations=8, max_levels=2, em_convergence=0.0)

    def batches():
        for start in range(0, len(G), 1024):
            yield G[start : start + 1024]

    params, hist, n_updates, converged = run_em_streamed(
        batches,
        init,
        max_iterations=8,
        max_levels=2,
        em_convergence=0.0,
    )
    assert n_updates == 8
    assert float(params.lam) == pytest.approx(float(ref.params.lam), rel=1e-10)
    np.testing.assert_allclose(np.asarray(params.m), np.asarray(ref.params.m), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(params.u), np.asarray(ref.params.u), rtol=1e-9)
    # histories align: entry 0 is the init
    assert hist["lam"][0] == pytest.approx(0.5)


def test_streamed_em_sharded_batches():
    G, init = _dgp(n=8_192)
    ref = run_em(jnp.asarray(G), init, max_iterations=5, max_levels=2, em_convergence=0.0)
    mesh = make_mesh()

    def batches():
        for start in range(0, len(G), 1000):  # ragged: exercises padding
            yield G[start : start + 1000]

    params, _, _, _ = run_em_streamed(
        batches,
        init,
        max_iterations=5,
        max_levels=2,
        em_convergence=0.0,
        mesh=mesh,
    )
    assert float(params.lam) == pytest.approx(float(ref.params.lam), rel=1e-10)


def test_mesh_from_settings():
    assert mesh_from_settings({"mesh": {}}) is None
    mesh = mesh_from_settings({"mesh": {"data": 8}})
    assert mesh.devices.size == 8
    with pytest.raises(ValueError):
        mesh_from_settings({"mesh": {"model": 2}})


def test_linker_with_mesh_setting():
    import pandas as pd

    from splink_tpu import Splink

    rng = np.random.default_rng(4)
    df = pd.DataFrame(
        {
            "unique_id": range(100),
            "name": rng.choice(["ann", "bob", "cat", "dan"], 100),
            "dob": rng.choice(["x", "y", "z"], 100),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {"col_name": "name", "comparison": {"kind": "exact"}},
            {"col_name": "dob", "comparison": {"kind": "exact"}},
        ],
        "blocking_rules": ["l.dob = r.dob"],
        "max_iterations": 5,
        "mesh": {"data": 8},
        "float64": True,  # keeps the mesh-vs-single comparison exact on CPU
    }
    linker = Splink(s, df=df)
    df_e = linker.get_scored_comparisons()
    assert df_e.match_probability.between(0, 1).all()

    s2 = {**s, "mesh": {}}
    linker2 = Splink(s2, df=df)
    df_e2 = linker2.get_scored_comparisons()
    np.testing.assert_allclose(
        df_e.match_probability.to_numpy(), df_e2.match_probability.to_numpy(), rtol=1e-9
    )


def test_mesh_linker_with_case_sql_matches_single_device():
    """Sharded EM over the 8-device mesh with a compiled CASE comparison
    must score like the single-device path."""
    import pandas as pd

    from splink_tpu import Splink

    rng = np.random.default_rng(9)
    n = 240
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["ann", "bob", "cat", "dan", "eve"], n),
            "city": rng.choice(["x", "y"], n),
        }
    )
    s = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.city = r.city"],
        "comparison_columns": [
            {
                "col_name": "name",
                "num_levels": 2,
                "case_expression": "case when name_l is null or name_r is "
                "null then -1 when lower(name_l) = lower(name_r) then 1 "
                "else 0 end",
            }
        ],
        "max_iterations": 5,
        "float64": True,
    }
    single = Splink(s, df=df).get_scored_comparisons()
    meshed = Splink({**s, "mesh": {"data": 8}}, df=df).get_scored_comparisons()
    m = single.merge(
        meshed, on=["unique_id_l", "unique_id_r"], suffixes=("_a", "_b")
    )
    assert len(m) == len(single) == len(meshed)
    np.testing.assert_allclose(
        m.match_probability_a, m.match_probability_b, rtol=1e-9
    )


def test_materialised_pattern_pass_mesh_bit_parity():
    """compute_pattern_ids with a mesh shards the pair axis and must be
    bit-identical to the single-device pass (round 4: materialised
    pattern jobs compose with multi-chip EM like virtual ones)."""
    import numpy as np
    import pandas as pd

    from splink_tpu.blocking import block_using_rules
    from splink_tpu.data import encode_table
    from splink_tpu.gammas import GammaProgram
    from splink_tpu.parallel.mesh import make_mesh
    from splink_tpu.settings import complete_settings_dict

    rng = np.random.default_rng(51)
    n = 500
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["ann", "bob", "cat", None], n),
            "dob": rng.choice([f"d{k}" for k in range(8)], n),
        }
    )
    s = complete_settings_dict(
        {
            "link_type": "dedupe_only",
            "comparison_columns": [{"col_name": "name", "num_levels": 3}],
            "blocking_rules": ["l.dob = r.dob"],
        }
    )
    t = encode_table(df, s)
    pairs = block_using_rules(s, t)
    prog = GammaProgram(s, t)
    p1, c1 = prog.compute_pattern_ids(pairs.idx_l, pairs.idx_r, 4096)
    p2, c2 = prog.compute_pattern_ids(
        pairs.idx_l, pairs.idx_r, 4096, mesh=make_mesh(8)
    )
    np.testing.assert_array_equal(p1, p2)
    np.testing.assert_array_equal(c1, c2)


def test_linker_mesh_materialised_pattern_pipeline_e2e():
    """Mesh + device_pair_generation=off + pairs above max_resident:
    the PatternStream/compute_pattern_ids mesh path end to end, scores
    identical to the single-device run."""
    import numpy as np
    import pandas as pd

    from splink_tpu import Splink

    rng = np.random.default_rng(53)
    n = 900
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": rng.choice(["ann", "bob", "cat", "dan", None], n),
            "dob": rng.choice([f"d{k}" for k in range(10)], n),
            "city": rng.choice(["x", "y", "z"], n),
        }
    )
    base = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {"col_name": "name", "num_levels": 3},
            {"col_name": "city", "num_levels": 2},
        ],
        "blocking_rules": ["l.dob = r.dob"],
        "max_resident_pairs": 1024,
        "device_pair_generation": "off",
        "max_iterations": 6,
    }
    key = ["unique_id_l", "unique_id_r"]
    a = (
        Splink(dict(base), df=df)
        .get_scored_comparisons()
        .sort_values(key)
        .reset_index(drop=True)
    )
    b = (
        Splink(dict(base, mesh={"data": 8}), df=df)
        .get_scored_comparisons()
        .sort_values(key)
        .reset_index(drop=True)
    )
    assert len(a) == len(b) and len(a) > 2000
    np.testing.assert_array_equal(a[key].to_numpy(), b[key].to_numpy())
    np.testing.assert_allclose(
        a.match_probability, b.match_probability, rtol=1e-12
    )
