"""Online serving (splink_tpu/serve/): serve<->offline score parity,
bucketed compile-cache behaviour, micro-batching admission control, artifact
durability, and the key-code cache-release regression.

The parity contract is BIT-identity: for every (query record, reference
record) pair the engine returns, the match probability must equal
``get_scored_comparisons`` on the same pair exactly — the serving path
re-encodes the query side against the reference vocabulary and runs the
same comparison kernels, so any drift is a bug, not tolerance noise.
"""

import warnings

import numpy as np
import pandas as pd
import pytest

from splink_tpu import Splink
from splink_tpu.serve import (
    BucketPolicy,
    IndexMismatchError,
    LinkageService,
    QueryEngine,
    build_index,
    load_index,
)
from splink_tpu.utils.logging_utils import DegradationWarning


def people_df(n=120, seed=11):
    rng = np.random.default_rng(seed)
    firsts = ["amelia", "oliver", "isla", "george", "ava", "noah", "emily"]
    lasts = ["smith", "jones", "taylor", "brown", "wilson", "evans"]
    return pd.DataFrame(
        {
            "unique_id": range(n),
            "first_name": [str(rng.choice(firsts)) for _ in range(n)],
            "surname": [str(rng.choice(lasts)) for _ in range(n)],
            "dob": [f"19{rng.integers(40, 99)}" for _ in range(n)],
        }
    )


def serve_settings(**over):
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {"col_name": "first_name", "num_levels": 3},
            {
                "col_name": "surname",
                "num_levels": 2,
                "comparison": {"kind": "exact"},
            },
        ],
        "blocking_rules": ["l.dob = r.dob", "l.surname = r.surname"],
        "max_iterations": 6,
    }
    s.update(over)
    return s


@pytest.fixture(scope="module")
def trained():
    """(df, linker, df_e, index): one trained linker + frozen index shared
    across the module (training dominates the suite's cost)."""
    df = people_df()
    linker = Splink(serve_settings(), df=df)
    df_e = linker.get_scored_comparisons()
    index = linker.export_index()
    return df, linker, df_e, index


@pytest.fixture(scope="module")
def engine(trained):
    _, _, _, index = trained
    eng = QueryEngine(
        index, top_k=64, policy=BucketPolicy((16, 128), (64, 256))
    )
    eng.warmup()
    return eng


def _offline_scores(df_e):
    return {
        (r["unique_id_l"], r["unique_id_r"]): r["match_probability"]
        for _, r in df_e.iterrows()
    }


def test_serve_offline_parity_bit_identical(trained, engine):
    """Every served (query, match) score equals the offline score for the
    same pair bitwise, and the served candidate sets cover EVERY offline
    pair (top_k exceeds the largest block, so nothing is cut off)."""
    df, _, df_e, index = trained
    offline = _offline_scores(df_e)
    top_p, top_rows, top_valid, n_cand = engine.query_arrays(df)
    assert top_p.dtype == np.float32
    served = set()
    checked = 0
    for q in range(len(df)):
        for r in range(top_p.shape[1]):
            if not top_valid[q, r]:
                continue
            m = int(index.unique_id[top_rows[q, r]])
            if m == q:
                continue  # self-match: not an offline pair (uid ordering)
            key = (min(q, m), max(q, m))
            assert key in offline, f"served pair {key} missing offline"
            assert np.float32(offline[key]) == top_p[q, r], key
            served.add(key)
            checked += 1
    assert checked > 100
    assert served == set(offline), "serve must cover every offline pair"


def test_serve_parity_float64_tier():
    """The float64 tier holds the same bit-identity (the engine runs the
    index's recorded dtype end to end)."""
    df = people_df(60, seed=3)
    linker = Splink(serve_settings(float64=True, max_iterations=3), df=df)
    df_e = linker.get_scored_comparisons()
    index = linker.export_index()
    assert index.dtype == "float64"
    eng = QueryEngine(index, top_k=64, policy=BucketPolicy((64,), (128,)))
    offline = _offline_scores(df_e)
    top_p, top_rows, top_valid, _ = eng.query_arrays(df)
    assert top_p.dtype == np.float64
    checked = 0
    for q in range(len(df)):
        for r in range(top_p.shape[1]):
            if not top_valid[q, r]:
                continue
            m = int(index.unique_id[top_rows[q, r]])
            if m == q:
                continue
            checked += 1
            assert offline[(min(q, m), max(q, m))] == top_p[q, r]
    assert checked > 50


def test_self_match_scores_highest(trained, engine):
    """A query identical to a reference record must retrieve that record
    at (joint-)top rank — the entity-lookup sanity check."""
    df, _, _, index = trained
    top_p, top_rows, top_valid, _ = engine.query_arrays(df.head(20))
    for q in range(20):
        ranks = [
            r
            for r in range(top_p.shape[1])
            if top_valid[q, r] and int(index.unique_id[top_rows[q, r]]) == q
        ]
        assert ranks, f"query {q} did not retrieve itself"
        assert top_p[q, ranks[0]] == top_p[q, 0]  # ties share the top score


def test_warmup_compiles_once_per_bucket_combo(trained):
    """Compile count == number of distinct (query, candidate) bucket
    combinations after warmup, and steady-state serving (any bucketed
    batch size) performs ZERO recompiles — measured by the jax.monitoring
    compile counter."""
    from splink_tpu.obs.metrics import compile_requests

    df, _, _, index = trained
    policy = BucketPolicy((8, 32), (64, 128))
    eng = QueryEngine(index, top_k=8, policy=policy)
    stats = eng.warmup()
    assert stats["combinations"] == 4
    # each combination costs exactly one backend_compile request — a real
    # compile, or a persistent-cache restore when an earlier test in this
    # session already compiled the identical program (the split accounting
    # tells them apart; neither may happen in steady state below)
    assert stats["compiles"] + stats["cache_hits"] == 4
    c0 = compile_requests()
    eng.query_arrays(df.head(3))
    eng.query_arrays(df.head(30))
    eng.query_arrays(df.head(70))  # > largest bucket: splits into chunks
    c1 = compile_requests()
    assert c1 - c0 == 0, "steady-state serving must not recompile"
    assert eng.warmed_shapes == {(8, 64), (8, 128), (32, 64), (32, 128)}


def test_large_batch_splits_into_bucket_chunks(trained, engine):
    """A batch beyond the largest query bucket chunks internally and the
    results equal the per-chunk results row for row."""
    df, _, _, _ = trained
    whole = engine.query_arrays(df)
    head = engine.query_arrays(df.head(50))
    for a, b in zip(whole, head):
        assert np.array_equal(a[:50], b)


def test_unseen_and_null_query_values(trained, engine):
    """Unseen names score through the kernels (fresh token ids); a null
    blocking key yields no candidates rather than an error."""
    df, _, _, _ = trained
    q = pd.DataFrame(
        {
            "unique_id": [0, 1],
            "first_name": ["zzyzx", None],
            "surname": [df["surname"][0], None],
            "dob": [df["dob"][0], None],
        }
    )
    top_p, top_rows, top_valid, n_cand = engine.query_arrays(q)
    assert n_cand[0] > 0  # dob+surname blocks still resolve
    assert n_cand[1] == 0 and not top_valid[1].any()


def test_key_code_cache_released_after_build(trained):
    """build_index runs through blocking's per-table key-code cache but
    must release it on completion: an index build holds the encoded table
    long-lived, and each cached key tuple is 8 bytes/row of host RAM.
    Building twice must not grow the cache either."""
    df, linker, _, _ = trained
    table = linker._ensure_encoded()
    for _ in range(2):
        build_index(linker)
        assert not getattr(table, "_key_code_cache", None)
        assert not getattr(table, "_asym_code_cache", None)


def test_index_save_load_roundtrip(tmp_path, trained, engine):
    """Scores from a loaded artifact are identical to the in-memory index;
    a tampered artifact is rejected, never served."""
    df, linker, _, _ = trained
    path = tmp_path / "idx"
    linker.export_index(path)
    index2 = load_index(path)
    eng2 = QueryEngine(
        index2, top_k=64, policy=BucketPolicy((16, 128), (64, 256))
    )
    a = engine.query_arrays(df.head(40))
    b = eng2.query_arrays(df.head(40))
    for x, y in zip(a, b):
        assert np.array_equal(x, y)
    # tamper with the committed meta -> hash binding rejects it
    import json

    meta_path = path / "linkage_index.json"
    meta = json.loads(meta_path.read_text())
    meta["n_rows"] = meta["n_rows"] + 1
    meta_path.write_text(json.dumps(meta))
    with pytest.raises(IndexMismatchError):
        load_index(path)


def test_short_candidate_rows_never_emit_sentinel_matches(trained):
    """A query with fewer valid candidates than top_k must report ONLY its
    real candidates: the re-picked mask-sentinel slots are flagged invalid
    (they previously leaked as duplicate matches scored -2.0)."""
    df, _, _, index = trained
    eng = QueryEngine(index, top_k=64, policy=BucketPolicy((16,), (64,)))
    top_p, top_rows, top_valid, n_cand = eng.query_arrays(df.head(16))
    for q in range(16):
        assert int(top_valid[q].sum()) == min(int(n_cand[q]), 64)
        assert (top_p[q][top_valid[q]] >= 0).all()
        rows = top_rows[q][top_valid[q]]
        assert len(np.unique(rows)) == len(rows), "duplicate match rows"


def test_top_k_capacity_validated(trained):
    """top_k beyond the largest candidate bucket cannot produce truncated
    nonsense — the engine rejects the configuration up front."""
    _, _, _, index = trained
    with pytest.raises(ValueError, match="serve_top_k"):
        QueryEngine(index, top_k=128, policy=BucketPolicy((16,), (64,)))


def test_submit_after_close_sheds_not_hangs(trained, engine):
    """A closed service must never hand out a future nobody will resolve:
    post-close submissions resolve immediately as shed, with the
    degradation event."""
    svc = LinkageService(engine, deadline_ms=1.0)
    svc.close()
    with pytest.warns(DegradationWarning, match="closed"):
        fut = svc.submit({"unique_id": 0, "first_name": "ava",
                          "surname": "smith", "dob": "1950"})
    assert fut.result(timeout=5).shed


def test_save_over_existing_index_is_crash_safe(tmp_path, trained):
    """Re-saving over a live artifact must leave the OLD artifact loadable
    at every intermediate point: the new arrays land in a fresh
    fingerprint-named file and the meta commit flips atomically."""
    import json

    df, linker, _, _ = trained
    path = tmp_path / "idx"
    linker.export_index(path)
    meta1 = json.loads((path / "linkage_index.json").read_text())
    # simulate the crash window: new arrays written, meta NOT yet
    # committed — the old meta must still load against the old arrays
    (path / "linkage_index-deadbeefdeadbeef.npz").write_bytes(b"garbage")
    index = load_index(path)
    assert index.n_rows == meta1["n_rows"]
    # a full re-save commits and sweeps the stray arrays file
    linker.export_index(path)
    leftovers = [p.name for p in path.iterdir() if p.suffix == ".npz"]
    meta2 = json.loads((path / "linkage_index.json").read_text())
    assert leftovers == [meta2["arrays_file"]]
    load_index(path)


def test_unsupported_blocking_rules_rejected():
    """Residual predicates and cartesian rules cannot be served; the build
    fails loudly instead of serving wrong candidates."""
    df = people_df(20)
    linker = Splink(
        serve_settings(
            blocking_rules=["l.dob = r.dob and l.unique_id + 1 < r.unique_id"]
        ),
        df=df,
    )
    with pytest.raises(ValueError, match="residual"):
        build_index(linker)
    with pytest.warns(UserWarning, match="blocking"):
        linker2 = Splink(serve_settings(blocking_rules=[]), df=df)
    with pytest.raises(ValueError, match="blocking rule"):
        build_index(linker2)


def test_service_micro_batching_end_to_end(trained, engine):
    """Submitted records coalesce into batches, every future resolves with
    its matches, and the latency summary reports percentiles."""
    df, _, df_e, _ = trained
    offline = _offline_scores(df_e)
    records = df.head(30).to_dict(orient="records")
    with LinkageService(engine, deadline_ms=20.0, queue_depth=64) as svc:
        futures = [svc.submit(r) for r in records]
        results = [f.result(timeout=30) for f in futures]
        summary = svc.latency_summary()
    assert all(not r.shed for r in results)
    assert summary["served"] == 30 and summary["shed"] == 0
    assert summary["p50_ms"] > 0 and summary["p99_ms"] >= summary["p50_ms"]
    # spot-check one served score against the offline frame, bit-identical
    for rec, res in zip(records, results):
        for uid, p in res.matches:
            if uid == rec["unique_id"]:
                continue
            key = (min(rec["unique_id"], uid), max(rec["unique_id"], uid))
            assert np.float32(offline[key]) == np.float32(p)


def test_overload_sheds_with_degradation_event(trained, engine):
    """Admission control: a full bounded queue sheds load through the
    structured degradation channel — submit never raises, the shed future
    resolves immediately with shed=True, and both the DegradationWarning
    and the telemetry event fire."""
    from splink_tpu.obs import events

    captured = []

    class _Sink:
        def emit(self, kind, **fields):
            captured.append((kind, fields))

    sink = _Sink()
    events.register_ambient(sink)
    try:
        svc = LinkageService(
            engine, queue_depth=2, deadline_ms=50.0, autostart=False
        )
        record = {"unique_id": 0, "first_name": "ava", "surname": "smith",
                  "dob": "1950"}
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            futures = [svc.submit(dict(record)) for _ in range(5)]
        shed = [f for f in futures if f.done() and f.result().shed]
        assert len(shed) == 3  # queue_depth=2 admitted two
        degr = [w for w in caught if issubclass(w.category, DegradationWarning)]
        assert len(degr) == 3
        assert any(k == "degradation" for k, _ in captured)
        # the two admitted requests still serve once the worker starts
        svc.start()
        pending = [f for f in futures if f not in shed]
        for f in pending:
            res = f.result(timeout=30)
            assert not res.shed and res.n_candidates >= 1
        svc.close()
    finally:
        events.unregister_ambient(sink)
