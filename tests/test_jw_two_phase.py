"""Two-phase Jaro-Winkler gamma scoring (gammas._jw_two_phase, ops/jw_bound).

Three properties keep the optimisation honest:

  * bound soundness — jw_upper_bound never undercuts the exact kernel
    (an unsound bound would silently misclassify pairs below a threshold);
  * bit-identity — the two-phase body and the exact body produce the SAME
    gamma matrix (the pruning is an optimisation, never a result change);
  * overflow redo — when the survivor capacity blows (forced here with
    jw_survivor_divisor = 10**6, capacity floor 1024), every consumer
    (safe _gamma_batch, the flagged G path, the pattern/histogram path)
    redoes the batch through the exact twin instead of scoring survivors
    it had no slots for.
"""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from splink_tpu.data import encode_table
from splink_tpu.gammas import GammaProgram
from splink_tpu.ops import jw_bound, strings
from splink_tpu.settings import complete_settings_dict

from conftest import py_jaro_winkler

W = 16  # packed char width for the direct-kernel fuzz


def _enc(words, width=W):
    n = len(words)
    b = np.zeros((n, width), np.uint8)
    lens = np.zeros(n, np.int32)
    for i, w in enumerate(words):
        raw = w.encode()[:width]
        b[i, : len(raw)] = np.frombuffer(raw, np.uint8)
        lens[i] = len(raw)
    return b, lens


# ----------------------------------------------------------------------
# Bound soundness
# ----------------------------------------------------------------------


def _fuzz_words(rng, n):
    """Adversarial mix: random words, heavy repeats (nibble-counter
    overflow), shared 4-char prefixes (the unconditional-survivor case),
    near-misses, empties."""
    alphabet = list("abcdefghijklmnopqrstuvwxyz")
    tight = list("abc")  # forces class collisions under the 32-way hash
    words = []
    for _ in range(n):
        r = rng.random()
        if r < 0.15:
            words.append("a" * rng.integers(0, 13))  # counts past cap 7
        elif r < 0.35:
            words.append("".join(rng.choice(tight, rng.integers(0, 12))))
        elif r < 0.55:
            words.append("pref" + "".join(rng.choice(alphabet, rng.integers(0, 8))))
        elif r < 0.6:
            words.append("")
        else:
            words.append("".join(rng.choice(alphabet, rng.integers(1, 12))))
    return words


def test_jw_upper_bound_sound_fuzz():
    """For every fuzzed pair: exact JW <= upper bound + BOUND_MARGIN.
    Soundness is what makes phase-1 exclusion safe — an excluded pair
    provably sits below the lowest threshold."""
    rng = np.random.default_rng(1234)
    words = _fuzz_words(rng, 600)
    bytes_, lens = _enc(words)
    token_ids = np.arange(len(words), dtype=np.int64)
    cnt, pref = jw_bound.jw_bound_row_aux(bytes_, lens, token_ids)

    il = rng.integers(0, len(words), 4000)
    ir = rng.integers(0, len(words), 4000)
    ub = np.asarray(
        jw_bound.jw_upper_bound(
            jnp.asarray(cnt[il]),
            jnp.asarray(pref[il, 0]),
            jnp.asarray(cnt[ir]),
            jnp.asarray(pref[ir, 0]),
            jnp.asarray(lens[il]),
            jnp.asarray(lens[ir]),
            0.1,
            0.7,
        )
    )
    exact = np.asarray(
        strings.jaro_winkler(
            bytes_[il], bytes_[ir], lens[il], lens[ir], 0.1, 0.7
        )
    )
    bad = exact > ub + jw_bound.BOUND_MARGIN
    assert not bad.any(), [
        (words[il[k]], words[ir[k]], float(exact[k]), float(ub[k]))
        for k in np.flatnonzero(bad)[:10]
    ]
    # the device kernel itself agrees with the independent Python oracle
    # on a sample (ties the soundness claim back to ground truth)
    sample = rng.integers(0, 4000, 50)
    want = [py_jaro_winkler(words[il[k]], words[ir[k]]) for k in sample]
    np.testing.assert_allclose(exact[sample], want, atol=1e-6)


def test_jw_bound_aux_null_rows_zero():
    words = ["abc", "", "abc"]
    bytes_, lens = _enc(words)
    token_ids = np.array([0, -1, 0], np.int64)  # middle row null
    cnt, pref = jw_bound.jw_bound_row_aux(bytes_, lens, token_ids)
    assert (cnt[1] == 0).all() and pref[1, 0] == 0
    np.testing.assert_array_equal(cnt[0], cnt[2])


# ----------------------------------------------------------------------
# Gamma bit-identity: two-phase vs exact, through GammaProgram
# ----------------------------------------------------------------------


def _jw_df(n=400, seed=5, similar=False):
    rng = np.random.default_rng(seed)
    if similar:
        # shared 6-char prefix, distinct suffixes: every cross pair is an
        # unconditional survivor (4-char prefix match -> bound 2.0) and no
        # pair is token-equal
        names = np.array([f"prefix{i:04d}" for i in range(n)], dtype=object)
    else:
        base = np.array(
            ["amelia", "amelie", "oliver", "olivia", "isla", "george",
             "georgia", "ava", "eva", "noah", "nora", "", None],
            dtype=object,
        )
        names = base[rng.integers(0, len(base), n)]
    return pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "name": names,
            "city": np.array(["x", "y"], dtype=object)[rng.integers(0, 2, n)],
        }
    )


def _jw_settings(**overrides):
    s = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.city = r.city"],
        "comparison_columns": [
            {
                "col_name": "name",
                "num_levels": 3,
                "comparison": {
                    "kind": "jaro_winkler",
                    "thresholds": [0.94, 0.88],
                },
            },
        ],
    }
    s.update(overrides)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return complete_settings_dict(s)


def _programs_and_pairs(df, rng_seed=9, **overrides):
    """(two-phase program, exact program, idx_l, idx_r) on one table."""
    s2 = _jw_settings(**overrides)
    s1 = _jw_settings(two_phase_jw="off", **overrides)
    table = encode_table(df, s2)
    prog2 = GammaProgram(s2, table)
    prog1 = GammaProgram(s1, table)
    assert prog2.two_phase_div and prog1.two_phase_div is None
    rng = np.random.default_rng(rng_seed)
    n_pairs = 2048
    il = rng.integers(0, len(df), n_pairs).astype(np.int32)
    ir = rng.integers(0, len(df), n_pairs).astype(np.int32)
    return prog2, prog1, il, ir


def test_two_phase_gamma_bit_identical_to_exact():
    """Realistic name data (some token-equal, some null, some near-miss):
    the two-phase G equals the exact G bit-for-bit, in both the G and the
    pattern/histogram regimes."""
    prog2, prog1, il, ir = _programs_and_pairs(_jw_df())
    G2 = prog2.compute(il, ir, batch_size=512)
    G1 = prog1.compute(il, ir, batch_size=512)
    np.testing.assert_array_equal(G2, G1)

    p2, c2 = prog2.compute_pattern_ids(il, ir, batch_size=512)
    p1, c1 = prog1.compute_pattern_ids(il, ir, batch_size=512)
    np.testing.assert_array_equal(p2, p1)
    np.testing.assert_array_equal(c2, c1)


def test_two_phase_levels_match_thresholds():
    """Spot-check the gamma levels against the oracle similarity: level =
    number of thresholds strictly below the pair's JW score."""
    df = _jw_df(n=60)
    prog2, _, _, _ = _programs_and_pairs(df)
    il = np.arange(0, 30, dtype=np.int32)
    ir = np.arange(30, 60, dtype=np.int32)
    G = prog2.compute(il, ir, batch_size=32)
    names = df["name"].to_numpy()
    for k in range(len(il)):
        a, b = names[il[k]], names[ir[k]]
        if a is None or b is None:
            assert G[k, 0] == -1  # null level (empty string is a VALUE)
            continue
        sim = py_jaro_winkler(a, b)
        want = (sim > 0.94) + (sim > 0.88)
        assert G[k, 0] == want, (a, b, sim, int(G[k, 0]), want)


# ----------------------------------------------------------------------
# Forced survivor overflow -> exact-twin redo
# ----------------------------------------------------------------------


def test_survivor_overflow_redo_g_and_pattern_regimes():
    """jw_survivor_divisor 10**6 drops capacity to the 1024 floor; 2048
    all-survivor pairs per batch therefore overflow, and every consumer
    must still produce the exact result."""
    df = _jw_df(similar=True)
    prog2, prog1, il, ir = _programs_and_pairs(
        _jw_df(similar=True), jw_survivor_divisor=10**6
    )
    # the overflow really happens: the flagged kernel reports it on a
    # full 2048-pair batch ...
    flagged = np.asarray(
        prog2._gamma_batch_flagged(jnp.asarray(il), jnp.asarray(ir))
    )
    assert flagged[-1, 0] == 1, "survivor capacity did not overflow"

    # ... and each consumer's redo restores exactness:
    # (a) the misuse-proof convenience batch (on-device lax.cond redo)
    G_safe = np.asarray(prog2._gamma_batch(jnp.asarray(il), jnp.asarray(ir)))
    G_exact = prog1.compute(il, ir, batch_size=2048)
    np.testing.assert_array_equal(G_safe, G_exact)

    # (b) the host G regime (flag row read -> exact-twin recompute)
    G2 = prog2.compute(il, ir, batch_size=2048)
    np.testing.assert_array_equal(G2, G_exact)

    # (c) the pattern/histogram regime (flagged batch skipped the
    # histogram; the redo's late accumulation commutes to the same total)
    p2, c2 = prog2.compute_pattern_ids(il, ir, batch_size=2048)
    p1, c1 = prog1.compute_pattern_ids(il, ir, batch_size=2048)
    np.testing.assert_array_equal(p2, p1)
    np.testing.assert_array_equal(c2, c1)
    assert c2.sum() == len(il)


def test_no_overflow_within_capacity():
    """Control for the overflow test: same all-survivor data in a batch
    at the 1024 capacity floor — every survivor has a slot (capacity =
    min(b, max(1024, b // div))), so no flag is raised."""
    prog2, _, il, ir = _programs_and_pairs(_jw_df(similar=True))
    flagged = np.asarray(
        prog2._gamma_batch_flagged(jnp.asarray(il[:1000]), jnp.asarray(ir[:1000]))
    )
    assert flagged[-1, 0] == 0
