"""Multi-host partitioning and initialisation semantics.

Real multi-host cannot run in CI; these tests pin the process_count=1 path
and the partitioning arithmetic under mocked process topology (the JAX
analogue of testing Spark partitioning logic without a cluster)."""

import numpy as np
import pytest

import splink_tpu.parallel.distributed as dist


def test_single_process_slice_covers_everything():
    assert dist.global_pair_slice(1000) == slice(0, 1000)
    assert dist.global_pair_slice(0) == slice(0, 0)


def test_initialize_multihost_single_process_is_noop():
    # no coordinator, no cluster env: logged no-op, no raise
    dist.initialize_multihost()


def test_initialize_multihost_explicit_misconfig_raises():
    with pytest.raises((RuntimeError, ValueError)):
        dist.initialize_multihost(
            coordinator_address="256.0.0.1:0",  # invalid address
            num_processes=2,
            process_id=0,
        )


@pytest.mark.parametrize("n_procs", [2, 3, 8])
@pytest.mark.parametrize("n_pairs", [0, 1, 7, 1000, 1001])
def test_slices_partition_the_pair_axis(monkeypatch, n_procs, n_pairs):
    """Across all processes the slices are disjoint, ordered, cover [0, n),
    and are balanced to within one batch."""
    import jax

    slices = []
    monkeypatch.setattr(jax, "process_count", lambda: n_procs)
    for pid in range(n_procs):
        monkeypatch.setattr(jax, "process_index", lambda pid=pid: pid)
        slices.append(dist.global_pair_slice(n_pairs))

    covered = []
    for s in slices:
        assert 0 <= s.start <= s.stop <= n_pairs
        covered.extend(range(s.start, s.stop))
    assert covered == list(range(n_pairs))
    sizes = [s.stop - s.start for s in slices]
    assert max(sizes) <= -(-n_pairs // n_procs) if n_pairs else max(sizes) == 0


def test_multihost_streamed_em_equals_single_host(monkeypatch):
    """Simulate two controller processes: each runs streamed-stats EM over
    its global_pair_slice, their per-pass sufficient statistics are summed
    (what the psum does on a real pod), and the parameter trajectory must
    equal the single-host run."""
    import jax.numpy as jnp

    from splink_tpu.models.fellegi_sunter import (
        FSParams,
        sufficient_stats,
        match_probability,
        update_params,
    )

    rng = np.random.default_rng(0)
    N, C = 10_000, 2
    G = rng.integers(-1, 3, size=(N, C)).astype(np.int8)
    init = FSParams(
        lam=jnp.asarray(0.4),
        m=jnp.asarray(np.tile([0.1, 0.2, 0.7], (C, 1))),
        u=jnp.asarray(np.tile([0.7, 0.2, 0.1], (C, 1))),
    )

    def one_pass(params, Gs):
        p = match_probability(jnp.asarray(Gs), params)
        return sufficient_stats(jnp.asarray(Gs), p, 3)

    # single host
    single = update_params(one_pass(init, G))

    # two simulated hosts: disjoint slices, stats added (the psum)
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    parts = []
    for pid in range(2):
        monkeypatch.setattr(jax, "process_index", lambda pid=pid: pid)
        sl = dist.global_pair_slice(N)
        parts.append(one_pass(init, G[sl]))
    combined = update_params(parts[0] + parts[1])

    np.testing.assert_allclose(np.asarray(combined.m), np.asarray(single.m), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(combined.u), np.asarray(single.u), rtol=1e-12)
    np.testing.assert_allclose(
        float(combined.lam), float(single.lam), rtol=1e-12
    )
