"""Linker-level out-of-core write path (ISSUE 15 tentpole wiring):
build_spill_dir routes blocking through the durable spill store, EM
consumes the manifest without materialising G, and the out-of-core index
build produces a CONTENT-FINGERPRINT-identical artifact to the resident
build."""

import json
import os
import warnings

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

import splink_tpu
from splink_tpu import Splink
from splink_tpu.ops.gamma import apply_null
from splink_tpu.serve.index import load_index
from splink_tpu.utils.logging_utils import DegradationWarning


def _custom_exact_first(ctx, col_settings):
    pc = ctx.col("first_name")
    return apply_null((pc.tok_l == pc.tok_r).astype(jnp.int8), pc.null)


splink_tpu.register_comparison("scale_exact_first", _custom_exact_first)


def _df(n=400, seed=0):
    rng = np.random.default_rng(seed)
    firsts = np.array(["amelia", "oliver", "isla", "george", "ava", "noah"])
    lasts = np.array(["smith", "jones", "taylor", "brown"])
    return pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "first_name": firsts[rng.integers(0, 6, n)],
            "surname": lasts[rng.integers(0, 4, n)],
            "city": [f"c{i % 3}" for i in range(n)],
        }
    )


def _settings(**overrides):
    s = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.city = r.city"],
        "comparison_columns": [
            {
                "col_name": "first_name",
                "num_levels": 2,
                "comparison": {"kind": "exact"},
            },
            {
                "col_name": "surname",
                "num_levels": 2,
                "comparison": {"kind": "exact"},
            },
        ],
        "max_iterations": 5,
        "em_convergence": 1e-12,
    }
    s.update(overrides)
    return s


def _settings_streamed(**overrides):
    """A custom comparison kernel disqualifies the pattern pipeline and a
    low residency cap disqualifies resident EM — the job lands on the
    streamed-stats driver, which is where the spill manifest feed plugs
    in."""
    return _settings(
        comparison_columns=[
            {
                "col_name": "first_name",
                "num_levels": 2,
                "comparison": {"kind": "custom", "fn": "scale_exact_first"},
            },
            {
                "col_name": "surname",
                "num_levels": 2,
                "comparison": {"kind": "exact"},
            },
        ],
        max_resident_pairs=2048,
        pair_batch_size=4096,
        **overrides,
    )


def test_spill_em_bit_identical_to_materialised(tmp_path):
    """The manifest-fed streamed EM (gammas per chunk, G never resident)
    produces EXACTLY the trajectory of the materialised streamed driver —
    batch boundaries match by construction, so anything but bit-identity
    is a feed bug."""
    df = _df()
    base = Splink(_settings_streamed(), df=df)
    base.estimate_parameters()

    spill = Splink(
        _settings_streamed(
            build_spill_dir=str(tmp_path / "b"),
            emit_shard_chunks=3,
            blocking_chunk_pairs=4096,
        ),
        df=df,
    )
    spill.estimate_parameters()
    assert getattr(spill._pairs, "spill_store", None) is not None
    assert spill._G is None, "spill EM must not materialise the gamma matrix"
    sa = json.dumps(
        {"c": base.params.params, "h": base.params.param_history},
        sort_keys=True,
    )
    sb = json.dumps(
        {"c": spill.params.params, "h": spill.params.param_history},
        sort_keys=True,
    )
    assert sa == sb


def test_ooc_index_fingerprint_identical_and_roundtrips(tmp_path):
    """ACCEPTANCE: the out-of-core index build's artifact is
    content-fingerprint-identical to the resident build's, the packed
    matrix rides as a disk-backed memmap, and the streaming save
    round-trips through load_index with the fingerprint intact."""
    df = _df(n=600, seed=3)
    resident = Splink(_settings(), df=df)
    resident.estimate_parameters()
    ix_res = resident.export_index()
    fp = ix_res.content_fingerprint()

    ooc = Splink(
        _settings(
            build_spill_dir=str(tmp_path / "b"),
            build_spill_chunk_rows=1024,  # < n_rows? no — schema floor;
        ),
        df=df,
    )
    ooc.estimate_parameters()
    ix_ooc = ooc.export_index()
    assert isinstance(ix_ooc.packed, np.memmap)
    assert ix_ooc.content_fingerprint() == fp
    assert np.array_equal(np.asarray(ix_ooc.packed), np.asarray(ix_res.packed))

    out = str(tmp_path / "artifact")
    ix_ooc.save(out)
    back = load_index(out)
    assert back.content_fingerprint() == fp


def test_spill_blocking_pairs_match_ordinary_path(tmp_path):
    """The store-backed pair set equals the ordinary blocking path's as a
    set (emission order differs: (rule, shard, seq) vs rule-unit order)."""
    df = _df(n=300, seed=5)
    a = Splink(_settings(), df=df)
    pa_ = a._ensure_pairs()
    b = Splink(
        _settings(build_spill_dir=str(tmp_path / "b"), emit_shard_chunks=2),
        df=df,
    )
    pb = b._ensure_pairs()
    assert pb.spill_store is not None
    assert set(zip(pa_.idx_l.tolist(), pa_.idx_r.tolist())) == set(
        zip(pb.idx_l.tolist(), pb.idx_r.tolist())
    )


def test_build_spill_dir_unsupported_rules_degrade(tmp_path):
    """Rule shapes the device emission plan rejects (cartesian residual)
    degrade to the ordinary path with a structured warning — never a lost
    run."""
    df = _df(n=60, seed=7).assign(amount=np.arange(60.0))
    s = _settings(
        blocking_rules=["l.amount < r.amount"],
        build_spill_dir=str(tmp_path / "b"),
    )
    s["comparison_columns"] = s["comparison_columns"][:1]
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        linker = Splink(s, df=df)
        pairs = linker._ensure_pairs()
    assert pairs.n_pairs > 0
    assert getattr(pairs, "spill_store", None) is None
    assert any(issubclass(x.category, DegradationWarning) for x in w)


def test_spill_em_checkpoint_resume_composes(tmp_path):
    """The spill-fed EM rides the SAME checkpoint plumbing as the
    materialised streamed driver: train 2 iterations, then resume from
    the checkpoint over the SAME store and land bit-identical to an
    uninterrupted run."""
    df = _df()
    ck = str(tmp_path / "ck")
    full = Splink(
        _settings_streamed(
            build_spill_dir=str(tmp_path / "b1"), max_iterations=5
        ),
        df=df,
    )
    full.estimate_parameters()

    part = Splink(
        _settings_streamed(
            build_spill_dir=str(tmp_path / "b2"), max_iterations=2
        ),
        df=df,
    )
    part.estimate_parameters(checkpoint_dir=ck)
    resumed = Splink(
        _settings_streamed(
            build_spill_dir=str(tmp_path / "b2"), max_iterations=5
        ),
        df=df,
    )
    resumed.estimate_parameters(checkpoint_dir=ck, resume=True)
    sa = json.dumps(
        {"c": full.params.params, "h": full.params.param_history},
        sort_keys=True,
    )
    sb = json.dumps(
        {"c": resumed.params.params, "h": resumed.params.param_history},
        sort_keys=True,
    )
    assert sa == sb
