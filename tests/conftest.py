"""Test configuration: run everything on CPU with 8 virtual devices.

This is the JAX analogue of the reference's "multi-node without a cluster"
strategy (sqlite unit tier + local Spark, /root/reference/tests/conftest.py):
kernels and EM are validated on CPU against independent numpy oracles, and
multi-chip sharding is exercised on a virtual 8-device mesh.

Must run before jax is imported anywhere in the test process.
"""

import os
import tempfile

# Force CPU: the environment pre-sets JAX_PLATFORMS=axon (real TPU) and
# pre-imports jax at interpreter startup, so the env var alone is ignored —
# jax.config.update is the reliable override. The test tier runs on 8 virtual
# CPU devices; x64 (needed for oracle-exact comparisons) is also unavailable
# on TPU.
os.environ["JAX_PLATFORMS"] = "cpu"
# Hermetic persistent compilation cache: the linker now enables the cache on
# EVERY backend (CPU entries keyed by target fingerprint), and the env var
# takes precedence over any settings value — pinning it to a per-session
# temp dir keeps test runs from reading ~/.cache state left by earlier runs
# (compile-count assertions account for in-session cache hits via
# obs.metrics.compile_stats). Tests that exercise the settings-driven path
# monkeypatch-delete the var.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    import atexit
    import shutil

    _xla_cache_dir = tempfile.mkdtemp(prefix="splink_tpu_test_xla_cache_")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = _xla_cache_dir
    atexit.register(shutil.rmtree, _xla_cache_dir, True)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def basic_settings():
    """A small two-column dedupe settings dict used across tests."""
    return {
        "link_type": "dedupe_only",
        "proportion_of_matches": 0.3,
        "comparison_columns": [
            {"col_name": "first_name", "num_levels": 2, "comparison": {"kind": "exact"}},
            {"col_name": "surname", "num_levels": 2, "comparison": {"kind": "exact"}},
        ],
        "blocking_rules": [],
    }


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# ----------------------------------------------------------------------
# Independent Python oracles (deliberately separate implementations from the
# JAX kernels they validate).
# ----------------------------------------------------------------------


def py_jaro_winkler(s1, s2, p=0.1, boost_threshold=0.7):
    """Jar-exact commons-text JaroWinklerDistance (verified against the
    reference jar's bytecode — scripts/jvm_mini.py, golden table
    tests/data/jar_similarity_vectors.json): the greedy match iterates the
    SHORTER string over the longer, transpositions are integer-halved, the
    Winkler prefix is uncapped with a min(p, 1/maxlen) scaling factor, the
    boost applies only at jaro >= threshold, and m == 0 (including both
    strings empty) gives 0.0."""
    if len(s1) > len(s2):
        s1, s2 = s2, s1  # jaro term m/l1 + m/l2 is symmetric
    l1, l2 = len(s1), len(s2)
    if l1 == 0:
        return 0.0
    window = max(l2 // 2 - 1, 0)
    used2 = [False] * l2
    matched1 = []
    for i, c in enumerate(s1):
        for j in range(max(0, i - window), min(l2, i + window + 1)):
            if not used2[j] and s2[j] == c:
                used2[j] = True
                matched1.append(i)
                break
    m = len(matched1)
    if m == 0:
        return 0.0
    seq1 = [s1[i] for i in matched1]
    seq2 = [s2[j] for j in range(l2) if used2[j]]
    t = sum(a != b for a, b in zip(seq1, seq2)) // 2  # Java integer halving
    jaro = (m / l1 + m / l2 + (m - t) / m) / 3
    ell = 0
    for a, b in zip(s1, s2):
        if a == b:
            ell += 1
        else:
            break
    if jaro < boost_threshold:
        return jaro
    return jaro + ell * min(p, 1.0 / l2) * (1 - jaro)


def py_levenshtein(s1, s2):
    d = list(range(len(s2) + 1))
    for i, c1 in enumerate(s1):
        nd = [i + 1]
        for j, c2 in enumerate(s2):
            nd.append(min(d[j + 1] + 1, nd[j] + 1, d[j] + (c1 != c2)))
        d = nd
    return d[-1]
