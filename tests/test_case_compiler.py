"""General CASE-expression compiler: hand-written SQL case_expressions
(beyond the generated shapes compat_sql fast-paths) must execute faithfully,
with SQL three-valued null semantics, inside the gamma program.

Reference behaviour being reproduced: arbitrary user case_expression accepted
at /root/reference/splink/settings.py:133-139 and executed row-wise by the
engine.
"""

import numpy as np
import pandas as pd
import pytest

from splink_tpu.case_compiler import (
    analyse_case_expression,
    compile_case_expression,
    parse_sql_expression,
)
from splink_tpu.compat_sql import SqlTranslationError
from splink_tpu.data import encode_table
from splink_tpu.gammas import GammaProgram
from splink_tpu.settings import complete_settings_dict


def _program(cols, df, extra=None):
    s = {
        "link_type": "dedupe_only",
        "comparison_columns": cols,
        "blocking_rules": ["l.unique_id = r.unique_id"],
    }
    s.update(extra or {})
    s = complete_settings_dict(s)
    table = encode_table(df, s)
    return GammaProgram(s, table), s


def _pairs_vs_first(df):
    n = len(df)
    return np.zeros(n - 1, np.int64), np.arange(1, n, dtype=np.int64)


# --------------------------------------------------------------------------
# parsing / analysis
# --------------------------------------------------------------------------


def test_parse_rejects_garbage_with_pointer():
    with pytest.raises(SqlTranslationError):
        parse_sql_expression("case when ;; then 1 end")


def test_analyse_infers_types_and_levels():
    info = analyse_case_expression(
        "case when abs(age_l - age_r) < 2 then 2 "
        "when name_l = name_r then 1 else 0 end"
    )
    assert info["columns"] == {"age": "numeric", "name": "string"}
    assert info["levels"] == {0, 1, 2}


def test_analyse_collects_phonetic_columns():
    info = analyse_case_expression(
        "case when dmetaphone(name_l) = dmetaphone(name_r) "
        "and length(name_l) > 3 then 1 else 0 end"
    )
    assert info["phonetic"] == {"name"}


def test_compile_rejects_out_of_range_levels():
    with pytest.raises(SqlTranslationError, match="outside"):
        compile_case_expression(
            "case when name_l = name_r then 5 else 0 end", num_levels=3
        )


def test_compile_rejects_unknown_function():
    with pytest.raises(SqlTranslationError, match="Unsupported function"):
        compile_case_expression(
            "case when soundex(name_l) = soundex(name_r) then 1 else 0 end", 2
        )


# --------------------------------------------------------------------------
# execution in the gamma program
# --------------------------------------------------------------------------


def test_hand_written_mixed_condition_case():
    df = pd.DataFrame(
        {
            "unique_id": range(6),
            "name": ["martha", "martha", "marhta", "marx", "zz", None],
        }
    )
    expr = """case
        when name_l is null or name_r is null then -1
        when name_l = name_r and length(name_l) > 4 then 2
        when jaro_winkler_sim(name_l, name_r) > 0.9
             or levenshtein(name_l, name_r) <= 2 then 1
        else 0 end"""
    prog, s = _program(
        [{"col_name": "name", "num_levels": 3, "case_expression": expr}], df
    )
    assert s["comparison_columns"][0]["comparison"]["kind"] == "case_sql"
    G = prog.compute(*_pairs_vs_first(df))
    # martha=martha len 6 -> 2; marhta jw .961 -> 1; marx lev 3, jw ~.88 -> 0
    # (jw(martha, marx) < .9, lev = 3); zz -> 0; null -> -1
    assert G[:, 0].tolist() == [2, 1, 0, 0, -1]


def test_numeric_arithmetic_and_null_falls_to_else():
    # No explicit null branch: SQL 3VL makes every comparison with null
    # unknown, so null rows take the ELSE value (0), NOT -1.
    df = pd.DataFrame(
        {
            "unique_id": range(5),
            "age": [40.0, 41.0, 43.0, 80.0, None],
        }
    )
    expr = """case
        when abs(age_l - age_r) / greatest(age_l, age_r) < 0.05 then 2
        when abs(age_l - age_r) < 5 then 1
        else 0 end"""
    prog, _ = _program(
        [{"col_name": "age", "num_levels": 3, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    # 41: rel .024 -> 2; 43: rel .07, abs 3 -> 1; 80 -> 0; null -> else 0
    assert G[:, 0].tolist() == [2, 1, 0, 0]


def test_cross_column_string_equality_uses_chars_not_tokens():
    # first/surname have independent token vocabularies; equality across
    # them must compare characters.
    df = pd.DataFrame(
        {
            "unique_id": range(4),
            "first": ["james", "smith", "james", "ann"],
            "sur": ["smith", "james", "poe", "lee"],
        }
    )
    expr = """case
        when first_l = sur_r or sur_l = first_r then 1
        else 0 end"""
    prog, _ = _program(
        [
            {
                "custom_name": "swapped",
                "custom_columns_used": ["first", "sur"],
                "num_levels": 2,
                "case_expression": expr,
            }
        ],
        df,
    )
    G = prog.compute(*_pairs_vs_first(df))
    # row0 (james, smith) vs row1 (smith, james): first_l=sur_r -> 1
    # vs row2 (james, poe): no; vs row3 (ann, lee): no
    assert G[:, 0].tolist() == [1, 0, 0]


def test_string_literal_and_lower():
    df = pd.DataFrame(
        {
            "unique_id": range(4),
            "city": ["London", "LONDON", "paris", None],
        }
    )
    expr = """case
        when lower(city_l) = 'london' and lower(city_r) = 'london' then 2
        when lower(city_l) = lower(city_r) then 1
        else 0 end"""
    prog, _ = _program(
        [{"col_name": "city", "num_levels": 3, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    # London/LONDON both lower to 'london' -> 2; paris -> 0; null -> else 0
    assert G[:, 0].tolist() == [2, 0, 0]


def test_ifnull_treats_null_as_empty():
    df = pd.DataFrame(
        {
            "unique_id": range(4),
            "name": [None, None, "bob", ""],
        }
    )
    expr = "case when ifnull(name_l, '') = ifnull(name_r, '') then 1 else 0 end"
    prog, _ = _program(
        [{"col_name": "name", "num_levels": 2, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    # null vs null -> '' = '' -> 1; null vs bob -> 0; null vs '' -> 1
    assert G[:, 0].tolist() == [1, 0, 1]


def test_missing_else_yields_null_gamma():
    df = pd.DataFrame(
        {"unique_id": range(3), "name": ["ann", "ann", "bob"]}
    )
    expr = "case when name_l = name_r then 1 end"
    prog, _ = _program(
        [{"col_name": "name", "num_levels": 2, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    # matched -> 1; unmatched, no ELSE -> SQL NULL -> -1
    assert G[:, 0].tolist() == [1, -1]


def test_dmetaphone_with_extra_condition():
    # The plain dmetaphone shapes fast-path to the native kernel; an extra
    # AND-condition forces the general compiler.
    df = pd.DataFrame(
        {
            "unique_id": range(4),
            "name": ["smith", "smyth", "sm", None],
        }
    )
    expr = """case
        when name_l is null or name_r is null then -1
        when name_l = name_r then 2
        when dmetaphone(name_l) = dmetaphone(name_r)
             and length(name_r) > 3 then 1
        else 0 end"""
    prog, s = _program(
        [{"col_name": "name", "num_levels": 3, "case_expression": expr}], df
    )
    assert s["comparison_columns"][0]["comparison"]["kind"] == "case_sql"
    G = prog.compute(*_pairs_vs_first(df))
    # smyth: same metaphone as smith, len 5 -> 1; sm: len 2 fails -> 0
    assert G[:, 0].tolist() == [1, 0, -1]


def test_nested_case_value():
    df = pd.DataFrame(
        {
            "unique_id": range(4),
            "name": ["anna", "anna", "annb", "xx"],
        }
    )
    expr = """case
        when name_l = name_r then 2
        else case when levenshtein(name_l, name_r) <= 1 then 1 else 0 end
        end"""
    prog, _ = _program(
        [{"col_name": "name", "num_levels": 3, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    assert G[:, 0].tolist() == [2, 1, 0]


def test_end_to_end_linker_with_hand_written_case():
    from splink_tpu import Splink

    rng = np.random.default_rng(5)
    firsts = np.array(["amelia", "oliver", "isla", "george", "ava", "noah"])
    n = 160
    df = pd.DataFrame(
        {
            "unique_id": np.arange(n),
            "first_name": firsts[rng.integers(0, 6, n)],
            "dob": [f"19{40 + i % 50}" for i in range(n)],
        }
    )
    settings = {
        "link_type": "dedupe_only",
        "blocking_rules": ["l.dob = r.dob"],
        "comparison_columns": [
            {
                "col_name": "first_name",
                "num_levels": 3,
                "case_expression": """case
                    when first_name_l is null or first_name_r is null then -1
                    when first_name_l = first_name_r then 2
                    when jaro_winkler_sim(first_name_l, first_name_r) > 0.7
                      then 1
                    else 0 end""",
            }
        ],
        "max_iterations": 5,
    }
    linker = Splink(settings, df=df)
    out = linker.get_scored_comparisons()
    assert "match_probability" in out.columns
    assert len(out) > 0
    exact = out[out.first_name_l == out.first_name_r]
    other = out[out.first_name_l != out.first_name_r]
    assert exact.match_probability.mean() > other.match_probability.mean()


def test_unparseable_case_reports_both_errors():
    df = pd.DataFrame({"unique_id": range(2), "name": ["a", "b"]})
    with pytest.raises(SqlTranslationError, match="General CASE compiler"):
        _program(
            [
                {
                    "col_name": "name",
                    "num_levels": 2,
                    "case_expression": "case when regexp_like(name_l, 'x') "
                    "then 1 else 0 end",
                }
            ],
            df,
        )


def test_quoted_literal_whitespace_preserved():
    df = pd.DataFrame(
        {"unique_id": range(3), "city": ["new  york", "new  york", "new york"]}
    )
    expr = "case when city_l = 'new  york' and city_r = 'new  york' then 1 else 0 end"
    prog, _ = _program(
        [{"col_name": "city", "num_levels": 2, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    # double-space literal must stay double-space: row1 matches, row2 doesn't
    assert G[:, 0].tolist() == [1, 0]


def test_then_null_and_else_null():
    df = pd.DataFrame(
        {"unique_id": range(4), "name": [None, "ann", "ann", "bob"]}
    )
    expr = """case
        when name_l is null or name_r is null then null
        when name_l = name_r then 1
        else null end"""
    prog, _ = _program(
        [{"col_name": "name", "num_levels": 2, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    # null side -> NULL -> -1 everywhere except... left side is always row0
    # (None), so every pair hits the null branch
    assert G[:, 0].tolist() == [-1, -1, -1]
    # now pair within non-null rows
    G2 = prog.compute(np.array([1, 1]), np.array([2, 3]))
    # ann=ann -> 1; ann vs bob -> ELSE NULL -> -1
    assert G2[:, 0].tolist() == [1, -1]


def test_ordering_comparison_infers_numeric_columns():
    info = analyse_case_expression(
        "case when height_l < width_r * 2 then 1 else 0 end"
    )
    assert info["columns"] == {"height": "numeric", "width": "numeric"}
    df = pd.DataFrame(
        {"unique_id": range(3), "size": [10.0, 5.0, 30.0]}
    )
    prog, _ = _program(
        [
            {
                "col_name": "size",
                "num_levels": 2,
                "case_expression": "case when size_l <= size_r then 1 else 0 end",
            }
        ],
        df,
    )
    G = prog.compute(*_pairs_vs_first(df))
    assert G[:, 0].tolist() == [0, 1]


def test_division_by_zero_is_sql_null():
    df = pd.DataFrame(
        {"unique_id": range(3), "amount": [0.0, 0.0, 10.0]}
    )
    # This IS the generated relative-difference shape (incl. the null
    # branch), so it fast-paths to the numeric_perc kernel — whose
    # zero-denominator semantics must match SQL's x/0 -> NULL -> skipped.
    expr = """case
        when amount_l is null or amount_r is null then -1
        when abs(amount_l - amount_r) / greatest(amount_l, amount_r) < 0.05
          then 1
        else 0 end"""
    prog, s = _program(
        [{"col_name": "amount", "num_levels": 2, "case_expression": expr}], df
    )
    assert s["comparison_columns"][0]["comparison"]["kind"] == "numeric_perc"
    G = prog.compute(*_pairs_vs_first(df))
    # pair (0,0): denominator 0 -> NULL -> else 0; pair (0,10): 10/10=1 -> 0
    assert G[:, 0].tolist() == [0, 0]

    # General-compiler path (shape the fast path rejects): same NULL rule.
    expr2 = """case
        when abs(amount_l - amount_r) / greatest(amount_l, amount_r) < 0.05
             and amount_l >= 0 then 1
        else 0 end"""
    prog2, s2 = _program(
        [{"col_name": "amount", "num_levels": 2, "case_expression": expr2}], df
    )
    assert s2["comparison_columns"][0]["comparison"]["kind"] == "case_sql"
    G2 = prog2.compute(*_pairs_vs_first(df))
    assert G2[:, 0].tolist() == [0, 0]


def test_greatest_skips_nulls_like_sql():
    df = pd.DataFrame(
        {
            "unique_id": range(3),
            "a": [5.0, None, None],
            "b": [1.0, 7.0, None],
        }
    )
    expr = "case when greatest(a_l, b_l) > 4 and a_r is null then 1 else 0 end"
    prog, _ = _program(
        [
            {
                "custom_name": "g",
                "custom_columns_used": ["a", "b"],
                "num_levels": 2,
                "case_expression": expr,
            }
        ],
        df,
    )
    # pairs (0,1) and (0,2): left row0 greatest(5,1)=5>4, a_r null -> 1
    G = prog.compute(*_pairs_vs_first(df))
    assert G[:, 0].tolist() == [1, 1]
    # left row1: greatest(null, 7)=7>4 (null skipped) -> 1
    G2 = prog.compute(np.array([1]), np.array([2]))
    assert G2[:, 0].tolist() == [1]


def test_extra_conjunct_never_fast_paths():
    """A hand-written CASE with an extra AND conjunct must NOT collapse onto
    a narrower native kernel (which would silently drop the conjunct)."""
    from splink_tpu.compat_sql import parse_case_expression

    for expr in [
        "case when age_l > 18 and abs(age_l - age_r) < 2 then 1 else 0 end",
        "case when name_l = name_r and jaro_winkler_sim(name_l, name_r) > 0.9"
        " then 2 when jaro_winkler_sim(name_l, name_r) > 0.7 then 1 else 0 end",
        "case when dmetaphone(name_l) = dmetaphone(name_r) then 1 "
        "when length(name_l) > 2 then 1 else 0 end",
    ]:
        with pytest.raises(SqlTranslationError):
            parse_case_expression(expr, 2)

    # and the guard-bearing numeric expression executes correctly end-to-end
    df = pd.DataFrame(
        {"unique_id": range(4), "age": [30.0, 31.0, 17.0, 50.0]}
    )
    prog, s = _program(
        [
            {
                "col_name": "age",
                "num_levels": 2,
                "case_expression": "case when age_l > 18 and "
                "abs(age_l - age_r) < 2 then 1 else 0 end",
            }
        ],
        df,
    )
    assert s["comparison_columns"][0]["comparison"]["kind"] == "case_sql"
    G = prog.compute(*_pairs_vs_first(df))
    # 30 vs 31: guard ok, diff 1 -> 1; vs 17: diff 13 -> 0; vs 50 -> 0
    assert G[:, 0].tolist() == [1, 0, 0]


def test_generated_shapes_still_fast_path():
    from splink_tpu.compat_sql import parse_case_expression

    jw3 = """case
    when name_l is null or name_r is null then -1
    when jaro_winkler_sim(name_l, name_r) > 0.94 then 2
    when jaro_winkler_sim(name_l, name_r) > 0.88 then 1
    else 0 end"""
    assert parse_case_expression(jw3, 3)["kind"] == "jaro_winkler"
    exact = """case
    when city_l is null or city_r is null then -1
    when city_l = city_r then 1
    else 0 end"""
    assert parse_case_expression(exact, 2)["kind"] == "exact"
    perc3 = """case
    when age_l is null or age_r is null then -1
    when (abs(age_l - age_r))/abs(
    case when age_l > age_r then age_l else age_r end
    ) < 0.0001 then 2
    when (abs(age_l - age_r))/abs(
    case when age_l > age_r then age_l else age_r end
    ) < 0.05 then 1
    else 0 end"""
    assert parse_case_expression(perc3, 3)["kind"] == "numeric_perc"


def test_equality_with_negative_literal_and_arith_infers_numeric():
    info = analyse_case_expression("case when code_l = -1 then 0 else 1 end")
    assert info["columns"] == {"code": "numeric"}
    info2 = analyse_case_expression(
        "case when total_l = price_r * 2 then 1 else 0 end"
    )
    assert info2["columns"] == {"total": "numeric", "price": "numeric"}
    df = pd.DataFrame({"unique_id": range(3), "code": [-1.0, -1.0, 4.0]})
    prog, _ = _program(
        [
            {
                "col_name": "code",
                "num_levels": 2,
                "case_expression": "case when code_l = -1 and code_r = -1 "
                "then 1 else 0 end",
            }
        ],
        df,
    )
    G = prog.compute(*_pairs_vs_first(df))
    assert G[:, 0].tolist() == [1, 0]


def test_nested_case_in_condition_position_not_level_checked():
    # inner CASE used inside a condition produces 10, which is NOT a gamma
    # outcome and must not be rejected
    fn = compile_case_expression(
        "case when (case when a_l = a_r then 10 else 0 end) = 10 then 1 "
        "else 0 end",
        num_levels=2,
    )
    assert fn is not None
    # but a nested CASE in VALUE position contributes outcomes
    with pytest.raises(SqlTranslationError, match="outside"):
        compile_case_expression(
            "case when a_l = a_r then case when b_l = b_r then 9 else 0 end "
            "else 0 end",
            num_levels=2,
        )


def test_non_integer_then_value_rejected():
    with pytest.raises(SqlTranslationError, match="not an integer"):
        compile_case_expression(
            "case when name_l = name_r then 1.5 else 0 end", num_levels=2
        )


def test_constant_null_arithmetic_and_division():
    # SQL constant folding: NULL + 1 is NULL, 1/0 is NULL — conditions using
    # them are unknown and fall through; no raw TypeError/ZeroDivisionError
    df = pd.DataFrame({"unique_id": range(3), "n": [1.0, 2.0, 3.0]})
    for cond in ["n_l > null + 1", "n_l > 1/0", "n_l > -(null)"]:
        prog, _ = _program(
            [
                {
                    "col_name": "n",
                    "num_levels": 2,
                    "case_expression": f"case when {cond} then 1 else 0 end",
                }
            ],
            df,
        )
        G = prog.compute(*_pairs_vs_first(df))
        assert G[:, 0].tolist() == [0, 0], cond


def test_parser_never_crashes_on_token_soup():
    """Random token soup must produce SqlTranslationError (or parse), never
    IndexError/TypeError/etc — settings errors should always be readable."""
    import random

    rng = random.Random(0)
    toks = ["case", "when", "then", "else", "end", "and", "or", "not", "is",
            "null", "(", ")", ",", "=", "<", ">", "<=", ">=", "<>", "+", "-",
            "*", "/", "'abc'", "'", "1.5", "name_l", "name_r", "abs", "x",
            "_l", "jaro_winkler_sim", "ifnull", ";", "@", "1e999"]
    for _ in range(500):
        s = " ".join(rng.choice(toks) for _ in range(rng.randint(1, 15)))
        try:
            parse_sql_expression(s)
        except SqlTranslationError:
            pass


def test_unicode_literal_and_wide_column():
    """Non-ASCII columns encode as wide (uint32 codepoints); CASE literals
    with non-ASCII characters must compare correctly against them."""
    df = pd.DataFrame(
        {
            "unique_id": range(4),
            "city": ["münchen", "münchen", "munchen", "köln"],
        }
    )
    expr = """case
        when city_l = 'münchen' and city_r = 'münchen' then 2
        when city_l = city_r then 1
        else 0 end"""
    prog, _ = _program(
        [{"col_name": "city", "num_levels": 3, "case_expression": expr}], df
    )
    G = prog.compute(*_pairs_vs_first(df))
    # münchen/münchen -> 2; munchen differs (ü != u) -> 0; köln -> 0
    assert G[:, 0].tolist() == [2, 0, 0]


# --------------------------------------------------------------------------
# substr / concat / trim (reference fixture parity: the reference's own
# conftest CASE uses substr — /root/reference/tests/conftest.py:116)
# --------------------------------------------------------------------------


def _gamma_for(expr, df, num_levels=2, col="name"):
    prog, _ = _program(
        [{"col_name": col, "num_levels": num_levels, "case_expression": expr}],
        df,
    )
    return prog.compute(*_pairs_vs_first(df))[:, 0].tolist()


def test_substr_prefix_equality():
    df = pd.DataFrame(
        {
            "unique_id": range(5),
            "name": ["Linacre", "Linacer", "Lim", "Li", "Smith"],
        }
    )
    got = _gamma_for(
        "case when substr(name_l, 1, 3) = substr(name_r, 1, 3) "
        "then 1 else 0 end",
        df,
    )
    # vs "Linacre": "Lin"=="Lin" -> 1; "Lim" -> 0; "Li" shorter -> 0; Smith 0
    assert got == [1, 0, 0, 0]


def test_substr_midstring_and_to_end():
    df = pd.DataFrame(
        {"unique_id": range(3), "name": ["abcdef", "xbcdef", "abXdef"]}
    )
    # substr(s, 2, 3) -> chars 2..4 (1-based)
    got = _gamma_for(
        "case when substr(name_l, 2, 3) = substr(name_r, 2, 3) "
        "then 1 else 0 end",
        df,
    )
    assert got == [1, 0]
    # 2-arg form runs to the end of the string
    got = _gamma_for(
        "case when substr(name_l, 3) = substr(name_r, 3) then 1 else 0 end",
        df,
    )
    assert got == [1, 0]


def test_substr_shorter_string_compares_by_length():
    # SQL: substr('Li',1,3) = 'Li' which != 'Lin' — length matters, not just
    # the zero-padded prefix bytes
    df = pd.DataFrame({"unique_id": range(2), "name": ["Lin", "Li"]})
    got = _gamma_for(
        "case when substr(name_l, 1, 3) = substr(name_r, 1, 3) "
        "then 1 else 0 end",
        df,
    )
    assert got == [0]


def test_substr_past_width_is_empty_string():
    df = pd.DataFrame({"unique_id": range(3), "name": ["ab", "cd", "ef"]})
    # start beyond every encoded width -> both sides '' -> equal
    got = _gamma_for(
        "case when substr(name_l, 90, 3) = substr(name_r, 90, 3) "
        "then 1 else 0 end",
        df,
    )
    assert got == [1, 1]


def test_substr_on_literal_folds():
    df = pd.DataFrame({"unique_id": range(2), "name": ["abc", "xbc"]})
    # pair is (row0, row1): name_l='abc', name_r='xbc'
    got = _gamma_for(
        "case when substr(name_r, 1, 2) = substr('abZ', 1, 2) "
        "then 1 else 0 end",
        df,
    )
    assert got == [0]  # 'xb' != 'ab'
    got = _gamma_for(
        "case when substr(name_l, 2, 2) = 'bc' then 1 else 0 end", df
    )
    assert got == [1]


def test_substr_dynamic_start_rejected():
    with pytest.raises(SqlTranslationError, match="constant integer"):
        compile_case_expression(
            "case when substr(name_l, length(name_l), 1) = 'x' "
            "then 1 else 0 end",
            2,
        )
    with pytest.raises(SqlTranslationError, match=">= 0"):
        # negative from-the-end starts stay unsupported in CASE (they ARE
        # supported in blocking keys); start 0 now behaves like start 1
        compile_case_expression(
            "case when substr(name_l, -2, 2) = 'bc' then 1 else 0 end", 2
        )


def test_substr_null_propagates():
    df = pd.DataFrame({"unique_id": range(3), "name": ["abc", None, "abd"]})
    got = _gamma_for(
        "case when substr(name_l, 1, 2) = substr(name_r, 1, 2) "
        "then 1 else 0 end",
        df,
    )
    # NULL row: condition unknown -> falls to ELSE 0; gamma stays 0 here
    assert got == [0, 1]


def test_concat_columns_and_literals():
    df = pd.DataFrame(
        {
            "unique_id": range(3),
            "first": ["ann", "ann", "bob"],
            "last": ["lee", "le", "lee"],
        }
    )
    prog, _ = _program(
        [
            {
                "custom_name": "full",
                "custom_columns_used": ["first", "last"],
                "num_levels": 2,
                "case_expression": "case when concat(first_l, '-', last_l) "
                "= concat(first_r, '-', last_r) then 1 else 0 end",
            }
        ],
        df,
    )
    got = prog.compute(*_pairs_vs_first(df))[:, 0].tolist()
    # 'ann-lee' vs 'ann-le' -> 0; 'ann-lee' vs 'bob-lee' -> 0
    assert got == [0, 0]
    # identical concatenations match
    df2 = pd.DataFrame(
        {
            "unique_id": range(2),
            "first": ["ann", "ann"],
            "last": ["lee", "lee"],
        }
    )
    prog2, _ = _program(
        [
            {
                "custom_name": "full",
                "custom_columns_used": ["first", "last"],
                "num_levels": 2,
                "case_expression": "case when concat(first_l, last_l) = "
                "concat(first_r, last_r) then 1 else 0 end",
            }
        ],
        df2,
    )
    assert prog2.compute(*_pairs_vs_first(df2))[:, 0].tolist() == [1]


def test_concat_no_boundary_confusion():
    # concat('ab','c') must NOT equal concat('a','bc')... lengths equal and
    # chars equal -> they DO equal as strings ('abc'='abc'), per SQL
    df = pd.DataFrame(
        {
            "unique_id": range(2),
            "a": ["ab", "a"],
            "b": ["c", "bc"],
        }
    )
    prog, _ = _program(
        [
            {
                "custom_name": "j",
                "custom_columns_used": ["a", "b"],
                "num_levels": 2,
                "case_expression": "case when concat(a_l, b_l) = "
                "concat(a_r, b_r) then 1 else 0 end",
            }
        ],
        df,
    )
    assert prog.compute(*_pairs_vs_first(df))[:, 0].tolist() == [1]


def test_concat_null_argument_yields_null():
    df = pd.DataFrame({"unique_id": range(2), "name": ["ab", "ab"]})
    # concat with a NULL literal is NULL for every row -> comparison unknown
    got = _gamma_for(
        "case when concat(name_l, null) = concat(name_r, null) "
        "then 1 else 0 end",
        df,
    )
    assert got == [0]
    df2 = pd.DataFrame({"unique_id": range(2), "name": ["ab", None]})
    got = _gamma_for(
        "case when concat(name_l, 'x') = concat(name_r, 'x') "
        "then 1 when name_l is not null then 0 else -1 end",
        df2,
        num_levels=2,
    )
    assert got == [0]  # null side -> unknown -> next branch


def test_trim_family():
    df = pd.DataFrame(
        {"unique_id": range(4), "name": ["ab", "  ab ", " ab", "ab  "]}
    )
    assert _gamma_for(
        "case when trim(name_l) = trim(name_r) then 1 else 0 end", df
    ) == [1, 1, 1]
    assert _gamma_for(
        "case when ltrim(name_l) = ltrim(name_r) then 1 else 0 end", df
    ) == [0, 1, 0]  # 'ab' vs 'ab ', 'ab', 'ab  '
    assert _gamma_for(
        "case when rtrim(name_l) = rtrim(name_r) then 1 else 0 end", df
    ) == [0, 0, 1]  # 'ab' vs '  ab', ' ab', 'ab'


def test_trim_all_space_and_literal_folding():
    df = pd.DataFrame({"unique_id": range(2), "name": ["   ", ""]})
    assert _gamma_for(
        "case when trim(name_l) = trim(name_r) then 1 else 0 end", df
    ) == [1]  # both trim to ''
    df2 = pd.DataFrame({"unique_id": range(2), "name": ["ab", "ab"]})
    assert _gamma_for(
        "case when name_l = trim('  ab  ') then 1 else 0 end", df2
    ) == [1]


def test_length_of_null_literal_is_null():
    # SQL: length(NULL) is NULL, not 4 (len('None'))
    df = pd.DataFrame({"unique_id": range(2), "name": ["abcd", "abcd"]})
    got = _gamma_for(
        "case when length(null) = 4 then 1 else 0 end", df
    )
    assert got == [0]  # unknown condition falls through to ELSE
    got = _gamma_for(
        "case when lower(null) is null and upper(null) is null "
        "then 1 else 0 end",
        df,
    )
    assert got == [1]


def test_data_dependent_case_outcome_rejected():
    # 'then col_l' could wrap in the int8 cast and alias pattern ids in the
    # streamed pattern regime — rejected statically now
    with pytest.raises(SqlTranslationError, match="constant integer"):
        compile_case_expression(
            "case when age_l = age_r then age_l else 0 end", num_levels=2
        )


def test_constant_arith_case_outcome_folds_and_checks():
    # 'then 1+1' folds to 2 and is range-checked
    fn = compile_case_expression(
        "case when name_l = name_r then 1 + 1 else 0 end", num_levels=3
    )
    assert fn is not None
    with pytest.raises(SqlTranslationError, match="outside"):
        compile_case_expression(
            "case when name_l = name_r then 1 + 2 else 0 end", num_levels=3
        )


def test_alias_suffix_tolerated():
    # the reference appends "as gamma_<col>" to every user case_expression
    fn = compile_case_expression(
        "case when name_l = name_r then 1 else 0 end as gamma_name",
        num_levels=2,
    )
    assert fn is not None


def test_substr_start_zero_behaves_like_one():
    """Spark: substring(s, 0, n) behaves like start 1 — the CASE compiler
    remaps rather than rejecting (round 4)."""
    df = pd.DataFrame(
        {"unique_id": range(3), "name": ["abcde", "abcxx", "zzzzz"]}
    )
    got0 = _gamma_for(
        "case when substr(name_l, 0, 3) = substr(name_r, 0, 3) "
        "then 1 else 0 end",
        df,
    )
    got1 = _gamma_for(
        "case when substr(name_l, 1, 3) = substr(name_r, 1, 3) "
        "then 1 else 0 end",
        df,
    )
    assert got0 == got1
